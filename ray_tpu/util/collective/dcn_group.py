"""Eager cross-process collectives over TCP rings (the DCN path).

Plays the role of the reference's GLOOGroup
(python/ray/util/collective/collective_group/gloo_collective_group.py, 565
LoC, pygloo with rendezvous through the GCS internal KV — gloo_util.py:271):
pure-python ring algorithms over persistent sockets, used for host-side
tensors and control data. On TPU pods this is the cross-slice/DCN fallback;
the high-bandwidth path is XLA collectives over ICI inside compiled
programs (see parallel/).

Algorithms (selected per op from the alpha-beta cost model in
topology.py, overridable via RT_COLLECTIVE_ALGO; the choice is recorded
in `last_op_info` and flows to the flight-recorder op observers):
  * allreduce[ring]: ring reduce-scatter + ring allgather (bandwidth-
    optimal, 2*(n-1)/n * bytes per link); optionally quantized on the
    wire (quant="int8"/"fp8", see quant.py): codes are decoded and
    reduced in fp32 at every hop (ReduceOp-safe two-pass), with an
    optional error-feedback residual folded into the next call.
  * allreduce[rd]: recursive doubling — ceil(log2 n) rounds moving the
    full message; latency-optimal for small tensors (barrier payloads,
    scalars, control-plane sync). Non-power-of-2 folds the extra ranks
    in and out.
  * allgather / reducescatter: single ring pass
  * broadcast: ring forward from root
  * barrier: zero-byte ring token
  * send/recv: direct socket between ranks

Every payload byte that leaves this rank is counted (`bytes_sent`), and
the send path honors the chaos DCN injections (`chaos.delay_dcn_send`,
`chaos.cap_dcn_bandwidth`) so the algorithm-selection bench is
deterministic on CPU loopback.

Fault model (preemption-aware): every socket carries an op deadline, so a
dead or wedged peer raises a typed CollectiveTimeoutError instead of
hanging the surviving ranks forever, and rendezvous is stamped with a
gang *epoch* — a stale member from a torn-down attempt can neither find
the new ring in the KV nor pass the identification handshake.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Dict, List, Optional

import numpy as np

import logging

from ray_tpu._private import chaos
from ray_tpu.exceptions import CollectiveTimeoutError
from ray_tpu.util import journal
from ray_tpu.util.collective import quant as quant_mod
from ray_tpu.util.collective.topology import (
    ALGO_HIER,
    ALGO_RD,
    ALGO_RING,
    Topology,
)
from ray_tpu.util.collective.types import ReduceOp

logger = logging.getLogger("ray_tpu.collective")

_LEN = struct.Struct("<Q")
# Identification frame on every initiated connection: sender rank + the
# gang epoch it believes it belongs to + the sender's HLC stamp
# (physical µs, logical counter) so the connect itself is causally
# ordered in the cluster journal — a DCN dial happens-after whatever
# the dialer saw last.
_IDENT = struct.Struct("<IIQI")


def _reduce(op: ReduceOp, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    if op == ReduceOp.SUM:
        return a + b
    if op == ReduceOp.PRODUCT:
        return a * b
    if op == ReduceOp.MIN:
        return np.minimum(a, b)
    if op == ReduceOp.MAX:
        return np.maximum(a, b)
    raise ValueError(op)


class _Peer:
    def __init__(self, sock: socket.socket, op_timeout: Optional[float] = None,
                 on_send=None):
        self.sock = sock
        self.lock = threading.Lock()
        # Owning group's byte accountant: called with the framed length
        # of every send (powers collective_bytes_total and the bench's
        # DCN-byte gates).
        self.on_send = on_send
        # One deadline per blocking socket op: a peer that stops draining
        # (or stops sending) trips socket.timeout instead of blocking the
        # rank forever mid-collective.
        if op_timeout and op_timeout > 0:
            sock.settimeout(op_timeout)

    def send_bytes(self, data: bytes):
        with self.lock:
            # Chaos DCN injections: a fixed per-send delay (models link
            # latency — what makes recursive doubling beat the ring) and
            # a bandwidth cap (models a saturated slow tier — what makes
            # quantization pay). Both are no-cost reads when chaos is off.
            delay = chaos.take_dcn_send_delay()
            if delay:
                time.sleep(delay)
            cap = chaos.dcn_bandwidth_cap()
            if cap:
                time.sleep((len(data) + _LEN.size) / cap)
            self.sock.sendall(_LEN.pack(len(data)) + data)
            if self.on_send is not None:
                self.on_send(len(data) + _LEN.size)

    def recv_bytes(self) -> bytes:
        header = self._recv_exact(8)
        (n,) = _LEN.unpack(header)
        return self._recv_exact(n)

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n:
            chunk = self.sock.recv(min(n, 1 << 20))
            if not chunk:
                raise ConnectionError("collective peer closed")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)


def _send_array(peer: _Peer, arr: np.ndarray):
    header = f"{arr.dtype.str}|{','.join(map(str, arr.shape))}".encode()
    peer.send_bytes(header)
    peer.send_bytes(np.ascontiguousarray(arr).tobytes())


def _recv_array(peer: _Peer) -> np.ndarray:
    out = _recv_frame(peer)
    if isinstance(out, quant_mod.QuantPayload):
        return quant_mod.decode(out)
    return out


def _send_quant(peer: _Peer, p: quant_mod.QuantPayload):
    """Quantized frame: 'Q|' header + codes + scales (3 length-prefixed
    messages; the byte accountant sees the true wire cost)."""
    shape_str = ",".join(map(str, p.shape))
    peer.send_bytes(
        f"Q|{p.scheme}|{p.block}|{p.dtype}|{shape_str}".encode()
    )
    peer.send_bytes(p.codes.tobytes())
    peer.send_bytes(p.scales.tobytes())


def _recv_frame(peer: _Peer):
    """Receive one frame: a plain ndarray or a QuantPayload (returned
    undecoded so the allgather phase can forward codes verbatim without
    re-quantizing)."""
    header = peer.recv_bytes().decode()
    if header.startswith("Q|"):
        _, scheme, block, dtype_str, shape_str = header.split("|")
        shape = (tuple(int(s) for s in shape_str.split(","))
                 if shape_str else ())
        codes = np.frombuffer(peer.recv_bytes(), dtype=np.int8).copy()
        scales = np.frombuffer(peer.recv_bytes(), dtype=np.float32).copy()
        return quant_mod.QuantPayload(
            scheme=scheme, codes=codes, scales=scales, shape=shape,
            dtype=dtype_str, block=int(block),
        )
    dtype_str, shape_str = header.split("|")
    shape = tuple(int(s) for s in shape_str.split(",")) if shape_str else ()
    data = peer.recv_bytes()
    return np.frombuffer(data, dtype=np.dtype(dtype_str)).reshape(shape).copy()


class DcnGroup:
    """One rank's membership in a TCP collective ring.

    `epoch` is the gang attempt number: a restarted training gang bumps
    it so rendezvous keys and identification frames from the previous
    (possibly half-dead) attempt can never splice into the new ring.
    `op_timeout` bounds every blocking send/recv inside a collective;
    exceeding it raises CollectiveTimeoutError.
    """

    def __init__(self, kv, world_size: int, rank: int, group_name: str,
                 timeout: Optional[float] = None, epoch: int = 0,
                 op_timeout: Optional[float] = None):
        from ray_tpu._private.config import get_config

        cfg = get_config()
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self.epoch = int(epoch)
        self._kv = kv
        # Flat topology as this ring sees it (each member is one DCN
        # endpoint); drives the per-op ring-vs-recursive-doubling choice.
        self.topo = Topology.detect(world_size, n_local=1)
        # Framed payload bytes this rank has pushed onto DCN (lifetime).
        self.bytes_sent = 0
        # (op, algo, tier, bytes, dtype, quant) of the last completed op
        # — read by the collective-API observer/metrics layer.
        self.last_op_info: dict = {}
        # Error-feedback residuals for quantized allreduce (lazy).
        self._ef: Optional[quant_mod.ErrorFeedback] = None
        self._timeout = (timeout if timeout is not None
                         else cfg.collective_rendezvous_timeout_s)
        self._op_timeout = (op_timeout if op_timeout is not None
                            else cfg.collective_op_timeout_s)
        # Listening socket for incoming peers.
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("127.0.0.1", 0))
        self._server.listen(world_size + 2)
        self.addr = self._server.getsockname()
        # Written by the accept thread, read by collective ops on the
        # main thread — guard with a lock rather than relying on the
        # GIL's per-op dict atomicity.
        self._accepted: Dict[int, _Peer] = {}
        self._accepted_lock = threading.Lock()
        self._outgoing: Dict[int, _Peer] = {}
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()
        self._register()

    # -- rendezvous through the GCS KV ----------------------------------
    def _key(self, rank: int) -> bytes:
        # Epoch-stamped: a stale rank from attempt N-1 looks up keys that
        # the attempt-N gang never wrote, and times out at rendezvous.
        return f"collective:{self.group_name}:e{self.epoch}:{rank}".encode()

    def _register(self):
        self._kv.kv_put(
            self._key(self.rank),
            f"{self.addr[0]}:{self.addr[1]}".encode(),
            ns="collective",
        )

    def _lookup(self, rank: int) -> tuple:
        deadline = time.monotonic() + self._timeout
        while time.monotonic() < deadline:
            raw = self._kv.kv_get(self._key(rank), ns="collective")
            if raw:
                host, port = raw.decode().rsplit(":", 1)
                return host, int(port)
            time.sleep(0.02)
        raise TimeoutError(
            f"rendezvous timeout waiting for rank {rank} of group "
            f"{self.group_name!r} (epoch {self.epoch})"
        )

    def _accept_loop(self):
        while True:
            try:
                sock, _ = self._server.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            peer = _Peer(sock, self._op_timeout, on_send=self._count_sent)
            # First frame identifies the sender: (rank, epoch). A member
            # of a different epoch is a zombie from a torn-down attempt —
            # close the socket so it can never inject into this ring.
            try:
                rank, epoch, pt, lc = _IDENT.unpack(peer.recv_bytes())
                if pt:
                    journal.observe_wire([pt, lc])
            except Exception:  # noqa: BLE001 — malformed/legacy handshake
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            if epoch != self.epoch:
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            with self._accepted_lock:
                self._accepted[rank] = peer

    def _peer_out(self, rank: int) -> _Peer:
        """Connection this rank initiated (used for sends to `rank`)."""
        peer = self._outgoing.get(rank)
        if peer is None:
            host, port = self._lookup(rank)
            sock = socket.create_connection((host, port), timeout=self._timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            peer = _Peer(sock, self._op_timeout, on_send=self._count_sent)
            pt, lc = journal.wire_stamp() or (0, 0)
            peer.send_bytes(_IDENT.pack(self.rank, self.epoch, pt, lc))
            self._outgoing[rank] = peer
        return peer

    def _peer_in(self, rank: int) -> _Peer:
        """Connection initiated by `rank` toward us (used for receives)."""
        deadline = time.monotonic() + self._timeout
        while time.monotonic() < deadline:
            with self._accepted_lock:
                peer = self._accepted.get(rank)
            if peer is not None:
                return peer
            time.sleep(0.002)
        raise CollectiveTimeoutError(
            f"no inbound connection from rank {rank} of group "
            f"{self.group_name!r} (epoch {self.epoch}) after "
            f"{self._timeout:.1f}s",
            group_name=self.group_name, rank=self.rank, peer_rank=rank,
        )

    def _timeout_error(self, op: str, peer_rank: int) -> CollectiveTimeoutError:
        journal.emit("collective.timeout", op=op, group=self.group_name,
                     rank=self.rank, peer_rank=peer_rank,
                     epoch=self.epoch, timeout_s=self._op_timeout)
        journal.trigger_postmortem(
            f"collective_timeout:{op}",
            group=self.group_name, rank=self.rank, peer_rank=peer_rank,
        )
        return CollectiveTimeoutError(
            f"collective {op} in group {self.group_name!r} (rank "
            f"{self.rank}, epoch {self.epoch}) timed out after "
            f"{self._op_timeout:.1f}s waiting on rank {peer_rank} — the "
            f"peer is dead or wedged",
            group_name=self.group_name, rank=self.rank, peer_rank=peer_rank,
        )

    # -- collectives -----------------------------------------------------
    @property
    def _right(self) -> int:
        return (self.rank + 1) % self.world_size

    @property
    def _left(self) -> int:
        return (self.rank - 1) % self.world_size

    def _count_sent(self, nbytes: int) -> None:
        self.bytes_sent += nbytes

    def _record_op(self, op_name: str, algo: str, bytes0: int,
                   dtype, quant: Optional[str] = None) -> None:
        self.last_op_info = {
            "op": op_name,
            "algo": algo,
            "tier": "dcn",
            "bytes": self.bytes_sent - bytes0,
            "dtype": str(dtype),
            "quant": quant,
        }

    def allreduce(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM,
                  quant: Optional[str] = None, error_feedback: bool = False,
                  algo: Optional[str] = None,
                  ef_key: Optional[object] = None) -> np.ndarray:
        """Allreduce with per-op algorithm selection.

        quant: "int8"/"fp8" — block-scale-quantize every wire message
            (ring only; codes are decoded and reduced in fp32 per hop).
        error_feedback: keep this rank's quantization residual and fold
            it into the next allreduce on the same `ef_key` (SUM only).
        algo: force "ring"/"rd"; default consults the topology cost
            model (and the RT_COLLECTIVE_ALGO env override).
        """
        n = self.world_size
        bytes0 = self.bytes_sent
        if algo is None:
            algo = self.topo.select("allreduce", arr.nbytes)
        if algo == ALGO_HIER:
            algo = ALGO_RING  # a flat ring has no local tier to shard on
        if quant is not None:
            quant_mod.validate_scheme(quant)
            algo = ALGO_RING  # quantization targets the bandwidth regime
        if error_feedback and not quant:
            raise ValueError("error_feedback requires quant='int8'/'fp8'")
        if error_feedback and op != ReduceOp.SUM:
            raise ValueError(
                "error_feedback folds an additive residual into the "
                "input — only ReduceOp.SUM is EF-safe"
            )
        if n == 1:
            self._record_op("allreduce", algo, bytes0, arr.dtype, quant)
            return arr.copy()
        if algo == ALGO_RD:
            out = self._allreduce_rd(arr, op)
        else:
            out = self._allreduce_ring(arr, op, quant=quant,
                                       error_feedback=error_feedback,
                                       ef_key=ef_key)
        self._record_op("allreduce", algo, bytes0, arr.dtype, quant)
        return out

    def _allreduce_ring(self, arr: np.ndarray, op: ReduceOp,
                        quant: Optional[str] = None,
                        error_feedback: bool = False,
                        ef_key: Optional[object] = None) -> np.ndarray:
        """Ring reduce-scatter + allgather; with `quant`, every hop's
        message is quantized on the wire but reduced in fp32 (the
        quantize-scatter / reduce-fp32 / quantize-gather two-pass), and
        with `error_feedback` the rounding error this rank injects is
        banked and folded into the next call's input."""
        n = self.world_size
        flat = np.ascontiguousarray(arr).reshape(-1)
        ef = None
        if error_feedback:
            if self._ef is None:
                self._ef = quant_mod.ErrorFeedback()
            ef = self._ef
            if ef_key is None:
                ef_key = ("allreduce", flat.size)
            flat = ef.apply(ef_key, flat)
        elif quant:
            flat = flat.astype(np.float32, copy=False)
        chunks: List[np.ndarray] = [c.copy() for c in np.array_split(flat, n)]
        # Flat offset of each chunk (EF residuals are positional).
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([c.size for c in chunks], out=offsets[1:])
        right, left = self._peer_out(self._right), self._peer_in(self._left)

        def _ship(idx: int):
            """Send chunk `idx`, quantizing (and EF-banking) if asked."""
            if not quant:
                _send_array(right, chunks[idx])
                return
            payload = quant_mod.encode(chunks[idx], quant)
            _send_quant(right, payload)
            if ef is not None:
                ef.add(ef_key, int(offsets[idx]),
                       chunks[idx] - quant_mod.decode(payload).reshape(-1),
                       flat.size)

        try:
            # Phase 1: ring reduce-scatter (reduction always on decoded
            # fp32/native values, never on codes).
            for step in range(n - 1):
                send_idx = (self.rank - step) % n
                recv_idx = (self.rank - step - 1) % n
                _ship(send_idx)
                incoming = _recv_array(left)
                chunks[recv_idx] = _reduce(op, chunks[recv_idx],
                                           incoming.reshape(-1))
            # Phase 2: ring allgather of reduced chunks. Quantized mode
            # encodes each chunk ONCE (by its owner) and forwards the
            # received codes verbatim, so the gather pass adds exactly
            # one rounding per chunk and every rank decodes identical
            # values (bitwise-consistent results across the ring).
            prev_payload = None
            for step in range(n - 1):
                send_idx = (self.rank + 1 - step) % n
                recv_idx = (self.rank - step) % n
                if not quant:
                    _send_array(right, chunks[send_idx])
                    chunks[recv_idx] = _recv_array(left).reshape(-1)
                    continue
                if step == 0:  # own reduced chunk: quantize once
                    payload = quant_mod.encode(chunks[send_idx], quant)
                    if ef is not None:
                        ef.add(
                            ef_key, int(offsets[send_idx]),
                            chunks[send_idx]
                            - quant_mod.decode(payload).reshape(-1),
                            flat.size,
                        )
                    # Every rank must end with the same values: the
                    # owner keeps the decoded codes too.
                    chunks[send_idx] = (
                        quant_mod.decode(payload).reshape(-1))
                else:  # forward the received codes unchanged
                    payload = prev_payload
                _send_quant(right, payload)
                prev_payload = _recv_frame(left)
                chunks[recv_idx] = (
                    quant_mod.decode(prev_payload).reshape(-1))
        except socket.timeout:
            raise self._timeout_error("allreduce", self._left) from None
        return np.concatenate(chunks).reshape(arr.shape).astype(arr.dtype, copy=False)

    def _allreduce_rd(self, arr: np.ndarray, op: ReduceOp) -> np.ndarray:
        """Recursive doubling: ceil(log2 n) pairwise full-message
        exchanges — latency-optimal for small messages. Non-power-of-2
        world sizes fold the surplus ranks into the low ranks first and
        fan the result back out at the end. Pair exchanges are ordered
        by rank (lower sends first) so two peers can never deadlock in
        sendall."""
        n = self.world_size
        r = self.rank
        p = 1 << (n.bit_length() - 1)  # largest power of two <= n
        val = np.ascontiguousarray(arr).reshape(-1).copy()
        shape, dtype = arr.shape, arr.dtype
        extra = n - p
        partner = r  # last peer touched, for the timeout message
        try:
            if r >= p:
                # Surplus rank: contribute to the partner, then wait for
                # the fanned-out result.
                partner = r - p
                _send_array(self._peer_out(partner), val)
                out = _recv_array(self._peer_in(partner)).reshape(-1)
                return out.reshape(shape).astype(dtype, copy=False)
            if r < extra:
                partner = r + p
                incoming = _recv_array(self._peer_in(r + p)).reshape(-1)
                val = _reduce(op, val, incoming)
            mask = 1
            while mask < p:
                partner = r ^ mask
                if r < partner:
                    _send_array(self._peer_out(partner), val)
                    incoming = _recv_array(self._peer_in(partner))
                else:
                    incoming = _recv_array(self._peer_in(partner))
                    _send_array(self._peer_out(partner), val)
                val = _reduce(op, val, incoming.reshape(-1))
                mask <<= 1
            if r < extra:
                _send_array(self._peer_out(r + p), val)
        except socket.timeout:
            raise self._timeout_error("allreduce[rd]", partner) from None
        return val.reshape(shape).astype(dtype, copy=False)

    def allgather(self, arr: np.ndarray) -> List[np.ndarray]:
        n = self.world_size
        bytes0 = self.bytes_sent
        out: List[Optional[np.ndarray]] = [None] * n
        out[self.rank] = np.asarray(arr).copy()
        if n == 1:
            self._record_op("allgather", ALGO_RING, bytes0, np.asarray(arr).dtype)
            return out  # type: ignore[return-value]
        right, left = self._peer_out(self._right), self._peer_in(self._left)
        try:
            for step in range(n - 1):
                send_idx = (self.rank - step) % n
                recv_idx = (self.rank - step - 1) % n
                _send_array(right, out[send_idx])
                out[recv_idx] = _recv_array(left)
        except socket.timeout:
            raise self._timeout_error("allgather", self._left) from None
        self._record_op("allgather", ALGO_RING, bytes0, np.asarray(arr).dtype)
        return out  # type: ignore[return-value]

    def reducescatter(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
        """Each rank gets the reduction of its 1/n slice.

        Ring reduce-scatter with the schedule shifted so that rank r ends
        holding fully-reduced chunk r.
        """
        n = self.world_size
        bytes0 = self.bytes_sent
        flat = np.ascontiguousarray(arr).reshape(-1)
        chunks = [c.copy() for c in np.array_split(flat, n)]
        if n == 1:
            self._record_op("reducescatter", ALGO_RING, bytes0, arr.dtype)
            return chunks[0]
        right, left = self._peer_out(self._right), self._peer_in(self._left)
        try:
            for step in range(n - 1):
                send_idx = (self.rank - step + n - 1) % n
                recv_idx = (self.rank - step + n - 2) % n
                _send_array(right, chunks[send_idx])
                incoming = _recv_array(left)
                chunks[recv_idx] = _reduce(op, chunks[recv_idx], incoming)
        except socket.timeout:
            raise self._timeout_error("reducescatter", self._left) from None
        self._record_op("reducescatter", ALGO_RING, bytes0, arr.dtype)
        return chunks[self.rank]

    def broadcast(self, arr: np.ndarray, root: int = 0) -> np.ndarray:
        bytes0 = self.bytes_sent
        if self.world_size == 1:
            self._record_op("broadcast", ALGO_RING, bytes0,
                            np.asarray(arr).dtype)
            return np.asarray(arr).copy()
        if self.rank == root:
            out = np.asarray(arr).copy()
        try:
            # Forward around the ring, skipping the wrap back to root.
            if self.rank != root:
                out = _recv_array(self._peer_in(self._left))
            if self._right != root:
                _send_array(self._peer_out(self._right), out)
        except socket.timeout:
            raise self._timeout_error("broadcast", self._left) from None
        self._record_op("broadcast", ALGO_RING, bytes0, out.dtype)
        return out

    def reduce(self, arr: np.ndarray, root: int = 0,
               op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
        # Simple: allreduce then root keeps (fine at control-plane sizes).
        bytes0 = self.bytes_sent
        out = self.allreduce(arr, op)
        algo = self.last_op_info.get("algo", ALGO_RING)
        self._record_op("reduce", algo, bytes0, arr.dtype)
        return out if self.rank == root else np.asarray(arr).copy()

    def barrier(self):
        bytes0 = self.bytes_sent
        self.allreduce(np.zeros(1, dtype=np.int32))
        algo = self.last_op_info.get("algo", ALGO_RING)
        self._record_op("barrier", algo, bytes0, np.dtype(np.int32))

    def send(self, arr: np.ndarray, dst_rank: int):
        bytes0 = self.bytes_sent
        try:
            _send_array(self._peer_out(dst_rank), np.asarray(arr))
        except socket.timeout:
            raise self._timeout_error("send", dst_rank) from None
        self._record_op("send", "p2p", bytes0, np.asarray(arr).dtype)

    def recv(self, src_rank: int) -> np.ndarray:
        bytes0 = self.bytes_sent
        try:
            out = _recv_array(self._peer_in(src_rank))
        except socket.timeout:
            raise self._timeout_error("recv", src_rank) from None
        self._record_op("recv", "p2p", bytes0, out.dtype)
        return out

    def destroy(self):
        # Drop the rendezvous entry so a recreated group with the same name
        # never resolves to this (now dead) listener.
        try:
            self._kv.kv_del(self._key(self.rank), ns="collective")
        except Exception:  # noqa: BLE001
            # A stale entry only delays (never corrupts) a future group:
            # rendezvous keys are epoch-stamped, so leaking one is safe —
            # but record it, a flood of these means the GCS is sick.
            logger.warning(
                "failed to delete rendezvous key for rank %d of group "
                "%r (epoch %d)", self.rank, self.group_name, self.epoch,
                exc_info=True,
            )
        try:
            self._server.close()
        except OSError:
            pass
        with self._accepted_lock:
            accepted = list(self._accepted.values())
        for p in accepted + list(self._outgoing.values()):
            try:
                p.sock.close()
            except OSError:
                pass
