"""Eager cross-process collectives over TCP rings (the DCN path).

Plays the role of the reference's GLOOGroup
(python/ray/util/collective/collective_group/gloo_collective_group.py, 565
LoC, pygloo with rendezvous through the GCS internal KV — gloo_util.py:271):
pure-python ring algorithms over persistent sockets, used for host-side
tensors and control data. On TPU pods this is the cross-slice/DCN fallback;
the high-bandwidth path is XLA collectives over ICI inside compiled
programs (see parallel/).

Algorithms:
  * allreduce: ring reduce-scatter + ring allgather (bandwidth-optimal,
    2*(n-1)/n * bytes per link)
  * allgather / reducescatter: single ring pass
  * broadcast: ring forward from root
  * barrier: zero-byte ring token
  * send/recv: direct socket between ranks

Fault model (preemption-aware): every socket carries an op deadline, so a
dead or wedged peer raises a typed CollectiveTimeoutError instead of
hanging the surviving ranks forever, and rendezvous is stamped with a
gang *epoch* — a stale member from a torn-down attempt can neither find
the new ring in the KV nor pass the identification handshake.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Dict, List, Optional

import numpy as np

import logging

from ray_tpu.exceptions import CollectiveTimeoutError
from ray_tpu.util.collective.types import ReduceOp

logger = logging.getLogger("ray_tpu.collective")

_LEN = struct.Struct("<Q")
# Identification frame on every initiated connection: sender rank + the
# gang epoch it believes it belongs to.
_IDENT = struct.Struct("<II")


def _reduce(op: ReduceOp, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    if op == ReduceOp.SUM:
        return a + b
    if op == ReduceOp.PRODUCT:
        return a * b
    if op == ReduceOp.MIN:
        return np.minimum(a, b)
    if op == ReduceOp.MAX:
        return np.maximum(a, b)
    raise ValueError(op)


class _Peer:
    def __init__(self, sock: socket.socket, op_timeout: Optional[float] = None):
        self.sock = sock
        self.lock = threading.Lock()
        # One deadline per blocking socket op: a peer that stops draining
        # (or stops sending) trips socket.timeout instead of blocking the
        # rank forever mid-collective.
        if op_timeout and op_timeout > 0:
            sock.settimeout(op_timeout)

    def send_bytes(self, data: bytes):
        with self.lock:
            self.sock.sendall(_LEN.pack(len(data)) + data)

    def recv_bytes(self) -> bytes:
        header = self._recv_exact(8)
        (n,) = _LEN.unpack(header)
        return self._recv_exact(n)

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n:
            chunk = self.sock.recv(min(n, 1 << 20))
            if not chunk:
                raise ConnectionError("collective peer closed")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)


def _send_array(peer: _Peer, arr: np.ndarray):
    header = f"{arr.dtype.str}|{','.join(map(str, arr.shape))}".encode()
    peer.send_bytes(header)
    peer.send_bytes(np.ascontiguousarray(arr).tobytes())


def _recv_array(peer: _Peer) -> np.ndarray:
    header = peer.recv_bytes().decode()
    dtype_str, shape_str = header.split("|")
    shape = tuple(int(s) for s in shape_str.split(",")) if shape_str else ()
    data = peer.recv_bytes()
    return np.frombuffer(data, dtype=np.dtype(dtype_str)).reshape(shape).copy()


class DcnGroup:
    """One rank's membership in a TCP collective ring.

    `epoch` is the gang attempt number: a restarted training gang bumps
    it so rendezvous keys and identification frames from the previous
    (possibly half-dead) attempt can never splice into the new ring.
    `op_timeout` bounds every blocking send/recv inside a collective;
    exceeding it raises CollectiveTimeoutError.
    """

    def __init__(self, kv, world_size: int, rank: int, group_name: str,
                 timeout: Optional[float] = None, epoch: int = 0,
                 op_timeout: Optional[float] = None):
        from ray_tpu._private.config import get_config

        cfg = get_config()
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self.epoch = int(epoch)
        self._kv = kv
        self._timeout = (timeout if timeout is not None
                         else cfg.collective_rendezvous_timeout_s)
        self._op_timeout = (op_timeout if op_timeout is not None
                            else cfg.collective_op_timeout_s)
        # Listening socket for incoming peers.
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("127.0.0.1", 0))
        self._server.listen(world_size + 2)
        self.addr = self._server.getsockname()
        # Written by the accept thread, read by collective ops on the
        # main thread — guard with a lock rather than relying on the
        # GIL's per-op dict atomicity.
        self._accepted: Dict[int, _Peer] = {}
        self._accepted_lock = threading.Lock()
        self._outgoing: Dict[int, _Peer] = {}
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()
        self._register()

    # -- rendezvous through the GCS KV ----------------------------------
    def _key(self, rank: int) -> bytes:
        # Epoch-stamped: a stale rank from attempt N-1 looks up keys that
        # the attempt-N gang never wrote, and times out at rendezvous.
        return f"collective:{self.group_name}:e{self.epoch}:{rank}".encode()

    def _register(self):
        self._kv.kv_put(
            self._key(self.rank),
            f"{self.addr[0]}:{self.addr[1]}".encode(),
            ns="collective",
        )

    def _lookup(self, rank: int) -> tuple:
        deadline = time.monotonic() + self._timeout
        while time.monotonic() < deadline:
            raw = self._kv.kv_get(self._key(rank), ns="collective")
            if raw:
                host, port = raw.decode().rsplit(":", 1)
                return host, int(port)
            time.sleep(0.02)
        raise TimeoutError(
            f"rendezvous timeout waiting for rank {rank} of group "
            f"{self.group_name!r} (epoch {self.epoch})"
        )

    def _accept_loop(self):
        while True:
            try:
                sock, _ = self._server.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            peer = _Peer(sock, self._op_timeout)
            # First frame identifies the sender: (rank, epoch). A member
            # of a different epoch is a zombie from a torn-down attempt —
            # close the socket so it can never inject into this ring.
            try:
                rank, epoch = _IDENT.unpack(peer.recv_bytes())
            except Exception:  # noqa: BLE001 — malformed/legacy handshake
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            if epoch != self.epoch:
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            with self._accepted_lock:
                self._accepted[rank] = peer

    def _peer_out(self, rank: int) -> _Peer:
        """Connection this rank initiated (used for sends to `rank`)."""
        peer = self._outgoing.get(rank)
        if peer is None:
            host, port = self._lookup(rank)
            sock = socket.create_connection((host, port), timeout=self._timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            peer = _Peer(sock, self._op_timeout)
            peer.send_bytes(_IDENT.pack(self.rank, self.epoch))
            self._outgoing[rank] = peer
        return peer

    def _peer_in(self, rank: int) -> _Peer:
        """Connection initiated by `rank` toward us (used for receives)."""
        deadline = time.monotonic() + self._timeout
        while time.monotonic() < deadline:
            with self._accepted_lock:
                peer = self._accepted.get(rank)
            if peer is not None:
                return peer
            time.sleep(0.002)
        raise CollectiveTimeoutError(
            f"no inbound connection from rank {rank} of group "
            f"{self.group_name!r} (epoch {self.epoch}) after "
            f"{self._timeout:.1f}s",
            group_name=self.group_name, rank=self.rank, peer_rank=rank,
        )

    def _timeout_error(self, op: str, peer_rank: int) -> CollectiveTimeoutError:
        return CollectiveTimeoutError(
            f"collective {op} in group {self.group_name!r} (rank "
            f"{self.rank}, epoch {self.epoch}) timed out after "
            f"{self._op_timeout:.1f}s waiting on rank {peer_rank} — the "
            f"peer is dead or wedged",
            group_name=self.group_name, rank=self.rank, peer_rank=peer_rank,
        )

    # -- collectives -----------------------------------------------------
    @property
    def _right(self) -> int:
        return (self.rank + 1) % self.world_size

    @property
    def _left(self) -> int:
        return (self.rank - 1) % self.world_size

    def allreduce(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
        n = self.world_size
        if n == 1:
            return arr.copy()
        flat = np.ascontiguousarray(arr).reshape(-1)
        chunks: List[np.ndarray] = [c.copy() for c in np.array_split(flat, n)]
        right, left = self._peer_out(self._right), self._peer_in(self._left)
        try:
            # Phase 1: ring reduce-scatter.
            for step in range(n - 1):
                send_idx = (self.rank - step) % n
                recv_idx = (self.rank - step - 1) % n
                _send_array(right, chunks[send_idx])
                incoming = _recv_array(left)
                chunks[recv_idx] = _reduce(op, chunks[recv_idx], incoming)
            # Phase 2: ring allgather of reduced chunks.
            for step in range(n - 1):
                send_idx = (self.rank + 1 - step) % n
                recv_idx = (self.rank - step) % n
                _send_array(right, chunks[send_idx])
                chunks[recv_idx] = _recv_array(left)
        except socket.timeout:
            raise self._timeout_error("allreduce", self._left) from None
        return np.concatenate(chunks).reshape(arr.shape).astype(arr.dtype, copy=False)

    def allgather(self, arr: np.ndarray) -> List[np.ndarray]:
        n = self.world_size
        out: List[Optional[np.ndarray]] = [None] * n
        out[self.rank] = np.asarray(arr).copy()
        if n == 1:
            return out  # type: ignore[return-value]
        right, left = self._peer_out(self._right), self._peer_in(self._left)
        try:
            for step in range(n - 1):
                send_idx = (self.rank - step) % n
                recv_idx = (self.rank - step - 1) % n
                _send_array(right, out[send_idx])
                out[recv_idx] = _recv_array(left)
        except socket.timeout:
            raise self._timeout_error("allgather", self._left) from None
        return out  # type: ignore[return-value]

    def reducescatter(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
        """Each rank gets the reduction of its 1/n slice.

        Ring reduce-scatter with the schedule shifted so that rank r ends
        holding fully-reduced chunk r.
        """
        n = self.world_size
        flat = np.ascontiguousarray(arr).reshape(-1)
        chunks = [c.copy() for c in np.array_split(flat, n)]
        if n == 1:
            return chunks[0]
        right, left = self._peer_out(self._right), self._peer_in(self._left)
        try:
            for step in range(n - 1):
                send_idx = (self.rank - step + n - 1) % n
                recv_idx = (self.rank - step + n - 2) % n
                _send_array(right, chunks[send_idx])
                incoming = _recv_array(left)
                chunks[recv_idx] = _reduce(op, chunks[recv_idx], incoming)
        except socket.timeout:
            raise self._timeout_error("reducescatter", self._left) from None
        return chunks[self.rank]

    def broadcast(self, arr: np.ndarray, root: int = 0) -> np.ndarray:
        if self.world_size == 1:
            return np.asarray(arr).copy()
        if self.rank == root:
            out = np.asarray(arr).copy()
        try:
            # Forward around the ring, skipping the wrap back to root.
            if self.rank != root:
                out = _recv_array(self._peer_in(self._left))
            if self._right != root:
                _send_array(self._peer_out(self._right), out)
        except socket.timeout:
            raise self._timeout_error("broadcast", self._left) from None
        return out

    def reduce(self, arr: np.ndarray, root: int = 0,
               op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
        # Simple: allreduce then root keeps (fine at control-plane sizes).
        out = self.allreduce(arr, op)
        return out if self.rank == root else np.asarray(arr).copy()

    def barrier(self):
        self.allreduce(np.zeros(1, dtype=np.int32))

    def send(self, arr: np.ndarray, dst_rank: int):
        try:
            _send_array(self._peer_out(dst_rank), np.asarray(arr))
        except socket.timeout:
            raise self._timeout_error("send", dst_rank) from None

    def recv(self, src_rank: int) -> np.ndarray:
        try:
            return _recv_array(self._peer_in(src_rank))
        except socket.timeout:
            raise self._timeout_error("recv", src_rank) from None

    def destroy(self):
        # Drop the rendezvous entry so a recreated group with the same name
        # never resolves to this (now dead) listener.
        try:
            self._kv.kv_del(self._key(self.rank), ns="collective")
        except Exception:  # noqa: BLE001
            # A stale entry only delays (never corrupts) a future group:
            # rendezvous keys are epoch-stamped, so leaking one is safe —
            # but record it, a flood of these means the GCS is sick.
            logger.warning(
                "failed to delete rendezvous key for rank %d of group "
                "%r (epoch %d)", self.rank, self.group_name, self.epoch,
                exc_info=True,
            )
        try:
            self._server.close()
        except OSError:
            pass
        with self._accepted_lock:
            accepted = list(self._accepted.values())
        for p in accepted + list(self._outgoing.values()):
            try:
                p.sock.close()
            except OSError:
                pass
