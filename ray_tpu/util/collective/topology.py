"""Topology model + alpha-beta cost model for collective algorithm
selection.

The gang's interconnect has two bandwidth tiers (SURVEY.md, PAPERS.md
"The Big Send-off"): ICI between the chips one process owns (fast,
reached through XLA programs) and DCN between processes/slices (orders
of magnitude slower, reached through the eager TCP rings in
dcn_group.py). A collective's best schedule depends on where its bytes
would land on that topology and how big the message is — TACCL
(arXiv:2111.04867) phrases this as a communication sketch; here the
sketch is fixed (ring / recursive doubling / sharded two-tier) and an
alpha-beta cost model picks among them per (collective, topology,
nbytes) at call time:

  * ring            — bandwidth-optimal, 2(n-1) latency terms; wins for
                      large messages on a flat topology.
  * recursive       — latency-optimal, ceil(log2 n) rounds each moving
    doubling          the full message; wins below the alpha/beta
                      crossover (small control-plane tensors, scalars).
  * sharded hier    — ICI-local reduce-scatter, DCN exchange of one
                      ICI shard per lane, ICI allgather; wins for large
                      messages whenever the topology HAS a local tier
                      (cuts DCN bytes per process to 1/n_local of the
                      flat all-devices ring — see hier_group.py).

`RT_COLLECTIVE_ALGO` (ring|rd|hier|auto) overrides the model for every
op, so a bad model decision can be steered around in production without
a code change; the chosen algorithm is recorded per op either way
(collective.last_op_info / the flight-recorder observer stream).

Link constants default to published TPU-pod ballparks and are
env-overridable (RT_COLLECTIVE_{ICI,DCN}_{ALPHA_S,GBPS}) — the model
only has to rank algorithms, not predict wall clock.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Optional

# Modeled algorithms (string enum kept loose: these travel through op
# observers, metrics tags, and the RT_COLLECTIVE_ALGO env override).
ALGO_RING = "ring"
ALGO_RD = "rd"            # recursive doubling (latency-optimal)
ALGO_HIER = "hier"        # sharded two-tier (ICI reduce-scatter / DCN / ICI)
ALGO_AUTO = "auto"
_VALID_ALGOS = (ALGO_RING, ALGO_RD, ALGO_HIER)

_ALGO_ENV = "RT_COLLECTIVE_ALGO"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return default


@dataclass(frozen=True)
class LinkTier:
    """One interconnect tier under the alpha-beta model: a message of b
    bytes costs alpha_s + b * beta_s_per_byte on one link."""

    name: str               # "ici" | "dcn"
    alpha_s: float          # per-message latency (s)
    beta_s_per_byte: float  # inverse bandwidth (s/byte)

    def xfer(self, nbytes: float) -> float:
        return self.alpha_s + nbytes * self.beta_s_per_byte


def ici_tier() -> LinkTier:
    """ICI defaults: ~1 us latency, ~100 GB/s per link (v4/v5 ballpark)."""
    gbps = _env_float("RT_COLLECTIVE_ICI_GBPS", 100.0)
    return LinkTier(
        "ici",
        alpha_s=_env_float("RT_COLLECTIVE_ICI_ALPHA_S", 1e-6),
        beta_s_per_byte=1.0 / (gbps * 1e9),
    )


def dcn_tier() -> LinkTier:
    """DCN defaults: ~50 us latency, ~12.5 GB/s (100 Gbps) per host."""
    gbps = _env_float("RT_COLLECTIVE_DCN_GBPS", 12.5)
    return LinkTier(
        "dcn",
        alpha_s=_env_float("RT_COLLECTIVE_DCN_ALPHA_S", 50e-6),
        beta_s_per_byte=1.0 / (gbps * 1e9),
    )


@dataclass(frozen=True)
class Topology:
    """The gang's link shape as the cost model sees it.

    n_procs  — DCN ring members (processes/slices/hosts).
    n_local  — devices each process reaches over the fast local tier
               (ICI chips on a TPU host; the virtual CPU mesh in tests);
               1 means the topology is flat and "hier" is meaningless.
    """

    n_procs: int
    n_local: int
    ici: LinkTier
    dcn: LinkTier

    @property
    def total_ranks(self) -> int:
        return self.n_procs * self.n_local

    @property
    def has_local_tier(self) -> bool:
        return self.n_local > 1

    # -- construction ----------------------------------------------------
    @classmethod
    def detect(cls, n_procs: int, n_local: Optional[int] = None) -> "Topology":
        """Build the topology at group creation: DCN width from the
        gang's world size, local width from TPU accelerator metadata
        (chip count) falling back to jax's local device count (the
        virtual CPU mesh in tests), falling back to flat."""
        if n_local is None:
            n_local = cls._detect_n_local()
        return cls(
            n_procs=max(1, int(n_procs)),
            n_local=max(1, int(n_local)),
            ici=ici_tier(),
            dcn=dcn_tier(),
        )

    @staticmethod
    def _detect_n_local() -> int:
        try:
            from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager

            chips = TPUAcceleratorManager.get_current_node_num_accelerators()
            if chips:
                return int(chips)
        except Exception:  # rtlint: disable=RT007 — metadata probe only
            pass
        try:
            import jax

            return len(jax.local_devices())
        except Exception:  # rtlint: disable=RT007 — no backend: flat topo
            return 1

    # -- cost model ------------------------------------------------------
    def cost_ring_allreduce(self, nbytes: float, n: Optional[int] = None,
                            tier: Optional[LinkTier] = None) -> float:
        """Ring reduce-scatter + allgather over `n` members of `tier`:
        2(n-1) serialized steps each moving nbytes/n."""
        n = n or self.n_procs
        tier = tier or self.dcn
        if n <= 1:
            return 0.0
        return 2 * (n - 1) * tier.xfer(nbytes / n)

    def cost_rd_allreduce(self, nbytes: float, n: Optional[int] = None,
                          tier: Optional[LinkTier] = None) -> float:
        """Recursive doubling: ceil(log2 n) rounds, full message each
        round (plus a fold round when n is not a power of two)."""
        n = n or self.n_procs
        tier = tier or self.dcn
        if n <= 1:
            return 0.0
        rounds = math.ceil(math.log2(n))
        if n & (n - 1):  # non-power-of-2 pays the fold in and out
            rounds += 2
        return rounds * tier.xfer(nbytes)

    def cost_hier_allreduce(self, nbytes: float) -> float:
        """Sharded two-tier: ICI reduce-scatter + per-lane DCN ring of
        one nbytes/n_local shard + ICI allgather. The DCN lanes are
        modeled parallel (per-chip NICs), so the DCN term is one ring
        over a single shard — the 1/n_local cut hier_group implements."""
        if not self.has_local_tier:
            return float("inf")
        shard = nbytes / self.n_local
        ici = 2 * (self.n_local - 1) * self.ici.xfer(nbytes / self.n_local)
        dcn = self.cost_ring_allreduce(shard, self.n_procs, self.dcn)
        return ici + dcn

    def crossover_nbytes(self) -> int:
        """Smallest power-of-2 message size at which the model stops
        picking the latency-optimal algorithm for allreduce (bisection
        over the same costs select_algorithm uses)."""
        lo = 1
        for exp in range(1, 34):
            size = 1 << exp
            if self.select("allreduce", size) != ALGO_RD:
                return size
            lo = size
        return lo

    # -- selection -------------------------------------------------------
    def select(self, collective: str, nbytes: float) -> str:
        """Pick the modeled-cheapest algorithm for one op. Env override
        RT_COLLECTIVE_ALGO wins (value "auto" falls through to the
        model); unknown values raise so a typo cannot silently pick a
        default."""
        forced = os.environ.get(_ALGO_ENV, "").strip().lower()
        if forced and forced != ALGO_AUTO:
            if forced not in _VALID_ALGOS:
                raise ValueError(
                    f"{_ALGO_ENV}={forced!r}: valid values are "
                    f"{_VALID_ALGOS + (ALGO_AUTO,)}"
                )
            if forced == ALGO_HIER and not self.has_local_tier:
                return ALGO_RING  # flat topology cannot shard locally
            return forced
        if self.n_procs <= 1:
            return ALGO_RING  # degenerate: no DCN exchange at all
        costs = {
            ALGO_RING: self.cost_ring_allreduce(nbytes),
            ALGO_RD: self.cost_rd_allreduce(nbytes),
        }
        if self.has_local_tier and collective in (
                "allreduce", "reducescatter"):
            costs[ALGO_HIER] = self.cost_hier_allreduce(nbytes)
        return min(costs, key=costs.get)


def select_algorithm(collective: str, topo: Topology, nbytes: float) -> str:
    """Module-level alias (the per-op call sites read better with it)."""
    return topo.select(collective, nbytes)
