"""Eager collectives over the devices attached to this process, via XLA.

This is the TPU-native replacement for the reference's NCCLGroup
(python/ray/util/collective/collective_group/nccl_collective_group.py:127):
on a TPU host one process owns all local chips, so "eager" collectives are
tiny jit-compiled programs over a persistent local mesh — the compiled
graph runs the reduction on ICI. (SURVEY.md §7 hard parts: "the eager
backend must JIT tiny collective programs and keep a persistent mesh
context per group".)

In tests, the same code runs over the 8 virtual CPU devices.
"""

from __future__ import annotations

import functools
from typing import List, Optional

from ray_tpu.util.collective.types import ReduceOp

_REDUCERS = {
    ReduceOp.SUM: "sum",
    ReduceOp.PRODUCT: "prod",
    ReduceOp.MIN: "min",
    ReduceOp.MAX: "max",
}


class XlaLocalGroup:
    """Collectives across this process's local devices.

    The "ranks" of this group are local devices, not processes: values are
    lists with one array per device (matching the reference's multi-GPU
    collective entry points, e.g. allreduce_multigpu).
    """

    def __init__(self, num_devices: Optional[int] = None):
        import jax

        devices = jax.local_devices()
        if num_devices is not None:
            devices = devices[:num_devices]
        self.devices = devices
        self.world_size = len(devices)
        import numpy as np
        from jax.sharding import Mesh

        self.mesh = Mesh(np.array(self.devices), axis_names=("rank",))
        # Same shape DcnGroup records, so the collective metrics/observer
        # stream covers both tiers. "bytes" is the LOGICAL per-device
        # message size — ICI wire bytes are XLA's business, not ours.
        self.last_op_info: dict = {}

    def _record_op(self, op_name: str, dtype, nbytes: int) -> None:
        self.last_op_info = {
            "op": op_name,
            "algo": "psum",
            "tier": "ici",
            "bytes": int(nbytes),
            "dtype": str(dtype),
            "quant": None,
        }

    @functools.lru_cache(maxsize=32)
    def _allreduce_fn(self, op: ReduceOp):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_tpu._private.jax_compat import shard_map

        reducer = {
            ReduceOp.SUM: jax.lax.psum,
            ReduceOp.MAX: jax.lax.pmax,
            ReduceOp.MIN: jax.lax.pmin,
        }.get(op)

        if reducer is None:  # product: log-space trick is lossy; use prod
            def reducer(x, axis_name):
                return jax.lax.all_gather(x, axis_name).prod(axis=0)

        @jax.jit
        def fn(stacked):
            # stacked: [world, ...] sharded over ranks on dim 0.
            def body(x):
                return reducer(x[0], "rank")[None]

            return shard_map(
                body,
                mesh=self.mesh,
                in_specs=P("rank"),
                out_specs=P("rank"),
            )(stacked)

        return fn

    def _stack(self, tensors):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        stacked = jnp.stack([jnp.asarray(t) for t in tensors])
        return jax.device_put(
            stacked, NamedSharding(self.mesh, P("rank"))
        )

    def allreduce(self, tensors: List, op: ReduceOp = ReduceOp.SUM) -> List:
        import numpy as np

        if len(tensors) != self.world_size:
            raise ValueError(
                f"need one tensor per device ({self.world_size}), got {len(tensors)}"
            )
        out = self._allreduce_fn(op)(self._stack(tensors))
        arr0 = np.asarray(tensors[0])
        self._record_op("allreduce", arr0.dtype, arr0.nbytes)
        return [out[i] for i in range(self.world_size)]

    def allgather(self, tensors: List) -> List[List]:
        import jax
        import numpy as np

        stacked = self._stack(tensors)
        gathered = [stacked[i] for i in range(self.world_size)]
        arr0 = np.asarray(tensors[0])
        self._record_op("allgather", arr0.dtype, arr0.nbytes)
        return [list(gathered) for _ in range(self.world_size)]

    def reducescatter(self, tensors: List, op: ReduceOp = ReduceOp.SUM) -> List:
        import numpy as np

        reduced = self.allreduce(tensors, op)
        outs = []
        for i in range(self.world_size):
            chunks = np.array_split(np.asarray(reduced[i]).reshape(-1), self.world_size)
            outs.append(chunks[i])
        arr0 = np.asarray(tensors[0])
        self._record_op("reducescatter", arr0.dtype, arr0.nbytes)
        return outs

    def broadcast(self, tensors: List, root_rank: int = 0) -> List:
        import jax.numpy as jnp
        import numpy as np

        src = jnp.asarray(tensors[root_rank])
        arr = np.asarray(tensors[root_rank])
        self._record_op("broadcast", arr.dtype, arr.nbytes)
        return [src for _ in range(self.world_size)]

    def barrier(self):
        import jax.numpy as jnp
        import numpy as np

        self.allreduce([jnp.zeros(1) for _ in range(self.world_size)])
        self._record_op("barrier", np.dtype(np.float32), 0)

    def destroy(self):
        pass
