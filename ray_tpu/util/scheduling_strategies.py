"""Scheduling strategies for tasks and actors.

Analog of python/ray/util/scheduling_strategies.py in the reference
(PlacementGroupSchedulingStrategy :15, NodeAffinitySchedulingStrategy :41,
NodeLabelSchedulingStrategy :135).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.util.placement_group import PlacementGroup


class PlacementGroupSchedulingStrategy:
    def __init__(
        self,
        placement_group: PlacementGroup,
        placement_group_bundle_index: int = 0,
        placement_group_capture_child_tasks: bool = False,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks

    def to_dict(self):
        return {
            "type": "placement_group",
            "pg_id": self.placement_group.id.binary(),
            "bundle_index": self.placement_group_bundle_index,
        }


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: bytes, soft: bool = False):
        self.node_id = node_id
        self.soft = soft

    def to_dict(self):
        return {"type": "node_affinity", "node_id": self.node_id, "soft": self.soft}


class NodeLabelSchedulingStrategy:
    def __init__(self, hard: Optional[dict] = None, soft: Optional[dict] = None):
        self.hard = hard or {}
        self.soft = soft or {}

    def to_dict(self):
        return {"type": "node_label", "hard": self.hard, "soft": self.soft}
