"""Distributed tracing: trace-context propagation across task boundaries.

Analog of the reference's OpenTelemetry integration
(python/ray/util/tracing/tracing_helper.py:326 _inject_tracing_into_function
+ context propagation in task metadata): when tracing is enabled, every
task/actor-call submission carries its caller's trace context, the
executing worker opens a child span for the task body, and nested submits
inherit — so one logical request yields a cross-process span TREE, not
disconnected per-process spans.

Spans ride the same GCS task-event stream the timeline uses (type
TRACE_SPAN), so `rt timeline` shows them and the state API can assemble
the tree per trace id. No OpenTelemetry dependency: span records are
plain events; export to OTLP is a consumer-side concern.

Usage:
    from ray_tpu.util import tracing
    tracing.enable()
    with tracing.span("handle-request"):
        rt.get(f.remote(...))   # f's execution becomes a child span
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

_tls = threading.local()
_enabled: Optional[bool] = None


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get("RT_TRACING", "0") not in ("0", "", "false")
    return _enabled


def _new_id(nbytes: int = 8) -> str:
    return os.urandom(nbytes).hex()


def current() -> Optional[Dict[str, str]]:
    """The active span context: {"trace_id", "span_id"} or None."""
    return getattr(_tls, "ctx", None)


def inject() -> Optional[Dict[str, str]]:
    """Context to attach to an outgoing task spec (None when tracing is
    off and no span is active).

    An ACTIVE context always propagates — worker processes adopt contexts
    via activate() without the driver's enabled flag (the reference
    propagates the same way: context in task metadata, not env). With
    tracing enabled but no active span, each submission roots a fresh
    trace, matching the reference's span-per-task behavior."""
    ctx = current()
    if ctx is not None:
        return {"trace_id": ctx["trace_id"], "parent_span_id": ctx["span_id"]}
    if not is_enabled():
        return None
    return {"trace_id": _new_id(16), "parent_span_id": ""}


def _record(name: str, ctx: Dict[str, str], parent_id: str, start: float,
            end: float, kind: str) -> None:
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import profiling

    try:
        client = worker_mod.get_client()
        node_id = client.node_id
        worker_id = client.client_id
    except Exception:  # noqa: BLE001 — not connected: drop the span  # rtlint: disable=RT007 — tracing is best-effort garnish and must never break the traced op
        return
    base = {
        "task_id": bytes.fromhex(ctx["span_id"]) + os.urandom(8),
        "name": name,
        "job_id": b"",
        "node_id": node_id,
        "worker_id": worker_id,
        "type": "TRACE_SPAN",
        "extra": {
            "trace_id": ctx["trace_id"],
            "span_id": ctx["span_id"],
            "parent_id": parent_id,
            "kind": kind,
        },
    }
    with profiling._lock:
        profiling._buffer.append({**base, "state": "RUNNING", "ts": start})
        profiling._buffer.append({**base, "state": "FINISHED", "ts": end})
    from ray_tpu.util import journal

    journal.emit("trace.span", name=name, trace_id=ctx["trace_id"],
                 kind=kind, dur_s=round(end - start, 6))
    # Bounded-delay batch flush: every span recorded inside the window
    # rides ONE add_task_events RPC (the old force-flush here cost one
    # GCS RPC per span — untenable once serve requests are traced).
    # atexit still force-flushes, so spans recorded just before a worker
    # idles out or the driver exits reach the timeline regardless.
    profiling.request_flush()


@contextmanager
def span(name: str):
    """Open a span as a child of the active one (or a trace root)."""
    if not is_enabled():
        yield
        return
    parent = current()
    ctx = {
        "trace_id": parent["trace_id"] if parent else _new_id(16),
        "span_id": _new_id(),
    }
    parent_id = parent["span_id"] if parent else ""
    prev = current()
    _tls.ctx = ctx
    start = time.time()
    try:
        yield
    finally:
        _tls.ctx = prev
        _record(name, ctx, parent_id, start, time.time(), "local")


@contextmanager
def attach(ctx: Optional[Dict[str, str]]):
    """Adopt an existing span context on THIS thread without opening a
    new span. Trace context is thread-local, so a background thread
    spawned mid-span starts detached; capture `current()` on the
    spawning side and `with tracing.attach(ctx):` in the thread body,
    and spans the thread opens join the request tree instead of rooting
    fresh traces. No-op (and records nothing) when ctx is None."""
    if not ctx:
        yield
        return
    prev = current()
    _tls.ctx = dict(ctx)
    try:
        yield
    finally:
        _tls.ctx = prev


@contextmanager
def activate(trace_ctx: Optional[Dict[str, str]], name: str):
    """Worker-side: adopt a received trace context for the duration of a
    task body, recording the execution as a child span. No-op when the
    submission carried no context."""
    if not trace_ctx:
        yield
        return
    ctx = {"trace_id": trace_ctx["trace_id"], "span_id": _new_id()}
    prev = current()
    _tls.ctx = ctx
    start = time.time()
    try:
        yield
    finally:
        _tls.ctx = prev
        _record(name, ctx, trace_ctx.get("parent_span_id", ""), start,
                time.time(), "task")


def get_trace(trace_id: str, address: Optional[str] = None) -> List[dict]:
    """Assemble one trace's spans (finished only) from the task-event
    stream, parent-linked: [{"name", "span_id", "parent_id", "ts",
    "dur_s", "kind"}]."""
    from ray_tpu.util.state.api import StateApiClient, fetch_task_events

    client = StateApiClient(address)
    try:
        events = fetch_task_events(client.call)
    finally:
        client.close()
    starts: Dict[bytes, dict] = {}
    spans: List[dict] = []
    for ev in events:
        if ev.get("type") != "TRACE_SPAN":
            continue
        extra = ev.get("extra", {})
        if extra.get("trace_id") != trace_id:
            continue
        if ev["state"] == "RUNNING":
            starts[ev["task_id"]] = ev
        elif ev["state"] == "FINISHED" and ev["task_id"] in starts:
            start = starts.pop(ev["task_id"])
            spans.append({
                "name": ev.get("name", ""),
                "span_id": extra["span_id"],
                "parent_id": extra.get("parent_id", ""),
                "kind": extra.get("kind", ""),
                "ts": start["ts"],
                "dur_s": max(0.0, ev["ts"] - start["ts"]),
            })
    spans.sort(key=lambda s: s["ts"])
    return spans
