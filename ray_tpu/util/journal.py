"""Cluster black box: per-process event journal with hybrid logical clocks.

The runtime's telemetry planes — the train flight recorder, the serve
observatory, the lifecycle profiler, loadgen stamp cards — each keep a
private ring with a private clock, so reconstructing *why* a client saw
a 503 after a chaos run means hand-joining five snapshots taken after
the evidence was overwritten. This module is the shared spine under all
of them: an always-on, lock-cheap, ring-buffered journal every emitter
routes one summary event through, stamped with a **hybrid logical
clock** (Kulkarni et al., "Logical Physical Clocks") so events from
different processes merge into one causally-consistent timeline despite
host clock skew.

HLC in one paragraph: a stamp is ``(pt, lc)`` — physical microseconds
plus a logical counter. A local event takes ``max(wall, last_pt)`` and
bumps ``lc`` when the wall did not advance (monotone under clock
regression); receiving a remote stamp takes the max of all three clocks
and bumps ``lc`` past whichever won, so *send happens-before receive*
holds in stamp order even when the receiver's wall clock is behind the
sender's. Stamps ride the wires that already exist: every RPC frame
(``_private/protocol.py``, the ``"h"`` field), observatory wire
contexts (handle stamp cards), and DCN identification frames.

Failure-triggered capture: typed failure observers (replica death seen
by the controller, breaker-open, collective timeout, deadline-expiry
storms, HOL detection, gang restart) call :func:`trigger_postmortem`,
which asks the GCS to fan a ``journal_dump`` push to every connected
process; each freezes its last-``journal_window_s`` ring into
``<journal_dir>/<bundle>/<label>-<pid>.jsonl``. ``rt postmortem
<bundle>`` merges the files into one HLC-ordered timeline and names the
culprit chain; ``rt timeline --cluster`` triggers a manual dump and
renders the live merged spine.

Knobs (Config fields, env-overridable): RT_JOURNAL_ENABLED,
RT_JOURNAL_RING, RT_JOURNAL_WINDOW_S, RT_JOURNAL_DIR,
RT_JOURNAL_AUTODUMP, RT_JOURNAL_COOLDOWN_S.

Steady-state cost is one short lock hold + a deque append per event
(emitters send one event per *step/request/transition*, never per
task), gated <2% on a 5 ms train step by bench_obs.py.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "HLC", "emit", "enabled", "wire_stamp", "observe_wire",
    "set_process_label", "process_label", "snapshot", "dump",
    "on_dump_trigger", "trigger_postmortem", "dump_dir", "load_bundle",
    "merge_events", "causal_chain", "render_timeline",
]


class HLC:
    """Hybrid logical clock: (physical µs, logical counter).

    ``tick()`` stamps a local/send event; ``update(remote)`` merges a
    received stamp. Both are monotone: a host clock stepping backwards
    (NTP correction, VM migration) bumps ``lc`` instead of ever issuing
    a stamp that sorts before an earlier one.
    """

    __slots__ = ("_pt", "_lc", "_lock")

    def __init__(self):
        self._pt = 0
        self._lc = 0
        self._lock = threading.Lock()

    def tick(self) -> Tuple[int, int]:
        wall = int(time.time() * 1e6)
        with self._lock:
            if wall > self._pt:
                self._pt = wall
                self._lc = 0
            else:
                self._lc += 1
            return self._pt, self._lc

    def update(self, remote: Tuple[int, int]) -> Tuple[int, int]:
        """Merge a remote stamp (message receive): the new local stamp
        sorts after both the remote stamp and every prior local one."""
        rpt, rlc = int(remote[0]), int(remote[1])
        wall = int(time.time() * 1e6)
        with self._lock:
            pt = max(wall, self._pt, rpt)
            if pt == self._pt and pt == rpt:
                lc = max(self._lc, rlc) + 1
            elif pt == self._pt:
                lc = self._lc + 1
            elif pt == rpt:
                lc = rlc + 1
            else:
                lc = 0
            self._pt, self._lc = pt, lc
            return pt, lc

    def read(self) -> Tuple[int, int]:
        with self._lock:
            return self._pt, self._lc


# -- process-wide singleton state ----------------------------------------

_hlc = HLC()
_lock = threading.Lock()
_ring: deque = deque()
_ring_max = 0
_label = ""
_events_total = 0
_dropped_total = 0
_seen_triggers: set = set()
_last_trigger_mono = 0.0
_metric_keys: Dict[str, tuple] = {}


def _cfg():
    from ray_tpu._private.config import get_config

    return get_config()


def enabled() -> bool:
    return _cfg().journal_enabled


def set_process_label(label: str, weak: bool = False) -> None:
    """Name this process in dumps ("driver", "serve-controller",
    "replica:app#0", ...). ``weak=True`` only fills an unset label —
    the GCS/raylet use it so an in-process test node never clobbers
    the driver's name."""
    global _label
    if weak and _label:
        return
    _label = str(label)


def process_label() -> str:
    return _label or f"pid{os.getpid()}"


def _metrics(kind: str):
    """Keyed counter fast path per event kind; lazy so importing the
    journal never drags the metrics/worker stack in."""
    key = _metric_keys.get(kind)
    if key is None:
        from ray_tpu.util import metrics as rt_metrics

        events = rt_metrics.get_or_create(
            rt_metrics.Counter, "journal_events_total",
            "Events appended to the process-local journal ring, by kind.",
            tag_keys=("kind",),
        )
        dropped = rt_metrics.get_or_create(
            rt_metrics.Counter, "journal_dropped_total",
            "Journal events overwritten before any dump captured them.",
        )
        key = (events, events._key({"kind": kind}), dropped, dropped._key(None))
        _metric_keys[kind] = key
    return key


def emit(kind: str, /, **fields: Any) -> None:
    """Append one event to this process's ring. Lock-cheap and never
    raises: the black box must not take down the component feeding it.
    ``kind`` is positional-only so a payload field named "kind" cannot
    collide at call time; envelope keys in the payload are prefixed
    rather than letting them clobber the stamp."""
    global _ring_max, _events_total, _dropped_total
    try:
        cfg = _cfg()
        if not cfg.journal_enabled:
            return
        if _ring_max != cfg.journal_ring:
            _resize_ring(cfg.journal_ring)
        pt, lc = _hlc.tick()
        rec = {"hlc": [pt, lc], "ts": time.time(), "kind": kind,
               "proc": process_label(), "pid": os.getpid()}
        for k in ("hlc", "ts", "kind", "proc", "pid"):
            if k in fields:
                fields[f"f_{k}"] = fields.pop(k)
        rec.update(fields)
        with _lock:
            dropped = len(_ring) >= _ring_max
            _ring.append(rec)
            _events_total += 1
            if dropped:
                _dropped_total += 1
        try:
            events, ek, drop_m, dk = _metrics(kind)
            events.inc_keyed(ek, 1.0)
            if dropped:
                drop_m.inc_keyed(dk, 1.0)
        except Exception:  # rtlint: disable=RT007 — metrics registry may not be up yet; the event is already in the ring
            pass
    except Exception:  # rtlint: disable=RT007 — emit() never raises by contract; the black box must not take down its feeder
        pass


def _resize_ring(n: int) -> None:
    global _ring, _ring_max
    with _lock:
        _ring = deque(_ring, maxlen=max(16, int(n)))
        _ring_max = _ring.maxlen


def counts() -> Tuple[int, int]:
    """(events_total, dropped_total) for this process."""
    with _lock:
        return _events_total, _dropped_total


# -- wire propagation -----------------------------------------------------

def wire_stamp() -> Optional[List[int]]:
    """HLC stamp for an outgoing frame ([pt_us, lc]), or None when the
    journal is disabled (the frame field is simply omitted)."""
    try:
        if not _cfg().journal_enabled:
            return None
        pt, lc = _hlc.tick()
        return [pt, lc]
    except Exception:  # rtlint: disable=RT007 — stamping must never break an RPC; the frame goes out unstamped
        return None


def observe_wire(h: Any) -> None:
    """Merge a received frame's HLC stamp into the local clock."""
    try:
        if h and _cfg().journal_enabled:
            _hlc.update((h[0], h[1]))
    except Exception:  # rtlint: disable=RT007 — a malformed wire stamp is ignored, the local clock stands
        pass


# -- freeze / dump --------------------------------------------------------

def dump_dir() -> str:
    d = _cfg().journal_dir or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "ray_tpu", "postmortem"
    )
    return d


def snapshot(window_s: Optional[float] = None) -> List[Dict]:
    """Copy of the ring (oldest first), optionally only the last
    ``window_s`` seconds by wall timestamp."""
    with _lock:
        events = list(_ring)
    if window_s is not None and window_s > 0:
        cutoff = time.time() - window_s  # rtlint: disable=RT011 — deliberate wall anchor: ring events carry wall ts for cross-process stitching
        events = [e for e in events if e.get("ts", 0.0) >= cutoff]
    return events


def dump(bundle_dir: str, trigger: Optional[Dict] = None,
         window_s: Optional[float] = None) -> Optional[str]:
    """Freeze this process's ring into ``bundle_dir`` as one JSONL file.

    Returns the written path (None on failure — dumping is best-effort,
    a full disk must not crash a replica that just survived a fault)."""
    try:
        window = window_s if window_s is not None else _cfg().journal_window_s
        events = snapshot(window_s=window)
        os.makedirs(bundle_dir, exist_ok=True)
        label = process_label().replace("/", "_").replace(":", "_")
        path = os.path.join(bundle_dir, f"{label}-{os.getpid()}.jsonl")
        ev_total, drop_total = counts()
        meta = {
            "kind": "journal.meta", "proc": process_label(),
            "pid": os.getpid(), "ts": time.time(),
            "hlc": list(_hlc.read()), "events": len(events),
            "events_total": ev_total, "dropped_total": drop_total,
            "trigger": trigger or {},
        }
        with open(path, "w") as f:
            f.write(json.dumps(meta, default=str) + "\n")
            for e in events:
                f.write(json.dumps(e, default=str) + "\n")
        return path
    except Exception:  # noqa: BLE001 — best-effort by contract
        return None


def on_dump_trigger(payload: Any) -> None:
    """``journal_dump`` pubsub push handler: every connected process runs
    this (worker.py subscribes on connect). Idempotent per trigger id —
    the GCS may re-publish after a redial replays subscriptions."""
    try:
        if not isinstance(payload, dict):
            return
        trigger_id = payload.get("trigger_id") or ""
        with _lock:
            if trigger_id in _seen_triggers:
                return
            _seen_triggers.add(trigger_id)
            if len(_seen_triggers) > 512:
                _seen_triggers.clear()
                _seen_triggers.add(trigger_id)
        observe_wire(payload.get("hlc"))
        bundle = payload.get("bundle")
        if not bundle:
            return
        dump(bundle, trigger=payload, window_s=payload.get("window_s"))
    except Exception:  # noqa: BLE001 — push handlers must never raise
        pass


def trigger_postmortem(reason: str, **detail: Any) -> None:
    """Publish a cluster-wide dump trigger via the GCS (fire-and-forget).

    Called by typed failure observers (breaker-open, replica-death
    replacement, collective timeout, HOL, deadline storms, gang
    restart). Local cooldown + GCS-side cooldown keep a failure *storm*
    from turning into a dump storm; the first trigger in a window wins
    and later ones ride in its bundle."""
    global _last_trigger_mono
    try:
        cfg = _cfg()
        if not cfg.journal_enabled or not cfg.journal_autodump:
            return
        now = time.monotonic()
        with _lock:
            if now - _last_trigger_mono < cfg.journal_cooldown_s:
                return
            _last_trigger_mono = now
        emit("journal.trigger_requested", reason=reason, **detail)

        def _fire():
            try:
                from ray_tpu._private import worker as worker_mod

                client = worker_mod.get_client()
                client._run(
                    client._gcs_call(
                        "journal_trigger",
                        {"reason": reason, "source": process_label(),
                         "detail": {k: str(v) for k, v in detail.items()}},
                    ),
                    timeout=10.0,
                )
            except Exception:  # noqa: BLE001 — no client / GCS down: the
                # local ring still holds the evidence for a manual dump.
                pass

        threading.Thread(
            target=_fire, name="rt-journal-trigger", daemon=True
        ).start()
    except Exception:  # rtlint: disable=RT007 — trigger is fire-and-forget by contract; the local ring keeps the evidence
        pass


# -- bundle assembly (rt postmortem / rt timeline --cluster) --------------

def load_bundle(bundle_dir: str) -> Tuple[List[Dict], List[Dict]]:
    """Read every per-process JSONL in a bundle.

    Returns (events, metas): events from all processes (unmerged),
    metas one per file (the ``journal.meta`` header lines)."""
    events: List[Dict] = []
    metas: List[Dict] = []
    for name in sorted(os.listdir(bundle_dir)):
        if not name.endswith(".jsonl"):
            continue
        path = os.path.join(bundle_dir, name)
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("kind") == "journal.meta":
                        metas.append(rec)
                    else:
                        events.append(rec)
        except OSError:
            continue
    return merge_events(events), metas


def _order_key(e: Dict) -> tuple:
    h = e.get("hlc") or [0, 0]
    try:
        pt, lc = int(h[0]), int(h[1])
    except (TypeError, ValueError, IndexError):
        pt, lc = 0, 0
    return (pt, lc, str(e.get("proc", "")), int(e.get("pid", 0) or 0))


def merge_events(events: Iterable[Dict]) -> List[Dict]:
    """One causally-ordered timeline: sort by (pt, lc, origin). HLC
    guarantees send < receive in this order; the origin tie-break makes
    the merge deterministic for concurrent events."""
    return sorted(events, key=_order_key)


#: Event kinds that seed a culprit chain (the injected/primary fault).
_CHAIN_SEEDS = (
    "chaos.", "collective.timeout", "raylet.worker_dead",
    "gcs.node_dead", "train.gang_restart",
)
#: Kinds that count as links from fault to client-observed effect. The
#: chain reports the FIRST occurrence of each link after the seed, in
#: HLC order — e.g. chaos.kill_replica → raylet.worker_dead →
#: gcs.actor DEAD → serve.controller replace → serve.breaker open →
#: serve.redispatch → serve.stream_resume → client.error.
_CHAIN_LINKS = (
    "chaos.", "raylet.worker_dead", "gcs.actor", "gcs.node_dead",
    "gcs.preemption", "serve.controller", "serve.breaker",
    "serve.redispatch", "serve.stream_resume", "serve.shed",
    "serve.deadline_expired", "serve.hol", "collective.timeout",
    "train.gang_restart", "train.resize", "serve.request_error",
    "client.error", "journal.trigger",
)


def _link_ident(e: Dict) -> Optional[str]:
    """Dedup identity for a chain link (None = not a link). State-change
    kinds key on their salient value so e.g. breaker open and breaker
    close are distinct links but 40 redispatches collapse to one."""
    kind = e.get("kind", "")
    for prefix in _CHAIN_LINKS:
        if kind.startswith(prefix):
            break
    else:
        return None
    if kind == "gcs.actor":
        # Only lifecycle edges matter for causality; ALIVE churn from
        # unrelated actors would bury the chain.
        if e.get("state") not in ("DEAD", "RESTARTING"):
            return None
        return f"{kind}:{e.get('state')}:{e.get('actor_id', '')}"
    if kind == "serve.breaker":
        return f"{kind}:{e.get('state')}:{e.get('replica', '')}"
    if kind == "serve.controller":
        return f"{kind}:{e.get('action')}:{e.get('app', '')}"
    return kind


def causal_chain(events: List[Dict]) -> List[Dict]:
    """Name the culprit chain in a merged timeline: the first injected /
    primary fault, then the first occurrence of each downstream link in
    HLC order, ending at the first client-observed error (when one was
    captured).

    An explicit chaos injection outranks ambient infrastructure seeds:
    a capture window usually also holds unrelated worker-death noise
    (a previous app's teardown, a drained replica being reaped), and
    seeding there would pin the postmortem on the wrong fault. When the
    timeline records an injection, that IS the primary fault; only
    without one does the earliest typed infrastructure failure seed."""
    events = merge_events(events)
    seed_idx = None
    for i, e in enumerate(events):
        if e.get("kind", "").startswith("chaos."):
            seed_idx = i
            break
    if seed_idx is None:
        for i, e in enumerate(events):
            kind = e.get("kind", "")
            if any(kind.startswith(s) for s in _CHAIN_SEEDS):
                seed_idx = i
                break
    if seed_idx is None:
        return []
    chain = [events[seed_idx]]
    seen = {_link_ident(events[seed_idx])}
    for e in events[seed_idx + 1:]:
        ident = _link_ident(e)
        if ident is None or ident in seen:
            continue
        seen.add(ident)
        chain.append(e)
        if e.get("kind") in ("client.error", "serve.request_error"):
            break
    return chain


def _fmt_event(e: Dict, t0: Optional[float] = None) -> str:
    ts = e.get("ts", 0.0)
    h = e.get("hlc") or [0, 0]
    rel = f"+{ts - t0:8.3f}s" if t0 is not None else (
        time.strftime("%H:%M:%S", time.localtime(ts))
        + f".{int((ts % 1) * 1000):03d}"
    )
    extras = " ".join(
        f"{k}={e[k]}" for k in sorted(e)
        if k not in ("hlc", "ts", "kind", "proc", "pid")
    )
    origin = f"{e.get('proc', '?')}({e.get('pid', '?')})"
    return (f"{rel}  hlc={h[0]}.{h[1]:<3} {origin:<28} "
            f"{e.get('kind', '?'):<24} {extras}")


def render_timeline(events: List[Dict], limit: int = 0,
                    relative: bool = True) -> str:
    """Human-readable merged spine, one line per event in HLC order."""
    events = merge_events(events)
    if limit and len(events) > limit:
        events = events[-limit:]
    if not events:
        return "(no events)"
    t0 = events[0].get("ts", 0.0) if relative else None
    return "\n".join(_fmt_event(e, t0) for e in events)
