"""User-facing application metrics: Counter, Gauge, Histogram.

Analog of the reference's ``ray.util.metrics`` (python/ray/util/metrics.py:
Counter :19, Gauge :150, Histogram :229). Metric records are aggregated
locally and flushed to the GCS metrics table once a second by a background
thread; the dashboard exports the cluster-wide aggregate in Prometheus
text format at ``/metrics`` (the role the per-node metrics agent +
prometheus_exporter.py plays in the reference).
"""

from __future__ import annotations

import atexit
import threading
import time
from bisect import bisect_left as _bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

_DEFAULT_BOUNDARIES = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    25.0, 50.0, 100.0,
)

# Boundaries for step/latency-class histograms (seconds). TPU step phases
# (dispatch, fetch, collective, feed stall) live well under the 5ms floor
# of _DEFAULT_BOUNDARIES; metrics that time hot-loop phases should pass
# these instead. Existing metrics keep _DEFAULT_BOUNDARIES — the GCS
# aggregator rejects a histogram re-registered under different
# boundaries, so the default must stay stable.
LATENCY_BOUNDARIES = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# LATENCY_BOUNDARIES with a multi-second tail, for request-scale
# histograms (e2e, TTFT) whose macro-load p99s run past 10s and would
# otherwise clamp into the +Inf bucket. A separate tuple — NOT an edit
# to LATENCY_BOUNDARIES — because the aggregator rejects re-registered
# histograms whose boundaries changed; only metrics that have always
# used this tuple may use it.
LATENCY_BOUNDARIES_WIDE = LATENCY_BOUNDARIES + (
    15.0, 25.0, 40.0, 60.0, 90.0, 120.0, 180.0, 300.0,
)

_registry_lock = threading.Lock()
_registry: List["Metric"] = []
_flusher_started = False


def _tags_key(tags: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(tags.items()))


class Metric:
    def __init__(
        self,
        name: str,
        description: str = "",
        tag_keys: Optional[Sequence[str]] = None,
    ):
        if not name:
            raise ValueError("metric name is required")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry.append(self)
        _ensure_flusher()

    @property
    def info(self) -> Dict:
        return {
            "name": self._name,
            "description": self._description,
            "tag_keys": self._tag_keys,
            "default_tags": dict(self._default_tags),
        }

    def set_default_tags(self, tags: Dict[str, str]):
        self._check_tags(tags)
        self._default_tags = dict(tags)
        return self

    def _check_tags(self, tags: Optional[Dict[str, str]]):
        for k in tags or ():
            if k not in self._tag_keys:
                raise ValueError(
                    f"tag {k!r} was not declared in tag_keys={self._tag_keys}"
                )

    def _merged(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        self._check_tags(tags)
        merged = dict(self._default_tags)
        merged.update(tags or {})
        return merged

    def _key(self, tags: Optional[Dict[str, str]]) -> tuple:
        """Resolve tags to the internal series key once, for hot paths
        that record per step/request: validate + merge + sort here, then
        pass the key to *_keyed() on every observation."""
        return _tags_key(self._merged(tags))

    def _drain(self) -> Optional[dict]:  # -> report record or None
        raise NotImplementedError


class Counter(Metric):
    """Monotonically increasing value (util/metrics.py:19)."""

    def __init__(self, name, description="", tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._deltas: Dict[tuple, float] = {}

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value <= 0:
            raise ValueError("Counter.inc() requires value > 0")
        self.inc_keyed(self._key(tags), value)

    def inc_keyed(self, key: tuple, value: float = 1.0):
        """inc() with a key pre-resolved via _key() — per-step hot path."""
        with self._lock:
            self._deltas[key] = self._deltas.get(key, 0.0) + value

    def _drain(self):
        with self._lock:
            if not self._deltas:
                return None
            deltas, self._deltas = self._deltas, {}
        return {
            "type": "counter",
            "name": self._name,
            "description": self._description,
            "data": [[list(k), v] for k, v in deltas.items()],
        }


class Gauge(Metric):
    """Point-in-time value (util/metrics.py:150)."""

    def __init__(self, name, description="", tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._values: Dict[tuple, float] = {}
        self._dirty = False

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        self.set_keyed(self._key(tags), value)

    def set_keyed(self, key: tuple, value: float):
        """set() with a key pre-resolved via _key() — per-step hot path."""
        with self._lock:
            self._values[key] = float(value)
            self._dirty = True

    def _drain(self):
        with self._lock:
            if not self._dirty:
                return None
            self._dirty = False
            values = dict(self._values)
        return {
            "type": "gauge",
            "name": self._name,
            "description": self._description,
            "data": [[list(k), v] for k, v in values.items()],
        }


class Histogram(Metric):
    """Distribution over fixed bucket boundaries (util/metrics.py:229)."""

    def __init__(self, name, description="", boundaries=None, tag_keys=None):
        bounds = tuple(boundaries or _DEFAULT_BOUNDARIES)
        if list(bounds) != sorted(bounds):
            raise ValueError("histogram boundaries must be sorted")
        super().__init__(name, description, tag_keys)
        self._boundaries = bounds
        # per-tags: [bucket_counts (len boundaries+1), sum, count]
        self._state: Dict[tuple, list] = {}
        # Lifetime aggregates, NOT cleared by _drain: local observers
        # (engine stats endpoints) read these without perturbing the
        # once-a-second GCS/Prometheus flush.
        self._life_sum = 0.0
        self._life_count = 0
        self._life_max = 0.0

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        self.observe_keyed(self._key(tags), value)

    def observe_keyed(self, key: tuple, value: float):
        """observe() with a key pre-resolved via _key() — hot path."""
        with self._lock:
            st = self._state.get(key)
            if st is None:
                st = self._state[key] = [
                    [0] * (len(self._boundaries) + 1), 0.0, 0,
                ]
            st[0][_bisect_left(self._boundaries, value)] += 1
            st[1] += value
            st[2] += 1
            self._life_sum += value
            self._life_count += 1
            if value > self._life_max:
                self._life_max = value

    def summary(self) -> Dict:
        """Lifetime {count, sum, avg, max} across all tag sets — a
        local, non-draining read (the flusher's _drain keeps its own
        delta state untouched by this)."""
        with self._lock:
            count = self._life_count
            return {
                "count": count,
                "sum": self._life_sum,
                "avg": self._life_sum / count if count else 0.0,
                "max": self._life_max,
            }

    def _drain(self):
        with self._lock:
            if not self._state:
                return None
            state, self._state = self._state, {}
        return {
            "type": "histogram",
            "name": self._name,
            "description": self._description,
            "boundaries": list(self._boundaries),
            "data": [
                [list(k), {"buckets": st[0], "sum": st[1], "count": st[2]}]
                for k, st in state.items()
            ],
        }


def get_or_create(metric_cls, name: str, description: str = "", **kwargs):
    """Return the already-registered metric called `name` (of the same
    class) or create it. Module-level metric definitions that can be
    re-imported/re-executed (trainer restarts, test reruns in one
    process) must not register duplicates — the flusher would double-
    report every increment."""
    with _registry_lock:
        for m in _registry:
            if m._name == name and type(m) is metric_cls:
                return m
    return metric_cls(name, description, **kwargs)


def _flush_once() -> bool:
    """Drain all registered metrics into one GCS report. Returns True if
    anything was sent."""
    from ray_tpu._private import worker as worker_mod

    client = worker_mod.get_client_or_none()
    if client is None or not getattr(client, "_connected", False):
        return False
    with _registry_lock:
        metrics = list(_registry)
    records = []
    for m in metrics:
        try:
            r = m._drain()
        except Exception:  # one broken metric must not poison the batch
            continue
        if r is not None:
            records.append(r)
    if not records:
        return False
    try:
        client._run(
            client.gcs.call("metrics_report", {"records": records}), timeout=5
        )
        return True
    except Exception:
        return False


def _flusher_loop():
    while True:
        time.sleep(1.0)
        try:
            _flush_once()
        except Exception:
            pass


def _ensure_flusher():
    global _flusher_started
    with _registry_lock:
        if _flusher_started:
            return
        _flusher_started = True
    threading.Thread(
        target=_flusher_loop, name="rt-metrics-flush", daemon=True
    ).start()
    # Final drain at interpreter exit: a short-lived task/worker that
    # records and exits within the flusher's 1s period would otherwise
    # silently drop its last counters (profiling.py registers the same
    # guard for timeline spans). Registered with the flusher — once per
    # process, and only in processes that actually use metrics.
    atexit.register(_flush_once)
