"""Serializability inspection (reference: ray.util.inspect_serializability,
util/check_serialize.py) — walks an object that fails to cloudpickle and
reports WHICH nested member is the culprit, instead of the raw opaque
pickling error users otherwise get from a failed task submission.
"""

from __future__ import annotations

import inspect
from typing import Any, List, Optional, Set, Tuple


def _try_pickle(obj: Any) -> Optional[Exception]:
    import cloudpickle

    try:
        cloudpickle.dumps(obj)
        return None
    except Exception as e:  # noqa: BLE001 — the error IS the data here
        return e


def _children(obj: Any) -> List[Tuple[str, Any]]:
    """Nested members worth blaming: closure cells, attributes, items."""
    out: List[Tuple[str, Any]] = []
    if inspect.isfunction(obj):
        if obj.__closure__:
            for name, cell in zip(
                obj.__code__.co_freevars, obj.__closure__
            ):
                try:
                    out.append((f" closure '{name}'", cell.cell_contents))
                except ValueError:  # empty cell
                    pass
        for name, val in (obj.__globals__ or {}).items():
            if name in obj.__code__.co_names and not inspect.ismodule(val):
                out.append((f" global '{name}'", val))
    elif isinstance(obj, dict):
        out.extend((f"[{k!r}]", v) for k, v in obj.items())
    elif isinstance(obj, (list, tuple, set)):
        out.extend((f"[{i}]", v) for i, v in enumerate(obj))
    elif hasattr(obj, "__dict__"):
        out.extend((f".{k}", v) for k, v in vars(obj).items())
    return out


def inspect_serializability(
    obj: Any, name: Optional[str] = None, depth: int = 3, _print=print
) -> Tuple[bool, Set[str]]:
    """Check cloudpickle-ability; on failure, recursively blame the
    smallest unpicklable members. Returns (serializable, failure_set)
    where failure_set names the offending paths (reference signature:
    ray.util.inspect_serializability)."""
    name = name or getattr(obj, "__name__", type(obj).__name__)
    failures: Set[str] = set()

    def visit(o: Any, path: str, d: int):
        err = _try_pickle(o)
        if err is None:
            return
        kids = _children(o) if d > 0 else []
        kid_failed = False
        for label, child in kids:
            child_err = _try_pickle(child)
            if child_err is not None:
                kid_failed = True
                visit(child, f"{path}{label}", d - 1)
        if not kid_failed:
            # This object itself is the leaf culprit.
            failures.add(path)
            _print(f"[serializability] {path}: {type(err).__name__}: {err}")

    visit(obj, name, depth)
    ok = not failures
    if ok:
        _print(f"[serializability] {name}: OK")
    return ok, failures
