"""Cluster state introspection API.

Analog of the reference's ``ray.util.state`` (python/ray/util/state/api.py,
state_manager.py aggregating from GCS): ``list_tasks/actors/nodes/objects/
jobs/placement_groups/workers`` plus ``summarize_tasks``, powering the
``rt list`` / ``rt summary`` CLI.
"""

from ray_tpu.util.state.api import (  # noqa: F401
    StateApiClient,
    get_timeline,
    get_worker_stacks,
    list_actors,
    list_jobs,
    drain_node,
    get_log,
    list_logs,
    list_nodes,
    list_objects,
    list_placement_groups,
    list_tasks,
    list_workers,
    summarize_tasks,
)
