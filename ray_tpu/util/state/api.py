"""State API client: list/summarize cluster entities from the GCS.

Reference analog: python/ray/util/state/api.py (StateApiClient over the
dashboard REST API) + state_manager.py (aggregation from GcsTaskManager and
raylets). Here the client talks straight to the GCS over the control-plane
protocol; per-node worker listings fan out to each raylet's get_info.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Dict, List, Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.node import EventLoopThread
from ray_tpu._private.protocol import connect


def _hex(b) -> str:
    return b.hex() if isinstance(b, (bytes, bytearray)) else str(b)


def fetch_task_events(call, page: int = 10_000, warn: bool = True) -> List[dict]:
    """Fetch the FULL task-event ring via offset pagination.

    `call` is any callable(method, payload) -> reply dict. Replaces the
    old single `limit=100_000` fetch that silently truncated; when the
    GCS reports evicted events ("dropped"), a warning lands on stderr so
    truncated history is never mistaken for complete history.
    """
    events: List[dict] = []
    offset = 0
    dropped = 0
    while True:
        r = call("list_task_events", {"offset": offset, "limit": page})
        evs = r.get("events", [])
        events.extend(evs)
        dropped = r.get("dropped", 0)
        total = r.get("total")
        if total is None:
            break  # pre-pagination server: one tail page is all there is
        offset += len(evs)
        if not evs or offset >= total:
            break
    if warn and dropped:
        print(
            f"warning: GCS task-event ring evicted {dropped} old events; "
            "timeline/trace history is incomplete",
            file=sys.stderr,
        )
    return events


class StateApiClient:
    """Dial the GCS directly (or reuse the connected driver's session)."""

    def __init__(self, address: Optional[str] = None):
        self._own_io: Optional[EventLoopThread] = None
        self._conn = None
        client = worker_mod.get_client_or_none()
        if address is None and client is not None and getattr(client, "gcs", None):
            self._loop = client.loop
            self._conn = client.gcs
        else:
            if address is None:
                address = os.environ.get("RT_GCS_ADDR")
            if address is None:
                raise RuntimeError(
                    "not connected: call rt.init() or pass address='host:port'"
                )
            host, port = address.rsplit(":", 1)
            self._own_io = EventLoopThread("rt-state")
            self._loop = self._own_io.loop
            self._conn = self._run_new(connect(host, int(port)))

    def _run_new(self, coro, timeout=30.0):
        import asyncio

        return asyncio.run_coroutine_threadsafe(coro, self._loop).result(timeout)

    def call(self, method: str, payload: Dict[str, Any] | None = None):
        return self._run_new(self._conn.call(method, payload or {}))

    def close(self):
        if self._own_io is not None:
            try:
                self._run_new(self._conn.close(), timeout=5)
            except Exception:
                pass
            self._own_io.stop()
            self._own_io = None

    # -- listings --------------------------------------------------------
    def nodes(self) -> List[dict]:
        out = []
        for n in self.call("get_nodes")["nodes"]:
            out.append(
                {
                    "node_id": _hex(n["node_id"]),
                    "state": n["state"],
                    "draining": bool(n.get("draining", False)),
                    "address": f"{n['address']}:{n['port']}",
                    "is_head": n.get("is_head", False),
                    "resources_total": n.get("resources_total", {}),
                    "resources_available": n.get("resources_available", {}),
                    "labels": n.get("labels", {}),
                }
            )
        return out

    def worker_stacks(self) -> List[dict]:
        """Live thread stacks of every worker on every node (the `rt
        stack` view; reference: dashboard py-spy on-demand profiling)."""
        import asyncio

        from ray_tpu._private.protocol import connect as _connect

        out: List[dict] = []
        for n in self.call("get_nodes")["nodes"]:
            if n["state"] != "ALIVE":
                continue

            async def _collect(addr=n["address"], port=n["port"]):
                conn = await _connect(addr, port, timeout=5)
                try:
                    return await asyncio.wait_for(
                        conn.call("worker_stacks", {}), 30
                    )
                finally:
                    await conn.close()

            try:
                r = self._run_new(_collect(), timeout=40)
            except Exception as e:  # noqa: BLE001 — node unreachable
                out.append({"node_id": _hex(n["node_id"]),
                            "error": f"{type(e).__name__}: {e}"})
                continue
            for w in r["workers"]:
                w = dict(w)
                w["node_id"] = _hex(n["node_id"])
                if isinstance(w.get("worker_id"), bytes):
                    w["worker_id"] = _hex(w["worker_id"])
                out.append(w)
        return out

    def task_events(self, warn: bool = True) -> List[dict]:
        """Every event in the GCS ring (paginated; warns if truncated)."""
        return fetch_task_events(self.call, warn=warn)

    def tasks(self, limit: int = 1000) -> List[dict]:
        events = self.task_events()
        # Collapse the event log into latest-state-per-task
        # (GcsTaskManager's task view).
        tasks: Dict[bytes, dict] = {}
        for ev in events:
            if ev.get("type") == "LIFECYCLE_SPAN":
                # Phase-mark events are per-hop profiler payloads, not
                # task state transitions.
                continue
            t = tasks.setdefault(
                ev["task_id"],
                {
                    "task_id": _hex(ev["task_id"]),
                    "name": ev.get("name", ""),
                    "job_id": _hex(ev.get("job_id", b"")),
                    "type": ev.get("type", "NORMAL_TASK"),
                    "events": [],
                },
            )
            t["state"] = ev["state"]
            t["node_id"] = _hex(ev.get("node_id", b""))
            if ev.get("worker_id"):
                t["worker_id"] = _hex(ev["worker_id"])
            t["events"].append({"state": ev["state"], "ts": ev["ts"]})
        out = list(tasks.values())[-limit:]
        for t in out:
            ts = {e["state"]: e["ts"] for e in t.pop("events")}
            if "RUNNING" in ts:
                end = ts.get("FINISHED") or ts.get("FAILED")
                if end is not None:
                    t["duration_s"] = round(end - ts["RUNNING"], 6)
        return out

    def actors(self) -> List[dict]:
        out = []
        for a in self.call("list_actors")["actors"]:
            out.append(
                {
                    "actor_id": _hex(a["actor_id"]),
                    "class_name": a.get("class_name", ""),
                    "state": a.get("state", ""),
                    "name": a.get("name") or "",
                    "node_id": _hex(a.get("node_id") or b""),
                    "pid": a.get("pid"),
                    "restarts": a.get("restarts_used", 0),
                }
            )
        return out

    def objects(self, limit: int = 10_000) -> List[dict]:
        out = []
        for o in self.call("list_objects", {"limit": limit})["objects"]:
            out.append(
                {
                    "object_id": _hex(o["object_id"]),
                    "size": o["size"],
                    "locations": [_hex(n) for n in o["nodes"]],
                }
            )
        return out

    def jobs(self) -> List[dict]:
        return [
            {**j, "job_id": _hex(j.get("job_id", b""))}
            for j in self.call("list_jobs")["jobs"]
        ]

    def placement_groups(self) -> List[dict]:
        out = []
        for pg in self.call("list_placement_groups")["pgs"]:
            out.append(
                {
                    "pg_id": _hex(pg["pg_id"]),
                    "name": pg.get("name", ""),
                    "state": pg["state"],
                    "strategy": pg["strategy"],
                    "bundles": pg["bundles"],
                    "bundle_nodes": [
                        _hex(n) if n else None for n in pg.get("bundle_nodes", [])
                    ],
                }
            )
        return out

    def workers(self) -> List[dict]:
        """Fan out to every raylet for its worker pool state."""
        out = []
        for n in self.call("get_nodes")["nodes"]:
            if n["state"] != "ALIVE":
                continue
            try:
                conn = self._run_new(connect(n["address"], n["port"]))
                info = self._run_new(conn.call("get_info", {}))
                self._run_new(conn.close(), timeout=5)
            except Exception:
                continue
            for w in info.get("workers", []):
                out.append(
                    {
                        "worker_id": _hex(w["worker_id"]),
                        "node_id": _hex(n["node_id"]),
                        "pid": w.get("pid"),
                        "idle": w.get("idle"),
                        "actor_id": _hex(w["actor_id"]) if w.get("actor_id") else None,
                    }
                )
        return out

    def timeline(self, lifecycle: bool = False) -> List[dict]:
        """Chrome-trace events (ray timeline analog,
        _private/profiling.py:124 chrome_tracing_dump). With
        lifecycle=True, sampled tasks' control-plane phase marks
        (LIFECYCLE_SPAN events) become their own rows — one lane per
        hop (client/raylet/worker) under the emitting node."""
        events = self.task_events()
        spans: Dict[bytes, dict] = {}
        trace: List[dict] = []
        for ev in events:
            key = ev["task_id"]
            if ev.get("type") == "LIFECYCLE_SPAN":
                if not lifecycle:
                    continue
                extra = ev.get("extra") or {}
                hop = extra.get("hop", "?")
                for phase, mark in (extra.get("phases") or {}).items():
                    try:
                        start, dur = float(mark[0]), float(mark[1])
                    except (TypeError, ValueError, IndexError):
                        continue
                    trace.append(
                        {
                            "name": phase,
                            "cat": "lifecycle",
                            "ph": "X",
                            "ts": start * 1e6,
                            "dur": dur * 1e6,
                            "pid": "node:" + _hex(ev.get("node_id", b""))[:8],
                            "tid": f"lifecycle:{hop}",
                            "args": {
                                "task_id": _hex(key),
                                "task": ev.get("name", ""),
                                "hop": hop,
                            },
                        }
                    )
                continue
            if ev["state"] == "RUNNING":
                spans[key] = ev
            elif ev["state"] in ("FINISHED", "FAILED") and key in spans:
                start = spans.pop(key)
                trace.append(
                    {
                        "name": ev.get("name") or _hex(key)[:8],
                        "cat": ev.get("type", "NORMAL_TASK").lower(),
                        "ph": "X",
                        "ts": start["ts"] * 1e6,
                        "dur": max(0.0, (ev["ts"] - start["ts"]) * 1e6),
                        "pid": "node:" + _hex(ev.get("node_id", b""))[:8],
                        "tid": "worker:" + _hex(ev.get("worker_id", b""))[:8],
                        "args": {"state": ev["state"]},
                    }
                )
        return trace


def _with_client(fn):
    def wrapper(*args, address: Optional[str] = None, **kwargs):
        client = StateApiClient(address)
        try:
            return fn(client, *args, **kwargs)
        finally:
            client.close()

    wrapper.__name__ = fn.__name__
    return wrapper


@_with_client
def list_nodes(c):
    return c.nodes()


@_with_client
def list_tasks(c, limit: int = 1000):
    return c.tasks(limit)


@_with_client
def list_actors(c):
    return c.actors()


@_with_client
def list_objects(c, limit: int = 10_000):
    return c.objects(limit)


@_with_client
def list_jobs(c):
    return c.jobs()


@_with_client
def list_placement_groups(c):
    return c.placement_groups()


@_with_client
def list_workers(c):
    return c.workers()


@_with_client
def get_timeline(c, lifecycle: bool = False):
    return c.timeline(lifecycle=lifecycle)


@_with_client
def get_worker_stacks(c):
    return c.worker_stacks()


@_with_client
def summarize_tasks(c):
    """`ray summary tasks` analog: counts by (name, state)."""
    summary: Dict[str, Dict[str, int]] = {}
    for t in c.tasks(limit=100_000):
        by_state = summary.setdefault(t["name"] or "<anonymous>", {})
        by_state[t.get("state", "?")] = by_state.get(t.get("state", "?"), 0) + 1
    return summary


@_with_client
def drain_node(c, node_id: str, timeout: float = 300.0, undo: bool = False,
               poll_s: float = 1.0):
    """Graceful node drain (reference: `ray drain-node` / autoscaler.proto
    DrainNode): cordon the node so every placement path skips it, wait
    for running work to finish (resources fully returned, no queued
    demand), then remove it. undo=True lifts a cordon instead."""
    import time as _time

    try:
        nid = bytes.fromhex(node_id)
    except ValueError:
        return {"ok": False,
                "error": f"invalid node id {node_id!r} (expected hex)"}
    if undo:
        return c.call("cordon_node", {"node_id": nid, "undo": True})
    r = c.call("cordon_node", {"node_id": nid})
    if not r.get("ok"):
        return r
    deadline = _time.monotonic() + timeout
    st: dict = {}
    idle_streak = 0
    # Two consecutive idle polls ≥0.6s apart must both pass: the GCS
    # availability view lags the raylet by one heartbeat (~0.5s), so a
    # single idle reading can predate a just-dispatched task or the
    # raylet even learning of the cordon.
    gap = max(poll_s, 0.6)
    while _time.monotonic() < deadline:
        st = c.call("node_drain_status", {"node_id": nid})
        if not st.get("ok"):
            return st
        if not st.get("draining"):
            # Cordon lifted mid-drain (rt drain --undo elsewhere, or a
            # GCS restart dropped the volatile flag): abort rather than
            # removing a node that is accepting work again.
            return {"ok": False, "error": "cordon was lifted mid-drain"}
        if st.get("state") != "ALIVE":
            # Died (or was removed) mid-drain: nothing left to wait for.
            return {"ok": True, "drained": True, "already_dead": True}
        if st.get("idle"):
            idle_streak += 1
            if idle_streak >= 2:
                c.call("drain_node", {"node_id": nid})
                return {"ok": True, "drained": True}
        else:
            idle_streak = 0
        _time.sleep(gap)
    return {"ok": False, "error": "drain timed out (node still busy; "
            "cordon stays in effect)", "status": st}


def _dial_raylet(c, node_hex, method, payload, timeout=30,
                 stop_on_ok=False):
    """Call one raylet (or every ALIVE one when node_hex is None; a hex
    PREFIX selects, so ids copied from truncated CLI output work).
    Returns [(node_hex, reply-or-error-dict)]; with stop_on_ok the dials
    stop at the first ok reply (no redundant transfers, and unreachable
    later nodes cost nothing). Raises if a requested node matches no
    ALIVE node."""
    import asyncio

    from ray_tpu._private.protocol import connect as _connect

    out = []
    matched = False
    for n in c.call("get_nodes")["nodes"]:
        nid = _hex(n["node_id"])
        if n["state"] != "ALIVE":
            continue
        if node_hex is not None and not nid.startswith(node_hex):
            continue
        matched = True

        async def _one(addr=n["address"], port=n["port"]):
            conn = await _connect(addr, port, timeout=5)
            try:
                return await asyncio.wait_for(
                    conn.call(method, payload), timeout
                )
            finally:
                await conn.close()

        try:
            reply = c._run_new(_one(), timeout=timeout + 10)
        except Exception as e:  # noqa: BLE001 — node unreachable
            reply = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        out.append((nid, reply))
        if stop_on_ok and reply.get("ok"):
            break
    if node_hex is not None and not matched:
        raise ValueError(f"no ALIVE node matches id prefix {node_hex!r}")
    return out


@_with_client
def list_logs(c, node_id: str = None):
    """Per-node session log files (reference: `ray logs` listing)."""
    out = []
    for nid, r in _dial_raylet(c, node_id, "list_logs", {}):
        for entry in r.get("logs", []):
            out.append({"node_id": nid, **entry})
        if "error" in r:
            out.append({"node_id": nid, "error": r["error"]})
    return out


@_with_client
def get_log(c, filename: str, node_id: str = None,
            tail_bytes: int = 64 * 1024) -> str:
    """Tail of one log file (reference: `ray logs <file>`); node_id
    defaults to the first ALIVE node holding it."""
    errors = []
    for nid, r in _dial_raylet(
        c, node_id, "read_log", {"name": filename, "tail_bytes": tail_bytes},
        stop_on_ok=True,
    ):
        if r.get("ok"):
            return r["data"].decode(errors="replace")
        errors.append(f"{nid}: {r.get('error')}")
    raise FileNotFoundError(
        f"log {filename!r} not found on any node ({'; '.join(errors)})"
    )
