"""joblib backend: run scikit-learn style `Parallel` work on the cluster.

Analog of the reference's ``ray.util.joblib`` (register_ray +
ray_backend.py): registers an "rt" backend so

    from ray_tpu.util.joblib import register_rt
    register_rt()
    with joblib.parallel_backend("rt"):
        Parallel(n_jobs=8)(delayed(f)(i) for i in range(100))

executes batches as cluster tasks.
"""

from __future__ import annotations

from joblib._parallel_backends import ParallelBackendBase
from joblib.parallel import register_parallel_backend

import ray_tpu as rt


@rt.remote
def _run_batch(batch):
    return batch()


class RTBackend(ParallelBackendBase):
    """Dispatch joblib batches as ray_tpu tasks."""

    supports_timeout = True
    uses_threads = False
    supports_sharedmem = False

    def configure(self, n_jobs=1, parallel=None, prefer=None, require=None,
                  **kwargs):
        if not rt.is_initialized():
            rt.init()
        self.parallel = parallel
        return self.effective_n_jobs(n_jobs)

    def effective_n_jobs(self, n_jobs):
        if n_jobs == 0:
            raise ValueError("n_jobs == 0 is not a valid specification")
        if n_jobs < 0:
            # Cluster-wide CPU count plays the role of cpu_count().
            try:
                from ray_tpu.util.state import list_nodes

                total = sum(
                    int(n["resources_total"].get("CPU", 0))
                    for n in list_nodes()
                    if n["state"] == "ALIVE"
                )
                return max(1, total)
            except Exception:
                return 4
        return n_jobs

    def apply_async(self, func, callback=None):
        ref = _run_batch.remote(func)
        return _RTFuture(ref, callback)

    # joblib >= 1.3 calls submit(); apply_async remains the legacy alias.
    def submit(self, func, callback=None):
        return self.apply_async(func, callback)

    def abort_everything(self, ensure_ready=True):
        if ensure_ready:
            self.configure(n_jobs=self.parallel.n_jobs, parallel=self.parallel)


class _RTFuture:
    def __init__(self, ref, callback):
        self._ref = ref
        self._callback = callback
        if callback is not None:
            import threading

            def waiter():
                try:
                    result = rt.get(ref)
                except Exception:
                    return
                callback(result)

            threading.Thread(target=waiter, daemon=True).start()

    def get(self, timeout=None):
        return rt.get(self._ref, timeout=timeout)


def register_rt():
    register_parallel_backend("rt", RTBackend)
