"""Unified memory accounting: device HBM + host object store.

A TPU job's memory lives in two pools the runtime previously accounted
separately or not at all: HBM (jax arrays on device — invisible to the
object store) and plasma (host shared memory — invisible to jax). OOMs
on either side get diagnosed by the other side's numbers unless someone
joins them. This module is the join:

  * device_memory() — this process's per-device view: live array bytes
    (summed over `jax.live_arrays()` shards per device) plus the
    allocator's own numbers (`device.memory_stats()`: bytes_in_use /
    peak / limit) where the backend provides them (TPU/GPU yes, CPU no).
  * MemoryAccountant / sample_once() — publish that view as node+device
    tagged gauges through the existing metrics stream, so the driver,
    `rt memory --devices`, `rt top`, and Grafana all read one source.
  * memory_summary() — the cluster-unified view assembled from the GCS:
    HBM gauges from every sampling process, per-node plasma usage from
    the raylet's `rt_raylet_store_used_bytes` gauge, and the object
    listing's primary-copy totals from the state API.

Reference analog: `ray memory` / memory_utils.py group object stats per
node; the HBM half has no reference analog (the reference has no device
accounting at all) — the shape follows jm.live_arrays-based profilers.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ray_tpu.util import metrics as _metrics

_gauges_lock = threading.Lock()
_gauges: Optional[Dict[str, Any]] = None


def _hbm_gauges() -> Dict[str, Any]:
    global _gauges
    with _gauges_lock:
        if _gauges is None:
            _gauges = {
                "live": _metrics.get_or_create(
                    _metrics.Gauge, "device_hbm_live_bytes",
                    "Bytes of live jax arrays resident per device.",
                    tag_keys=("node", "device"),
                ),
                "arrays": _metrics.get_or_create(
                    _metrics.Gauge, "device_hbm_live_arrays",
                    "Count of live jax arrays per device.",
                    tag_keys=("node", "device"),
                ),
                "in_use": _metrics.get_or_create(
                    _metrics.Gauge, "device_hbm_in_use_bytes",
                    "Allocator bytes_in_use per device (memory_stats; "
                    "absent on backends without allocator stats).",
                    tag_keys=("node", "device"),
                ),
                "limit": _metrics.get_or_create(
                    _metrics.Gauge, "device_hbm_limit_bytes",
                    "Allocator bytes_limit per device (memory_stats).",
                    tag_keys=("node", "device"),
                ),
            }
        return _gauges


def _node_tag() -> str:
    from ray_tpu._private import worker as worker_mod

    client = worker_mod.get_client_or_none()
    if client is not None and getattr(client, "node_id", None):
        return client.node_id.hex()[:12]
    return "-"


def device_memory() -> List[Dict[str, Any]]:
    """Per-device memory view of THIS process: one dict per addressable
    jax device with live-array accounting and (when the backend exposes
    it) allocator stats. Empty list when jax has no backend."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — no jax backend in this process
        return []
    live_bytes: Dict[Any, int] = {}
    live_count: Dict[Any, int] = {}
    for arr in jax.live_arrays():
        try:
            for shard in arr.addressable_shards:
                d = shard.device
                nbytes = getattr(shard.data, "nbytes", 0)
                live_bytes[d] = live_bytes.get(d, 0) + int(nbytes)
                live_count[d] = live_count.get(d, 0) + 1
        except Exception:  # noqa: BLE001 — deleted/donated array mid-walk
            continue
    out = []
    for d in devices:
        entry: Dict[str, Any] = {
            "device": str(d),
            "kind": getattr(d, "device_kind", "?"),
            "live_bytes": live_bytes.get(d, 0),
            "live_arrays": live_count.get(d, 0),
        }
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — backend without stats
            stats = None
        if stats:
            for src, dst in (("bytes_in_use", "bytes_in_use"),
                             ("peak_bytes_in_use", "peak_bytes_in_use"),
                             ("bytes_limit", "bytes_limit")):
                if src in stats:
                    entry[dst] = int(stats[src])
        out.append(entry)
    return out


def sample_once() -> List[Dict[str, Any]]:
    """Take one device-memory sample and publish it as gauges; returns
    the sample. Call from any process holding device arrays (training
    workers, serving engines) — each publishes under its own node tag."""
    sample = device_memory()
    if not sample:
        return sample
    g = _hbm_gauges()
    node = _node_tag()
    for entry in sample:
        tags = {"node": node, "device": entry["device"]}
        g["live"].set(float(entry["live_bytes"]), tags=tags)
        g["arrays"].set(float(entry["live_arrays"]), tags=tags)
        if "bytes_in_use" in entry:
            g["in_use"].set(float(entry["bytes_in_use"]), tags=tags)
        if "bytes_limit" in entry:
            g["limit"].set(float(entry["bytes_limit"]), tags=tags)
    return sample


def _sample_loop(stop_event: threading.Event, interval_s: float) -> None:
    """Sampler-thread body (module function per RT006: communicates with
    the owner only through the stop event; gauges are process-global)."""
    while not stop_event.wait(interval_s):
        try:
            sample_once()
        except Exception:  # noqa: BLE001 — sampling must never kill the host  # rtlint: disable=RT007
            pass


class MemoryAccountant:
    """Background HBM sampler: publishes this process's device gauges
    every `interval_s` until stop() (or GC — daemon thread). One per
    process is enough; the gauges are process-global."""

    def __init__(self, interval_s: float = 5.0):
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=_sample_loop, args=(self._stop, interval_s),
            name="rt-mem-accountant", daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


def _snapshot_metrics(address: Optional[str]) -> Dict[str, Dict]:
    """{name: {tags_tuple: value}} for the gauges memory_summary reads."""
    from ray_tpu.util.state.api import StateApiClient

    client = StateApiClient(address)
    try:
        snapshot = client.call("metrics_snapshot")["metrics"]
    finally:
        client.close()
    out: Dict[str, Dict] = {}
    for m in snapshot:
        series = {}
        for tags, val in m["series"]:
            series[tuple(sorted((k, v) for k, v in tags))] = val
        out[m["name"]] = series
    return out


def memory_summary(address: Optional[str] = None) -> Dict[str, Any]:
    """Cluster-unified memory view: every sampled device's HBM gauges,
    per-node object-store usage, and the object table's primary-copy
    totals — one dict, one source for CLI/dashboard rendering."""
    from ray_tpu.util.state import api as state_api

    snap = _snapshot_metrics(address)

    devices: Dict[tuple, Dict[str, Any]] = {}
    for metric, field in (
        ("device_hbm_live_bytes", "live_bytes"),
        ("device_hbm_live_arrays", "live_arrays"),
        ("device_hbm_in_use_bytes", "bytes_in_use"),
        ("device_hbm_limit_bytes", "bytes_limit"),
    ):
        for tags, val in snap.get(metric, {}).items():
            td = dict(tags)
            key = (td.get("node", "-"), td.get("device", "?"))
            d = devices.setdefault(
                key, {"node": key[0], "device": key[1]}
            )
            d[field] = int(val)

    per_node_store: Dict[str, Dict[str, int]] = {}
    for tags, val in snap.get("rt_raylet_store_used_bytes", {}).items():
        node = dict(tags).get("node", "-")
        per_node_store.setdefault(node, {})["used_bytes"] = int(val)
    for tags, val in snap.get("rt_raylet_store_objects", {}).items():
        node = dict(tags).get("node", "-")
        per_node_store.setdefault(node, {})["num_objects"] = int(val)

    objects = state_api.list_objects(address=address)
    obj_bytes = sum(o["size"] or 0 for o in objects)

    return {
        "devices": sorted(
            devices.values(), key=lambda d: (d["node"], d["device"])
        ),
        "hbm_live_bytes": sum(d.get("live_bytes", 0)
                              for d in devices.values()),
        "object_store": {
            "per_node": per_node_store,
            "used_bytes": sum(v.get("used_bytes", 0)
                              for v in per_node_store.values()),
            "num_objects": sum(v.get("num_objects", 0)
                               for v in per_node_store.values()),
        },
        "objects": {"count": len(objects), "bytes": obj_bytes},
    }
