"""Structured event log: JSON-line files per component.

Analog of the reference's RAY_EVENT macros (src/ray/util/event.h), which
write structured JSON event files the dashboard's event module tails.
Here any component calls `record_event(...)`; events append to
`<event dir>/events_<source>.log` as one JSON object per line and the
dashboard surfaces the merged tail at /api/events.

Event dir: $RT_EVENT_DIR, else $TMPDIR/ray_tpu/events. Writes are
append-only + line-atomic (single write syscall under PIPE_BUF for
typical event sizes), so concurrent processes can share a file.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR")


def event_dir() -> str:
    d = os.environ.get("RT_EVENT_DIR") or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "ray_tpu", "events"
    )
    os.makedirs(d, exist_ok=True)
    return d


#: Rotate an event file once it passes this size (one .1 generation kept).
ROTATE_BYTES = 4 * 1024 * 1024
#: Bound how much of each file a reader loads (tail window).
TAIL_BYTES = 256 * 1024


def record_event(source: str, message: str, severity: str = "INFO",
                 **fields: Any) -> None:
    """Append one structured event; never raises (observability must not
    take down the component reporting it)."""
    try:
        entry = {
            "timestamp": time.time(),
            "source": source,
            "severity": severity if severity in SEVERITIES else "INFO",
            "message": message,
            "pid": os.getpid(),
            **fields,
        }
        path = os.path.join(event_dir(), f"events_{source}.log")
        try:
            if os.path.getsize(path) >= ROTATE_BYTES:
                os.replace(path, path + ".1")
        except OSError:
            pass
        with open(path, "a") as f:
            f.write(json.dumps(entry, default=str) + "\n")
    except Exception:  # noqa: BLE001 — best-effort by contract
        pass


def read_events(limit: int = 200, source: str = "") -> List[Dict]:
    """Merged most-recent events across components (dashboard backend)."""
    out: List[Dict] = []
    try:
        d = event_dir()
        for name in os.listdir(d):
            if not name.startswith("events_") or not name.endswith(".log"):
                continue
            if source and name != f"events_{source}.log":
                continue
            path = os.path.join(d, name)
            try:
                with open(path, "rb") as f:
                    # Bounded tail window: the dashboard polls this, so
                    # it must never read a whole (rotated-capped) file.
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    f.seek(max(0, size - TAIL_BYTES))
                    chunk = f.read().decode(errors="replace")
                lines = chunk.splitlines()
                if size > TAIL_BYTES and lines:
                    lines = lines[1:]  # first line may be torn
                lines = lines[-limit:]
            except OSError:
                continue
            for line in lines:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except Exception:  # noqa: BLE001
        return out
    out.sort(key=lambda e: e.get("timestamp", 0))
    return out[-limit:]
