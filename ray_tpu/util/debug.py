"""Debug utilities (reference: ray.util.debug — log_once/disable_log_once)."""

from __future__ import annotations

import time
from typing import Set

_logged: Set[str] = set()
_disabled = False
_periodic: dict = {}


def log_once(key: str) -> bool:
    """True the FIRST time this key is seen (per process) — gate warnings
    that would otherwise spam per-task (reference: util/debug.py log_once)."""
    if _disabled:
        return False
    if key in _logged:
        return False
    _logged.add(key)
    return True


def log_every_n_seconds(key: str, period_s: float = 60.0) -> bool:
    """True at most once per `period_s` for this key."""
    if _disabled:
        return False
    now = time.monotonic()
    last = _periodic.get(key)
    if last is not None and now - last < period_s:
        return False
    _periodic[key] = now
    return True


def disable_log_once_globally() -> None:
    global _disabled
    _disabled = True


def enable_periodic_logging() -> None:
    global _disabled
    _disabled = False


def reset_log_once(key: str) -> None:
    _logged.discard(key)
    _periodic.pop(key, None)
