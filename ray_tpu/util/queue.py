"""Distributed FIFO queue backed by an actor.

Analog of python/ray/util/queue.py in the reference.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import ray_tpu as rt


class Empty(Exception):
    pass


class Full(Exception):
    pass


@rt.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.items: List[Any] = []

    def put(self, item) -> bool:
        if self.maxsize > 0 and len(self.items) >= self.maxsize:
            return False
        self.items.append(item)
        return True

    def get(self) -> tuple:
        if not self.items:
            return False, None
        return True, self.items.pop(0)

    def qsize(self) -> int:
        return len(self.items)

    def empty(self) -> bool:
        return not self.items

    def full(self) -> bool:
        return self.maxsize > 0 and len(self.items) >= self.maxsize


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = actor_options or {}
        self.actor = _QueueActor.options(**opts).remote(maxsize)

    def put(self, item, block: bool = True, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if rt.get(self.actor.put.remote(item)):
                return
            if not block or (deadline and time.monotonic() > deadline):
                raise Full()
            time.sleep(0.01)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = rt.get(self.actor.get.remote())
            if ok:
                return item
            if not block or (deadline and time.monotonic() > deadline):
                raise Empty()
            time.sleep(0.01)

    def put_nowait(self, item):
        self.put(item, block=False)

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        return rt.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return rt.get(self.actor.empty.remote())

    def full(self) -> bool:
        return rt.get(self.actor.full.remote())

    def shutdown(self):
        rt.kill(self.actor)
