"""ActorPool: load-balance work over a fixed set of actors.

Analog of python/ray/util/actor_pool.py in the reference.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu as rt


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._pending = []

    def submit(self, fn: Callable, value):
        if not self._idle:
            self._pending.append((fn, value))
            return
        actor = self._idle.pop()
        ref = fn(actor, value)
        self._future_to_actor[ref] = actor

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending)

    def get_next(self, timeout=None):
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        ready, _ = rt.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout
        )
        if not ready:
            raise TimeoutError("get_next timed out")
        ref = ready[0]
        actor = self._future_to_actor.pop(ref)
        self._idle.append(actor)
        if self._pending:
            fn, value = self._pending.pop(0)
            self.submit(fn, value)
        return rt.get(ref)

    def map(self, fn: Callable, values: Iterable):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable):
        yield from self.map(fn, values)
