"""Placement groups: atomic gang reservation of resource bundles.

Analog of python/ray/util/placement_group.py (:41 PlacementGroup, :146
placement_group()) backed by the GCS two-phase bundle reservation
(gcs/gcs_server/gcs_placement_group_scheduler.h; strategies from
bundle_scheduling_policy.cc). On TPU clusters a bundle is typically one
whole host of a pod slice, so STRICT_SPREAD of N bundles == gang-reserve an
N-host slice.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.ids import PlacementGroupID
from ray_tpu.exceptions import PlacementGroupSchedulingError

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundles = bundles

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def ready(self, timeout: float = 30.0) -> bool:
        """Block until the group is reserved (reference: pg.ready())."""
        client = worker_mod.get_client()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = client._run(
                client.gcs.call("get_placement_group", {"pg_id": self.id.binary()})
            )["pg"]
            if info is None:
                return False
            if info["state"] == "CREATED":
                return True
            if info["state"] in ("INFEASIBLE", "REMOVED"):
                raise PlacementGroupSchedulingError(
                    f"placement group {self.id.hex()} is {info['state']}"
                )
            time.sleep(0.05)
        return False

    def bundle_node_ids(self) -> List[bytes]:
        client = worker_mod.get_client()
        info = client._run(
            client.gcs.call("get_placement_group", {"pg_id": self.id.binary()})
        )["pg"]
        return info["bundle_nodes"] if info else []

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles))


@dataclass
class PlacementGroupConfig:
    """Declarative gang spec: bundles plus the scheduling tier.

    `priority` is the preemption class — when this gang cannot place, the
    GCS may reclaim chips from strictly lower-priority gangs (and this
    gang may in turn be evicted by higher tiers). 0 is the default
    best-effort tier.
    """

    bundles: List[Dict[str, float]] = field(default_factory=list)
    strategy: str = "PACK"
    name: str = ""
    priority: int = 0

    def create(self) -> PlacementGroup:
        return placement_group(
            self.bundles,
            strategy=self.strategy,
            name=self.name,
            priority=self.priority,
        )


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    priority: int = 0,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    client = worker_mod.get_client()
    pg_id = PlacementGroupID.from_random()
    resp = client._run(
        client.gcs.call(
            "create_placement_group",
            {
                "pg_id": pg_id.binary(),
                "bundles": [dict(b) for b in bundles],
                "strategy": strategy,
                "name": name,
                "priority": int(priority),
            },
        )
    )
    pg = PlacementGroup(pg_id, [dict(b) for b in bundles])
    if not resp.get("ok"):
        # Reservation is retried by ready(); surface infeasibility there.
        pass
    return pg


def remove_placement_group(pg: PlacementGroup):
    client = worker_mod.get_client()
    client._run(
        client.gcs.call("remove_placement_group", {"pg_id": pg.id.binary()})
    )


def placement_group_state(pg: PlacementGroup) -> Optional[str]:
    """Current GCS state of the group (None once it is forgotten)."""
    client = worker_mod.get_client()
    info = client._run(
        client.gcs.call("get_placement_group", {"pg_id": pg.id.binary()})
    )["pg"]
    return info["state"] if info else None


def release_placement_group_bundles(pg: PlacementGroup, indices: List[int]):
    """Give individual bundles of a CREATED group back to the cluster
    (elastic shrink): their chips are credited and, when the release
    satisfies a partial-reclamation drain, the GCS records a *resize
    obligation* so the gang can reclaim exactly these bundles later."""
    client = worker_mod.get_client()
    resp = client._run(
        client.gcs.call(
            "release_pg_bundles",
            {"pg_id": pg.id.binary(), "indices": [int(i) for i in indices]},
        )
    )
    if not resp.get("ok"):
        raise PlacementGroupSchedulingError(
            f"bundle release failed for pg {pg.id.hex()}: "
            f"{resp.get('error', 'unknown error')}"
        )


def reserve_placement_group_bundles(pg: PlacementGroup, indices: List[int]):
    """Re-reserve previously released bundles (elastic grow-back).
    Fails while the chips are fenced for another claimant or occupied."""
    client = worker_mod.get_client()
    resp = client._run(
        client.gcs.call(
            "reserve_pg_bundles",
            {"pg_id": pg.id.binary(), "indices": [int(i) for i in indices]},
        )
    )
    if not resp.get("ok"):
        raise PlacementGroupSchedulingError(
            f"bundle re-reserve failed for pg {pg.id.hex()}: "
            f"{resp.get('error', 'unknown error')}"
        )


def placement_group_resize_state(pg: PlacementGroup) -> Dict:
    """Resize obligations recorded against this group: the bundles it
    gave up to a partial reclamation and whether the claimant has
    released them (state \"lifted\" — the fence-lift signal the trainer's
    grow-back path polls)."""
    client = worker_mod.get_client()
    return client._run(
        client.gcs.call("get_resize_state", {"pg_id": pg.id.binary()})
    )
