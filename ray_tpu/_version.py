"""Version of ray_tpu.

Reference analog: python/ray/_version.py (version string consumed by
python/ray/__init__.py:82).
"""

version = "0.1.0"
