"""The autoscaler control loop.

Analog of the reference's ``StandardAutoscaler``
(autoscaler/_private/autoscaler.py:171) driven by the head-node Monitor
(_private/monitor.py:126), with the demand binpacking of
resource_demand_scheduler.py: unmet task demand bundles are packed onto
node types to decide scale-up; idle provider nodes past the timeout are
drained and terminated for scale-down.

TPU specifics: a node type with ``slice_hosts`` N scales in whole slices —
N hosts are created (and terminated) together, because a partial TPU slice
cannot run SPMD programs.

Config shape (mirrors the reference's YAML ``available_node_types``):

    {
      "node_types": {
        "cpu-worker": {"resources": {"CPU": 4}, "min_workers": 0,
                        "max_workers": 10},
        "v5e-slice":  {"resources": {"TPU": 4}, "slice_hosts": 4,
                        "max_workers": 2},   # max 2 slices = 8 hosts
      },
      "idle_timeout_s": 60,
    }
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.node import EventLoopThread
from ray_tpu._private.protocol import connect
from ray_tpu.autoscaler.node_provider import NodeProvider


def _fits(bundle: Dict[str, float], free: Dict[str, float]) -> bool:
    return all(free.get(k, 0) + 1e-9 >= v for k, v in bundle.items())


def _claim(bundle: Dict[str, float], free: Dict[str, float]):
    for k, v in bundle.items():
        free[k] = free.get(k, 0) - v


class StandardAutoscaler:
    def __init__(
        self,
        config: Dict,
        provider: NodeProvider,
        gcs_address: str,
        io: Optional[EventLoopThread] = None,
    ):
        self.config = config
        self.provider = provider
        self.node_types: Dict[str, dict] = config.get("node_types", {})
        self.idle_timeout_s = config.get("idle_timeout_s", 60.0)
        self._own_io = io is None
        self.io = io or EventLoopThread("rt-autoscaler")
        host, port = gcs_address.rsplit(":", 1)
        self.gcs = self.io.run(connect(host, int(port)))
        self._idle_since: Dict[str, float] = {}  # provider id -> ts
        # Launched but not yet registered: count toward limits so one burst
        # of updates doesn't over-launch.
        self._starting: Dict[str, List[str]] = {t: [] for t in self.node_types}
        # Slice membership for slice_hosts>1 types: type -> list of pid
        # groups created together. Scale-down is slice-atomic: a group is
        # terminated only when every host in it has idled past the timeout.
        self._slice_groups: Dict[str, List[List[str]]] = {}
        self._warned_unplaceable: set = set()
        self._warned_untracked_slice: set = set()

    def close(self):
        try:
            self.io.run(self.gcs.close(), timeout=5)
        except Exception:
            pass
        if self._own_io:
            self.io.stop()

    # -- state ------------------------------------------------------------
    def _cluster_nodes(self) -> List[dict]:
        return self.io.run(self.gcs.call("get_nodes", {}))["nodes"]

    def _provider_view(self):
        """provider id -> {type, node_id(hex or None)}; prunes _starting."""
        view = {}
        for pid in self.provider.non_terminated_nodes():
            tags = self.provider.node_tags(pid)
            view[pid] = {
                "type": tags.get("rt-node-type"),
                "node_id": tags.get("rt-node-id"),
            }
        for t, pids in self._starting.items():
            self._starting[t] = [p for p in pids if p in view]
        return view

    def _count_by_type(self, view) -> Dict[str, int]:
        counts = {t: 0 for t in self.node_types}
        for info in view.values():
            if info["type"] in counts:
                counts[info["type"]] += 1
        return counts

    # -- the decision step ------------------------------------------------
    def update(self) -> Dict[str, int]:
        """One reconcile pass. Returns {node_type: hosts_launched}."""
        nodes = self._cluster_nodes()
        alive = [n for n in nodes if n["state"] == "ALIVE"]
        view = self._provider_view()
        launched: Dict[str, int] = {}

        # ---- scale up: binpack unmet demand --------------------------
        free_per_node = [dict(n.get("resources_available", {})) for n in alive]
        unmet: List[Dict[str, float]] = []
        for n in alive:
            for bundle in n.get("demand_bundles", []) or []:
                placed = False
                for free in free_per_node:
                    if _fits(bundle, free):
                        _claim(bundle, free)
                        placed = True
                        break
                if not placed:
                    unmet.append(bundle)

        if unmet:
            counts = self._count_by_type(view)
            # Pending capacity from still-starting nodes absorbs demand.
            pending_free = []
            for t, pids in self._starting.items():
                spec = self.node_types.get(t, {})
                for pid in pids:
                    if view.get(pid, {}).get("node_id") is None:
                        pending_free.append(dict(spec.get("resources", {})))
            to_launch: Dict[str, int] = {}
            for bundle in unmet:
                placed = False
                for free in pending_free:
                    if _fits(bundle, free):
                        _claim(bundle, free)
                        placed = True
                        break
                if placed:
                    continue
                for t, spec in self.node_types.items():
                    res = spec.get("resources", {})
                    if not _fits(bundle, dict(res)):
                        continue
                    slice_hosts = spec.get("slice_hosts", 1)
                    in_use = counts.get(t, 0) + to_launch.get(t, 0) * slice_hosts
                    max_hosts = spec.get("max_workers", 2**31) * slice_hosts
                    if in_use + slice_hosts > max_hosts:
                        continue
                    to_launch[t] = to_launch.get(t, 0) + 1
                    free = dict(res)
                    _claim(bundle, free)
                    pending_free.append(free)
                    for _ in range(slice_hosts - 1):
                        pending_free.append(dict(res))
                    placed = True
                    break
                if not placed:
                    key = tuple(sorted(bundle.items()))
                    if key not in self._warned_unplaceable:
                        self._warned_unplaceable.add(key)
                        import sys

                        print(
                            f"[ray_tpu autoscaler] WARNING: demand {bundle} "
                            "fits no configured node type (or all types are "
                            "at max_workers); the task will stay pending.",
                            file=sys.stderr, flush=True,
                        )
            for t, groups in to_launch.items():
                spec = self.node_types[t]
                slice_hosts = spec.get("slice_hosts", 1)
                n_hosts = groups * slice_hosts
                pids = self.provider.create_node(t, spec, n_hosts)
                self._starting.setdefault(t, []).extend(pids)
                self._record_slices(t, slice_hosts, pids)
                launched[t] = launched.get(t, 0) + n_hosts

        # ---- enforce min_workers -------------------------------------
        counts = self._count_by_type(self._provider_view())
        for t, spec in self.node_types.items():
            slice_hosts = spec.get("slice_hosts", 1)
            min_hosts = spec.get("min_workers", 0) * slice_hosts
            if counts.get(t, 0) < min_hosts:
                # Round up to whole slices: a partial slice is useless.
                need = min_hosts - counts.get(t, 0)
                need = -(-need // slice_hosts) * slice_hosts
                pids = self.provider.create_node(t, spec, need)
                self._starting.setdefault(t, []).extend(pids)
                self._record_slices(t, slice_hosts, pids)
                launched[t] = launched.get(t, 0) + need

        # ---- scale down: idle past timeout ---------------------------
        by_node_id = {n["node_id"].hex() if isinstance(n["node_id"], bytes)
                      else n["node_id"]: n for n in alive}
        now = time.monotonic()
        view = self._provider_view()
        counts = self._count_by_type(view)

        def idle_expired(pid, info):
            """True once the host has been idle past the timeout."""
            node = by_node_id.get(info.get("node_id") or "")
            if node is None:
                return False  # still starting
            idle = (
                not node.get("demand_bundles")
                and node.get("resources_available") == node.get("resources_total")
            )
            if not idle:
                self._idle_since.pop(pid, None)
                return False
            first = self._idle_since.setdefault(pid, now)
            return now - first > self.idle_timeout_s

        # Single-host node types terminate host by host.
        for pid, info in view.items():
            spec = self.node_types.get(info["type"] or "", {})
            if spec.get("slice_hosts", 1) > 1:
                continue
            if (
                idle_expired(pid, info)
                and counts.get(info["type"], 0) - 1 >= spec.get("min_workers", 0)
            ):
                self._drain_and_terminate(pid, info)
                counts[info["type"]] = counts.get(info["type"], 0) - 1

        # Slice types terminate whole slices, and only when EVERY host of
        # the slice has idled past the timeout: a partial slice cannot run
        # SPMD programs, so per-host scale-down would strand capacity.
        for t, spec in self.node_types.items():
            slice_hosts = spec.get("slice_hosts", 1)
            if slice_hosts <= 1:
                continue
            min_hosts = spec.get("min_workers", 0) * slice_hosts
            for group in self._live_slice_groups(t, slice_hosts, view):
                # Evaluate EVERY host (no short-circuit): idle_expired also
                # clears a busy host's stale idle timer, and skipping that
                # reset would let a pre-busy timer expire the slice.
                statuses = [idle_expired(pid, view[pid]) for pid in group]
                if not all(statuses):
                    continue
                if counts.get(t, 0) - len(group) < min_hosts:
                    continue
                for pid in group:
                    self._drain_and_terminate(pid, view[pid])
                counts[t] = counts.get(t, 0) - len(group)
                self._slice_groups[t].remove(group)
        return launched

    # -- slice bookkeeping -------------------------------------------------
    def _record_slices(self, t: str, slice_hosts: int, pids: List[str]):
        """Remember which provider hosts were created together as slices."""
        if slice_hosts <= 1:
            return
        groups = self._slice_groups.setdefault(t, [])
        for i in range(0, len(pids), slice_hosts):
            groups.append(list(pids[i:i + slice_hosts]))

    def _live_slice_groups(self, t: str, slice_hosts: int, view) -> List[List[str]]:
        """Recorded slice groups pruned to live hosts; adopts untracked ones.

        Hosts of a slice type with no recorded group (e.g. they predate this
        autoscaler process) are chunked into slices in sorted order so they
        can still be scaled down atomically rather than leaking forever.
        """
        live = {pid for pid, info in view.items() if info["type"] == t}
        groups: List[List[str]] = []
        tracked: set = set()
        for g in self._slice_groups.get(t, []):
            g2 = [p for p in g if p in live]
            if g2:
                groups.append(g2)
                tracked.update(g2)
        untracked = sorted(live - tracked)
        if untracked:
            if t not in self._warned_untracked_slice:
                self._warned_untracked_slice.add(t)
                import sys

                print(
                    f"[ray_tpu autoscaler] WARNING: {len(untracked)} hosts of "
                    f"slice type {t!r} have no recorded slice group; adopting "
                    "them in sorted order for slice-atomic scale-down.",
                    file=sys.stderr, flush=True,
                )
            for i in range(0, len(untracked), slice_hosts):
                groups.append(untracked[i:i + slice_hosts])
        self._slice_groups[t] = groups
        return list(groups)

    def _drain_and_terminate(self, pid: str, info: dict):
        node_id = info.get("node_id")
        if node_id:
            try:
                self.io.run(
                    self.gcs.call(
                        "drain_node", {"node_id": bytes.fromhex(node_id)}
                    ),
                    timeout=10,
                )
            except Exception:
                pass
        self.provider.terminate_node(pid)
        self._idle_since.pop(pid, None)
