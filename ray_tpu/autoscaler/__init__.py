from ray_tpu.autoscaler.autoscaler import StandardAutoscaler  # noqa: F401
from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    FakeMultiNodeProvider,
    ProcessNodeProvider,
    NodeProvider,
)
from ray_tpu.autoscaler.v2 import (  # noqa: F401
    Instance,
    InstanceManager,
    Monitor,
    Reconciler,
    Scheduler,
)
