"""Node providers: how the autoscaler creates/terminates hosts.

Analog of the reference's ``NodeProvider`` ABC
(python/ray/autoscaler/node_provider.py) and the offline test provider
(autoscaler/_private/fake_multi_node/node_provider.py — "nodes" are local
processes so autoscaler logic is testable without a cloud).

TPU framing: a *node type* describes one host class; a TPU slice node type
sets ``slice_hosts`` > 1, and the provider must create/terminate those
hosts atomically — a partial slice is useless to SPMD jobs (the reference
reaches the same effect through GKE TPU node pools).
"""

from __future__ import annotations

import abc
from typing import Dict, List, Optional


class NodeProvider(abc.ABC):
    """Minimal provider surface the autoscaler drives."""

    @abc.abstractmethod
    def create_node(self, node_type: str, node_config: Dict, count: int) -> List[str]:
        """Launch `count` hosts of `node_type`; returns provider node ids."""

    @abc.abstractmethod
    def terminate_node(self, provider_node_id: str) -> None:
        ...

    @abc.abstractmethod
    def non_terminated_nodes(self) -> List[str]:
        ...

    @abc.abstractmethod
    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        """Must include "rt-node-type"; includes "rt-node-id" (hex) once
        the raylet on that host has registered."""


class FakeMultiNodeProvider(NodeProvider):
    """Boots raylets in-process against a live GCS (offline testing).

    The reference's fake provider launches local processes; here the
    cluster harness's event loop hosts extra raylet control loops, which
    is exactly how multi-node tests run (cluster_utils.Cluster).
    """

    def __init__(self, io_loop_thread, gcs_host: str, gcs_port: int):
        self.io = io_loop_thread
        self.gcs_host, self.gcs_port = gcs_host, gcs_port
        self._nodes: Dict[str, dict] = {}  # provider id -> {raylet, type}
        self._counter = 0

    def create_node(self, node_type: str, node_config: Dict, count: int) -> List[str]:
        from ray_tpu._private.raylet import Raylet

        created = []
        for _ in range(count):
            raylet = Raylet(
                self.gcs_host,
                self.gcs_port,
                dict(node_config.get("resources", {"CPU": 1})),
                labels={"rt-node-type": node_type},
            )
            self.io.run(raylet.start())
            self._counter += 1
            pid = f"fake-{node_type}-{self._counter}"
            self._nodes[pid] = {"raylet": raylet, "type": node_type}
            created.append(pid)
        return created

    def terminate_node(self, provider_node_id: str) -> None:
        node = self._nodes.pop(provider_node_id, None)
        if node is None:
            return
        try:
            self.io.run(node["raylet"].stop(), timeout=10)
        except Exception:
            pass

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)

    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        node = self._nodes.get(provider_node_id)
        if node is None:
            return {}
        return {
            "rt-node-type": node["type"],
            "rt-node-id": node["raylet"].node_id.hex(),
        }


class ProcessNodeProvider(NodeProvider):
    """Launches each node as a real raylet SUBPROCESS against a live GCS —
    the reference's fake_multi_node pattern
    (autoscaler/_private/fake_multi_node/node_provider.py): full process
    isolation, so autoscaler e2e tests exercise the same join/heartbeat/
    death paths a real cloud node takes."""

    def __init__(self, gcs_host: str, gcs_port: int):
        self.gcs_host, self.gcs_port = gcs_host, gcs_port
        self._nodes: Dict[str, dict] = {}  # provider id -> {proc, type, node_id}
        self._counter = 0

    def create_node(self, node_type: str, node_config: Dict, count: int) -> List[str]:
        import json
        import subprocess
        import sys

        created = []
        for _ in range(count):
            self._counter += 1
            pid = f"proc-{node_type}-{self._counter}"
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "ray_tpu._private.raylet",
                    "--gcs-host", self.gcs_host,
                    "--gcs-port", str(self.gcs_port),
                    "--resources",
                    json.dumps(node_config.get("resources", {"CPU": 1})),
                    "--labels", json.dumps({"rt-node-type": node_type}),
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL,
                text=True,
            )
            node_id = None
            for line in proc.stdout:  # startup banner
                if line.startswith("RAYLET_NODE_ID="):
                    node_id = line.strip().split("=", 1)[1]
                if line.startswith("RAYLET_STORE="):
                    break
            # Keep draining stdout forever: workers inherit this pipe and
            # a full (unread) 64KB pipe blocks their print()s — wedging
            # tasks with no diagnostic.
            import threading

            threading.Thread(
                target=lambda s=proc.stdout: [None for _ in s],
                daemon=True,
            ).start()
            self._nodes[pid] = {"proc": proc, "type": node_type,
                                "node_id": node_id}
            created.append(pid)
        return created

    def terminate_node(self, provider_node_id: str) -> None:
        node = self._nodes.pop(provider_node_id, None)
        if node is None:
            return
        node["proc"].terminate()
        try:
            node["proc"].wait(timeout=10)
        except Exception:  # noqa: BLE001
            node["proc"].kill()

    def non_terminated_nodes(self) -> List[str]:
        # A crashed raylet process counts as terminated (cloud-instance
        # failure surface the reconciler must observe).
        return [
            pid for pid, n in self._nodes.items()
            if n["proc"].poll() is None
        ]

    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        node = self._nodes.get(provider_node_id)
        if node is None:
            return {}
        return {
            "rt-node-type": node["type"],
            "rt-node-id": node["node_id"] or "",
        }

    def shutdown(self):
        for pid in list(self._nodes):
            self.terminate_node(pid)


class GoogleCloudTransport:  # pragma: no cover - needs GCP network
    """Default HTTP transport for GKETPUNodeProvider: Bearer-token REST
    calls against the container/compute APIs, token from the GCE metadata
    server. Injectable so the provider is fully testable offline (the
    reference's fake-provider pattern, autoscaler/_private/fake_multi_node)."""

    METADATA_TOKEN_URL = (
        "http://metadata.google.internal/computeMetadata/v1/instance/"
        "service-accounts/default/token"
    )

    def __init__(self, token_provider=None):
        self._token_provider = token_provider or self._metadata_token

    def _metadata_token(self) -> str:
        import json as _json
        import urllib.request

        req = urllib.request.Request(
            self.METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"}
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            return _json.loads(resp.read())["access_token"]

    def request(self, method: str, url: str, body: Optional[dict] = None) -> dict:
        import json as _json
        import urllib.request

        data = _json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url,
            data=data,
            method=method,
            headers={
                "Authorization": f"Bearer {self._token_provider()}",
                "Content-Type": "application/json",
            },
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            payload = resp.read()
            return _json.loads(payload) if payload else {}


class GKETPUNodeProvider(NodeProvider):
    """GKE TPU slice node pools as autoscaler nodes.

    Mapping (reference: autoscaler/_private/gcp/node_provider.py +
    kuberay TPU webhook semantics):
      * a node type's ``node_config`` names its GKE ``node_pool``; a TPU
        slice type also sets ``slice_hosts`` (hosts per slice);
      * ``create_node(type, cfg, count)`` resizes the pool UP by
        ``count * slice_hosts`` via ``nodePools/:setSize`` — slices are
        whole-pool-increment atomic, a partial slice is useless to SPMD;
      * ``terminate_node`` deletes the slice's VMs through the pool's
        instance-group manager (``deleteInstances``), shrinking the pool;
      * provider node ids are ``{pool}|{instance-url}``.

    All API traffic flows through the injected ``transport.request(method,
    url, body) -> dict`` so tests drive the provider against a recorded
    API surface; production uses GoogleCloudTransport.
    """

    CONTAINER = "https://container.googleapis.com/v1"

    def __init__(self, project: str, zone: str, cluster: str, transport=None,
                 poll_interval_s: float = 2.0, op_timeout_s: float = 600.0,
                 managed_pools: Optional[List[str]] = None):
        self.project, self.zone, self.cluster = project, zone, cluster
        self.transport = transport or GoogleCloudTransport()
        self.poll_interval_s = poll_interval_s
        self.op_timeout_s = op_timeout_s
        # Which pools this provider owns. Explicit list survives a head
        # restart; None = discover every pool from the cluster API — the
        # live API, never in-process memory, is the source of truth for
        # node enumeration (a restarted provider must still see running
        # TPU slices or the autoscaler double-pays for them).
        self._managed_pools = list(managed_pools) if managed_pools else None
        self._tags: Dict[str, Dict[str, str]] = {}  # advisory type tags

    # -- REST helpers -----------------------------------------------------
    def _cluster_path(self) -> str:
        return (
            f"{self.CONTAINER}/projects/{self.project}/zones/{self.zone}/"
            f"clusters/{self.cluster}"
        )

    def _pool(self, pool: str) -> dict:
        return self.transport.request(
            "GET", f"{self._cluster_path()}/nodePools/{pool}"
        )

    def _wait_op(self, op: dict) -> None:
        """Poll a container Operation until DONE (setSize is async)."""
        import time

        name = op.get("name")
        if not name or op.get("status") == "DONE":
            return
        url = (
            f"{self.CONTAINER}/projects/{self.project}/zones/{self.zone}/"
            f"operations/{name}"
        )
        deadline = time.monotonic() + self.op_timeout_s
        while time.monotonic() < deadline:
            cur = self.transport.request("GET", url)
            if cur.get("status") == "DONE":
                if cur.get("error"):
                    raise RuntimeError(f"GKE operation {name} failed: {cur['error']}")
                return
            time.sleep(self.poll_interval_s)
        raise TimeoutError(f"GKE operation {name} not DONE after {self.op_timeout_s}s")

    def _managed_instances(self, pool: str) -> List[str]:
        """Instance URLs behind a pool's instance group manager(s)."""
        info = self._pool(pool)
        urls = []
        for ig_url in info.get("instanceGroupUrls", []):
            # ..../instanceGroupManagers/{name} — listManagedInstances is a
            # POST on the compute API.
            resp = self.transport.request(
                "POST", ig_url + "/listManagedInstances", {}
            )
            urls.extend(
                mi["instance"] for mi in resp.get("managedInstances", [])
            )
        return urls

    # -- NodeProvider surface --------------------------------------------
    def create_node(self, node_type: str, node_config: Dict, count: int) -> List[str]:
        pool = node_config["node_pool"]
        slice_hosts = int(node_config.get("slice_hosts", 1))
        # Current size = the LIVE instance list. initialNodeCount is
        # immutable creation-time metadata: trusting it on a pool that
        # has since shrunk would over-provision whole (billed) slices.
        before = set(self._managed_instances(pool))
        target = len(before) + count * slice_hosts
        op = self.transport.request(
            "POST",
            f"{self._cluster_path()}/nodePools/{pool}:setSize",
            {"nodeCount": target},
        )
        self._wait_op(op)
        after = self._managed_instances(pool)
        new = [u for u in after if u not in before]
        ids = [f"{pool}|{u}" for u in new]
        for nid in ids:
            self._tags[nid] = {"rt-node-type": node_type,
                               "rt-node-pool": pool}
        return ids

    def terminate_node(self, provider_node_id: str) -> None:
        pool, _, instance_url = provider_node_id.partition("|")
        info = self._pool(pool)
        for ig_url in info.get("instanceGroupUrls", []):
            # Multi-zonal pools have several IGMs; only the one actually
            # holding the instance accepts the delete (the others 4xx).
            # An accepted request returns a compute Operation — in ANY
            # state (PENDING/RUNNING/DONE) the deletion is underway.
            try:
                self.transport.request(
                    "POST",
                    ig_url + "/deleteInstances",
                    {"instances": [instance_url]},
                )
                break
            except Exception:  # noqa: BLE001 — wrong IGM for this instance
                continue
        self._tags.pop(provider_node_id, None)

    def _pools(self) -> List[str]:
        if self._managed_pools is not None:
            return self._managed_pools
        resp = self.transport.request(
            "GET", f"{self._cluster_path()}/nodePools"
        )
        return [p["name"] for p in resp.get("nodePools", [])]

    def non_terminated_nodes(self) -> List[str]:
        out = []
        for pool in self._pools():
            out.extend(f"{pool}|{u}" for u in self._managed_instances(pool))
        return out

    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        tags = dict(self._tags.get(provider_node_id, {}))
        tags.setdefault("rt-node-pool", provider_node_id.split("|", 1)[0])
        return tags


class GCETPUNodeProvider(NodeProvider):
    """Direct (non-GKE) TPU VMs via the Cloud TPU API — the most common
    real TPU deployment (reference pattern:
    autoscaler/_private/gcp/node_provider.py, which splits compute vs
    tpu resources; here the provider IS the tpu.googleapis.com surface).

    Mapping:
      * one TPU API ``node`` IS one slice (multi-host slices are a
        single node resource with several worker endpoints), so slice
        atomicity is the API's own granularity — create/delete always
        moves whole slices;
      * ``node_config``: ``accelerator_type`` (e.g. "v5litepod-16"),
        ``runtime_version``, optional ``network``, ``labels``,
        ``metadata`` (startup script that runs `rt start` and joins the
        cluster);
      * provider node ids are the TPU node names; tags ride TPU labels
        (``rt-managed``/``rt-node-type``), so a RESTARTED provider
        re-discovers its fleet from the live API — never from memory.

    All traffic flows through the injected ``transport.request`` so
    tests drive a recorded API surface; production uses
    GoogleCloudTransport (same bearer-token REST as GKE).
    """

    TPU = "https://tpu.googleapis.com/v2"
    # Node states that hold (or will hold) real capacity. STOPPED slices
    # keep their name reserved -> still "non-terminated" for the
    # autoscaler's accounting.
    LIVE_STATES = ("CREATING", "READY", "STARTING", "STOPPING", "STOPPED",
                   "REPAIRING")

    def __init__(self, project: str, zone: str, transport=None,
                 name_prefix: str = "rt-tpu",
                 poll_interval_s: float = 2.0, op_timeout_s: float = 900.0):
        self.project, self.zone = project, zone
        self.transport = transport or GoogleCloudTransport()
        self.name_prefix = name_prefix
        self.poll_interval_s = poll_interval_s
        self.op_timeout_s = op_timeout_s
        self._list_cache = None  # (monotonic_ts, nodes) — one per tick

    def _parent(self) -> str:
        return f"{self.TPU}/projects/{self.project}/locations/{self.zone}"

    def _wait_op(self, op: dict) -> dict:
        import time

        name = op.get("name")
        if not name or op.get("done"):
            if op.get("error"):
                raise RuntimeError(f"TPU operation failed: {op['error']}")
            return op
        deadline = time.monotonic() + self.op_timeout_s
        while time.monotonic() < deadline:
            cur = self.transport.request("GET", f"{self.TPU}/{name}")
            if cur.get("done"):
                if cur.get("error"):
                    raise RuntimeError(
                        f"TPU operation {name} failed: {cur['error']}"
                    )
                return cur
            time.sleep(self.poll_interval_s)
        raise TimeoutError(
            f"TPU operation {name} not done after {self.op_timeout_s}s"
        )

    # -- NodeProvider surface --------------------------------------------
    def create_node(self, node_type: str, node_config: Dict,
                    count: int) -> List[str]:
        import uuid

        ids = []
        for _ in range(count):
            node_id = f"{self.name_prefix}-{uuid.uuid4().hex[:8]}"
            body = {
                "acceleratorType": node_config["accelerator_type"],
                "runtimeVersion": node_config.get(
                    "runtime_version", "tpu-ubuntu2204-base"
                ),
                "labels": {
                    "rt-managed": "1",
                    "rt-node-type": node_type,
                    **(node_config.get("labels") or {}),
                },
            }
            if node_config.get("network"):
                body["networkConfig"] = {"network": node_config["network"]}
            if node_config.get("metadata"):
                body["metadata"] = dict(node_config["metadata"])
            op = self.transport.request(
                "POST", f"{self._parent()}/nodes?nodeId={node_id}", body
            )
            # Waiting per slice keeps failures attributable: a quota
            # denial names the slice it refused instead of surfacing
            # three creates later.
            self._wait_op(op)
            ids.append(node_id)
        self._list_cache = None
        return ids

    def terminate_node(self, provider_node_id: str) -> None:
        # Fire-and-forget like the GKE provider: once the DELETE is
        # accepted the teardown is underway (slices take minutes to
        # die; waiting would freeze the autoscaler's reconcile loop),
        # and a node deleted out-of-band (404) is already the desired
        # state. Completion is observed by the state filter in _nodes.
        try:
            self.transport.request(
                "DELETE", f"{self._parent()}/nodes/{provider_node_id}"
            )
        except Exception:  # noqa: BLE001 — already gone / in teardown
            pass
        self._list_cache = None

    def _nodes(self) -> List[dict]:
        # One fleet listing serves a whole reconcile tick: both
        # autoscalers call node_tags per node right after
        # non_terminated_nodes, which would otherwise be N+1 full list
        # requests against the Cloud TPU API quota.
        import time

        cached = getattr(self, "_list_cache", None)
        if cached is not None and time.monotonic() - cached[0] < 5.0:
            return cached[1]
        resp = self.transport.request("GET", f"{self._parent()}/nodes")
        out = []
        for node in resp.get("nodes", []):
            labels = node.get("labels") or {}
            if labels.get("rt-managed") != "1":
                continue
            if node.get("state") not in self.LIVE_STATES:
                continue
            out.append(node)
        self._list_cache = (time.monotonic(), out)
        return out

    def non_terminated_nodes(self) -> List[str]:
        # name is "projects/p/locations/z/nodes/{id}".
        return [n["name"].rsplit("/", 1)[1] for n in self._nodes()]

    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        for node in self._nodes():
            if node["name"].rsplit("/", 1)[1] == provider_node_id:
                labels = node.get("labels") or {}
                return {
                    "rt-node-type": labels.get("rt-node-type", ""),
                    "rt-state": node.get("state", ""),
                    "rt-workers": str(
                        len(node.get("networkEndpoints") or []) or 1
                    ),
                }
        return {}


def make_node_provider(provider_config: Dict, **runtime_kwargs) -> NodeProvider:
    """Provider registry (reference: autoscaler/_private/providers.py
    _get_node_provider): maps a config ``type`` to a provider class.

    runtime_kwargs carries environment handles some providers need
    (ProcessNodeProvider's gcs_host/gcs_port); cloud providers take
    everything from the config dict.
    """
    ptype = (provider_config or {}).get("type", "process")
    cfg = dict(provider_config or {})
    cfg.pop("type", None)
    if ptype == "gke":
        return GKETPUNodeProvider(
            cfg.pop("project"), cfg.pop("zone"), cfg.pop("cluster"), **cfg
        )
    if ptype in ("gce_tpu", "tpu_vm"):
        return GCETPUNodeProvider(cfg.pop("project"), cfg.pop("zone"), **cfg)
    if ptype == "process":
        return ProcessNodeProvider(
            runtime_kwargs["gcs_host"], runtime_kwargs["gcs_port"]
        )
    raise ValueError(
        f"unknown provider type {ptype!r}: expected gke / gce_tpu / process"
    )
