"""Node providers: how the autoscaler creates/terminates hosts.

Analog of the reference's ``NodeProvider`` ABC
(python/ray/autoscaler/node_provider.py) and the offline test provider
(autoscaler/_private/fake_multi_node/node_provider.py — "nodes" are local
processes so autoscaler logic is testable without a cloud).

TPU framing: a *node type* describes one host class; a TPU slice node type
sets ``slice_hosts`` > 1, and the provider must create/terminate those
hosts atomically — a partial slice is useless to SPMD jobs (the reference
reaches the same effect through GKE TPU node pools).
"""

from __future__ import annotations

import abc
from typing import Dict, List, Optional


class NodeProvider(abc.ABC):
    """Minimal provider surface the autoscaler drives."""

    @abc.abstractmethod
    def create_node(self, node_type: str, node_config: Dict, count: int) -> List[str]:
        """Launch `count` hosts of `node_type`; returns provider node ids."""

    @abc.abstractmethod
    def terminate_node(self, provider_node_id: str) -> None:
        ...

    @abc.abstractmethod
    def non_terminated_nodes(self) -> List[str]:
        ...

    @abc.abstractmethod
    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        """Must include "rt-node-type"; includes "rt-node-id" (hex) once
        the raylet on that host has registered."""


class FakeMultiNodeProvider(NodeProvider):
    """Boots raylets in-process against a live GCS (offline testing).

    The reference's fake provider launches local processes; here the
    cluster harness's event loop hosts extra raylet control loops, which
    is exactly how multi-node tests run (cluster_utils.Cluster).
    """

    def __init__(self, io_loop_thread, gcs_host: str, gcs_port: int):
        self.io = io_loop_thread
        self.gcs_host, self.gcs_port = gcs_host, gcs_port
        self._nodes: Dict[str, dict] = {}  # provider id -> {raylet, type}
        self._counter = 0

    def create_node(self, node_type: str, node_config: Dict, count: int) -> List[str]:
        from ray_tpu._private.raylet import Raylet

        created = []
        for _ in range(count):
            raylet = Raylet(
                self.gcs_host,
                self.gcs_port,
                dict(node_config.get("resources", {"CPU": 1})),
                labels={"rt-node-type": node_type},
            )
            self.io.run(raylet.start())
            self._counter += 1
            pid = f"fake-{node_type}-{self._counter}"
            self._nodes[pid] = {"raylet": raylet, "type": node_type}
            created.append(pid)
        return created

    def terminate_node(self, provider_node_id: str) -> None:
        node = self._nodes.pop(provider_node_id, None)
        if node is None:
            return
        try:
            self.io.run(node["raylet"].stop(), timeout=10)
        except Exception:
            pass

    def non_terminated_nodes(self) -> List[str]:
        return list(self._nodes)

    def node_tags(self, provider_node_id: str) -> Dict[str, str]:
        node = self._nodes.get(provider_node_id)
        if node is None:
            return {}
        return {
            "rt-node-type": node["type"],
            "rt-node-id": node["raylet"].node_id.hex(),
        }


class GKETPUNodeProvider(NodeProvider):  # pragma: no cover - needs GCP
    """Skeleton provider for GKE TPU slice node pools.

    Creating a node type with ``slice_hosts`` maps to resizing the
    corresponding TPU node pool (each slice = `slice_hosts` VMs that must
    come and go together). Requires cluster credentials + the GKE API,
    which this offline build cannot exercise; the methods document the
    mapping and fail loudly.
    """

    def __init__(self, project: str, zone: str, cluster: str):
        raise NotImplementedError(
            "GKE TPU provider requires GCP credentials and the container "
            "API; deploy-side integration point. Use FakeMultiNodeProvider "
            "for offline testing."
        )

    def create_node(self, node_type, node_config, count):
        raise NotImplementedError

    def terminate_node(self, provider_node_id):
        raise NotImplementedError

    def non_terminated_nodes(self):
        raise NotImplementedError

    def node_tags(self, provider_node_id):
        raise NotImplementedError
