"""Cluster launcher: bring a cluster up from a YAML config.

Analog of the reference's `ray up/down/attach/exec` CLI
(python/ray/scripts/scripts.py:566 and the command-runner layer in
autoscaler/_private/command_runner.py): a YAML file names the head and
worker hosts; per-node CommandRunners (SSH, or local subprocess for
single-host/testing) run file mounts, setup commands, and the
`rt start` service commands on each node.

YAML schema::

    cluster_name: my-pod
    provider:
      type: ssh            # or "local" (every node is this host)
      head_ip: 10.0.0.1
      worker_ips: [10.0.0.2, 10.0.0.3]
    auth:                  # ssh provider only
      ssh_user: ubuntu
      ssh_private_key: ~/.ssh/id_rsa
    port: 6379             # GCS port on the head
    file_mounts:           # remote path -> local path, pushed to all
      /home/ubuntu/app: ./app
    setup_commands:        # run on every node before start
      - pip install -e /home/ubuntu/app
    head_setup_commands: []
    worker_setup_commands: []
    head_start_commands:   # {port}/{head_address} substituted
      - python -m ray_tpu start --head --port {port}
    worker_start_commands:
      - python -m ray_tpu start --address {head_address}
    stop_commands:
      - python -m ray_tpu stop
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional

DEFAULT_HEAD_START = ["{python} -m ray_tpu start --head --port {port}"]
DEFAULT_WORKER_START = ["{python} -m ray_tpu start --address {head_address}"]
DEFAULT_STOP = ["{python} -m ray_tpu stop"]


class CommandRunner(ABC):
    """Runs shell commands / pushes files on one node (reference:
    command_runner.py CommandRunnerInterface)."""

    @abstractmethod
    def run(self, cmd: str, timeout: float = 600.0) -> str:
        """Run a shell command; returns stdout, raises on failure."""

    @abstractmethod
    def put(self, local_path: str, remote_path: str) -> None:
        """Copy a local file/directory onto the node."""


class LocalCommandRunner(CommandRunner):
    """Every 'node' is this host (the reference's local/fake provider
    pattern — the single-host and test path)."""

    def __init__(self, env: Optional[Dict[str, str]] = None):
        self.env = env

    def run(self, cmd: str, timeout: float = 600.0) -> str:
        env = dict(os.environ)
        if self.env:
            env.update(self.env)
        proc = subprocess.run(
            cmd, shell=True, capture_output=True, text=True,
            timeout=timeout, env=env,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"command failed ({proc.returncode}): {cmd}\n"
                f"stdout: {proc.stdout[-2000:]}\nstderr: {proc.stderr[-2000:]}"
            )
        return proc.stdout

    def put(self, local_path: str, remote_path: str) -> None:
        import shutil

        local_path = os.path.abspath(os.path.expanduser(local_path))
        remote_path = os.path.expanduser(remote_path)
        if local_path == remote_path:
            return
        os.makedirs(os.path.dirname(remote_path) or ".", exist_ok=True)
        if os.path.isdir(local_path):
            shutil.copytree(local_path, remote_path, dirs_exist_ok=True)
        else:
            shutil.copy2(local_path, remote_path)


class SSHCommandRunner(CommandRunner):
    """SSH/scp command runner (reference: command_runner.py
    SSHCommandRunner). ssh_cmd_prefix is injectable for tests."""

    SSH_OPTS = [
        "-o", "StrictHostKeyChecking=no",
        "-o", "UserKnownHostsFile=/dev/null",
        "-o", "LogLevel=ERROR",
        "-o", "ConnectTimeout=10",
    ]

    def __init__(self, ip: str, user: str, key: Optional[str] = None,
                 port: int = 22):
        self.ip = ip
        self.user = user
        self.key = os.path.expanduser(key) if key else None
        self.port = port

    def _base(self, scp: bool = False) -> List[str]:
        cmd = ["scp" if scp else "ssh", *self.SSH_OPTS]
        cmd += (["-P"] if scp else ["-p"]) + [str(self.port)]
        if self.key:
            cmd += ["-i", self.key]
        return cmd

    def run(self, cmd: str, timeout: float = 600.0) -> str:
        full = self._base() + [f"{self.user}@{self.ip}",
                               f"bash -lc {shlex.quote(cmd)}"]
        proc = subprocess.run(
            full, capture_output=True, text=True, timeout=timeout
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"ssh to {self.ip} failed ({proc.returncode}): {cmd}\n"
                f"stderr: {proc.stderr[-2000:]}"
            )
        return proc.stdout

    def put(self, local_path: str, remote_path: str) -> None:
        local_path = os.path.expanduser(local_path)
        flags = ["-r"] if os.path.isdir(local_path) else []
        full = (self._base(scp=True) + flags
                + [local_path, f"{self.user}@{self.ip}:{remote_path}"])
        proc = subprocess.run(full, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"scp to {self.ip} failed: {proc.stderr[-2000:]}"
            )

    def attach_command(self) -> str:
        parts = self._base() + [f"{self.user}@{self.ip}"]
        return " ".join(shlex.quote(p) for p in parts)


class ClusterLauncher:
    def __init__(self, config: Dict[str, Any]):
        self.config = config
        self.name = config.get("cluster_name", "ray-tpu-cluster")
        provider = config.get("provider") or {"type": "local"}
        self.provider_type = provider.get("type", "local")
        self.head_ip = provider.get("head_ip", "127.0.0.1")
        self.worker_ips: List[str] = list(provider.get("worker_ips", []))
        self.port = int(config.get("port", 6379))

    @classmethod
    def from_yaml(cls, path: str) -> "ClusterLauncher":
        import yaml

        with open(os.path.expanduser(path)) as f:
            return cls(yaml.safe_load(f) or {})

    # -- runners ---------------------------------------------------------
    def _runner(self, ip: str) -> CommandRunner:
        if self.provider_type == "local":
            return LocalCommandRunner()
        auth = self.config.get("auth") or {}
        return SSHCommandRunner(
            ip,
            auth.get("ssh_user", "root"),
            auth.get("ssh_private_key"),
            int(auth.get("ssh_port", 22)),
        )

    def _subst(self, cmd: str) -> str:
        return cmd.format(
            python=shlex.quote(sys.executable),
            port=self.port,
            head_address=f"{self.head_ip}:{self.port}",
            cluster_name=self.name,
        )

    def _run_all(self, runner: CommandRunner, commands: List[str],
                 log) -> None:
        for cmd in commands:
            cmd = self._subst(cmd)
            log(f"  $ {cmd}")
            out = runner.run(cmd)
            if out.strip():
                log("    " + out.strip().replace("\n", "\n    "))

    def _file_mounts(self, runner: CommandRunner, log) -> None:
        for remote, local in (self.config.get("file_mounts") or {}).items():
            log(f"  mount {local} -> {remote}")
            runner.put(local, remote)

    # -- operations (the `rt up/down/exec/attach` verbs) ----------------
    def up(self, log=print) -> str:
        """Bring the head up, then every worker (reference:
        create_or_update_cluster, scripts.py:566)."""
        cfg = self.config
        setup = list(cfg.get("setup_commands") or [])
        log(f"[{self.name}] head {self.head_ip}")
        head = self._runner(self.head_ip)
        self._file_mounts(head, log)
        self._run_all(
            head,
            setup + list(cfg.get("head_setup_commands") or []),
            log,
        )
        self._run_all(
            head,
            list(cfg.get("head_start_commands") or DEFAULT_HEAD_START),
            log,
        )
        for ip in self.worker_ips:
            log(f"[{self.name}] worker {ip}")
            w = self._runner(ip)
            self._file_mounts(w, log)
            self._run_all(
                w, setup + list(cfg.get("worker_setup_commands") or []), log
            )
            self._run_all(
                w,
                list(cfg.get("worker_start_commands") or DEFAULT_WORKER_START),
                log,
            )
        address = f"{self.head_ip}:{self.port}"
        log(f"[{self.name}] up — connect with rt.init(address={address!r})")
        return address

    def down(self, log=print) -> None:
        """Stop services on every node, workers first (reference:
        teardown_cluster)."""
        stop = list(self.config.get("stop_commands") or DEFAULT_STOP)
        for ip in [*self.worker_ips, self.head_ip]:
            log(f"[{self.name}] stopping {ip}")
            try:
                self._run_all(self._runner(ip), stop, log)
            except Exception as e:  # noqa: BLE001 — best-effort teardown
                log(f"  warning: {e}")

    def exec(self, cmd: str, all_nodes: bool = False, log=print) -> List[str]:
        """Run a command on the head (or every node) — `rt exec`."""
        outs = []
        targets = [self.head_ip] + (self.worker_ips if all_nodes else [])
        for ip in targets:
            outs.append(self._runner(ip).run(self._subst(cmd)))
        return outs

    def attach_command(self) -> str:
        """The shell command `rt attach` would exec into."""
        runner = self._runner(self.head_ip)
        if isinstance(runner, SSHCommandRunner):
            return runner.attach_command()
        return os.environ.get("SHELL", "/bin/bash")
