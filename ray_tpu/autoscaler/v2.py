"""Autoscaler v2: instance-manager architecture with explicit lifecycle.

Analog of the reference's autoscaler v2 (python/ray/autoscaler/v2/
instance_manager/, v2/scheduler.py, backed by GcsAutoscalerStateManager —
SURVEY.md §2.2): instead of v1's implicit "launched/running" bookkeeping,
every cloud instance is a first-class record walking an explicit state
machine, and a Reconciler makes the world match the schedule each tick:

    QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING
                 |              |            |
            ALLOCATION_FAILED   |       RAY_STOPPED
                 |              v            v
                 +-------> TERMINATING -> TERMINATED

The separation of concerns mirrors the reference:
  * InstanceManager  — the instance table + legal-transition enforcement
    (reference: v2/instance_manager/instance_manager.py, instance
    lifecycle in instance_storage.py / common.py Instance proto states)
  * Scheduler        — demand bundles -> per-type target counts
    (reference: v2/scheduler.py ResourceDemandScheduler)
  * Reconciler       — drives providers + observed ray state toward the
    target (reference: v2/instance_manager/reconciler.py)

TPU specifics carry over from v1: a node type with slice_hosts = N is
managed in atomic groups of N instances (a partial slice cannot run SPMD
programs) — both scale-up and scale-down happen slice-at-a-time.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ray_tpu.autoscaler.node_provider import NodeProvider

# -- instance lifecycle ------------------------------------------------------

QUEUED = "QUEUED"                      # decided, not yet requested from cloud
REQUESTED = "REQUESTED"                # provider.create_node issued
ALLOCATED = "ALLOCATED"                # cloud reports the VM up
RAY_RUNNING = "RAY_RUNNING"            # raylet registered with the GCS
RAY_STOPPED = "RAY_STOPPED"            # raylet gone (drained or died)
ALLOCATION_FAILED = "ALLOCATION_FAILED"
TERMINATING = "TERMINATING"            # provider.terminate_node issued
TERMINATED = "TERMINATED"

_LEGAL: Dict[str, Tuple[str, ...]] = {
    QUEUED: (REQUESTED, TERMINATED),
    REQUESTED: (ALLOCATED, ALLOCATION_FAILED, TERMINATING),
    ALLOCATED: (RAY_RUNNING, RAY_STOPPED, TERMINATING),
    RAY_RUNNING: (RAY_STOPPED, TERMINATING),
    RAY_STOPPED: (TERMINATING, RAY_RUNNING),
    ALLOCATION_FAILED: (QUEUED, TERMINATED),
    TERMINATING: (TERMINATED,),
    TERMINATED: (),
}


@dataclass
class Instance:
    instance_id: str
    node_type: str
    status: str = QUEUED
    cloud_id: Optional[str] = None
    slice_group: Optional[str] = None  # atomic-slice membership
    status_history: List[Tuple[str, float]] = field(default_factory=list)
    idle_since: Optional[float] = None

    def age_in_status(self) -> float:
        if not self.status_history:
            return 0.0
        return time.monotonic() - self.status_history[-1][1]


class InstanceManager:
    """The instance table. All mutations go through set_status, which
    enforces the lifecycle's legal transitions and records history."""

    def __init__(self):
        self._instances: Dict[str, Instance] = {}

    def create(self, node_type: str, slice_group: Optional[str] = None) -> Instance:
        inst = Instance(
            instance_id=uuid.uuid4().hex[:12],
            node_type=node_type,
            slice_group=slice_group,
        )
        inst.status_history.append((QUEUED, time.monotonic()))
        self._instances[inst.instance_id] = inst
        return inst

    def set_status(self, instance_id: str, status: str) -> Instance:
        inst = self._instances[instance_id]
        if status not in _LEGAL[inst.status]:
            raise ValueError(
                f"illegal transition {inst.status} -> {status} "
                f"for instance {instance_id}"
            )
        inst.status = status
        inst.status_history.append((status, time.monotonic()))
        return inst

    def instances(self, statuses: Optional[Tuple[str, ...]] = None,
                  node_type: Optional[str] = None) -> List[Instance]:
        out = []
        for inst in self._instances.values():
            if statuses and inst.status not in statuses:
                continue
            if node_type and inst.node_type != node_type:
                continue
            out.append(inst)
        return out

    def get(self, instance_id: str) -> Instance:
        return self._instances[instance_id]

    def by_cloud_id(self, cloud_id: str) -> Optional[Instance]:
        for inst in self._instances.values():
            if inst.cloud_id == cloud_id:
                return inst
        return None


# -- scheduler ---------------------------------------------------------------

_ACTIVE = (QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING, RAY_STOPPED)


def _fits(bundle: Dict[str, float], free: Dict[str, float]) -> bool:
    return all(free.get(k, 0) + 1e-9 >= v for k, v in bundle.items())


def _claim(bundle: Dict[str, float], free: Dict[str, float]):
    for k, v in bundle.items():
        free[k] = free.get(k, 0) - v


class Scheduler:
    """Demand bundles + node-type config -> launch decisions
    (reference: v2/scheduler.py ResourceDemandScheduler).

    Bin-packs unmet demand onto copies of each node type, respecting
    min/max workers; slice types count in whole slices.
    """

    def __init__(self, node_types: Dict[str, dict]):
        self.node_types = node_types

    def desired_launches(
        self,
        demands: List[Dict[str, float]],
        free_per_node: List[Dict[str, float]],
        active_counts: Dict[str, int],
    ) -> Dict[str, int]:
        """Returns {node_type: units to launch} (a unit = slice_hosts
        hosts for slice types, 1 host otherwise)."""
        free = [dict(f) for f in free_per_node]
        unmet: List[Dict[str, float]] = []
        for bundle in demands:
            for f in free:
                if _fits(bundle, f):
                    _claim(bundle, f)
                    break
            else:
                unmet.append(bundle)

        launches: Dict[str, int] = {}
        # min_workers floors first.
        for t, spec in self.node_types.items():
            slice_hosts = spec.get("slice_hosts", 1)
            have_units = active_counts.get(t, 0) // slice_hosts
            need = spec.get("min_workers", 0) - have_units
            if need > 0:
                launches[t] = need

        for bundle in unmet:
            placed = False
            for t, spec in self.node_types.items():
                res = dict(spec.get("resources", {}))
                if not _fits(bundle, res):
                    continue
                slice_hosts = spec.get("slice_hosts", 1)
                have_units = (
                    active_counts.get(t, 0) // slice_hosts
                    + launches.get(t, 0)
                )
                if have_units >= spec.get("max_workers", 2 ** 30):
                    continue
                launches[t] = launches.get(t, 0) + 1
                # The new unit's free capacity absorbs later bundles too.
                unit_free = dict(res)
                _claim(bundle, unit_free)
                free.append(unit_free)
                placed = True
                break
            if not placed:
                pass  # infeasible on every type — surfaced via report()
        return launches


# -- reconciler --------------------------------------------------------------


class GcsRayState:
    """Live ray_state_fn backed by the GCS (the GcsAutoscalerStateManager
    role): maps provider cloud ids to registered nodes via the provider's
    rt-node-id tag, reports aliveness + free resources, and accumulates
    idle seconds from observed full-availability transitions."""

    def __init__(self, provider: NodeProvider, gcs_call):
        """gcs_call: callable(method, payload) -> response dict (sync)."""
        self.provider = provider
        self.gcs_call = gcs_call
        self._idle_since: Dict[str, float] = {}

    def __call__(self) -> Dict[str, dict]:
        nodes = {
            n["node_id"].hex() if isinstance(n["node_id"], bytes)
            else n["node_id"]: n
            for n in self.gcs_call("get_nodes", {})["nodes"]
        }
        now = time.monotonic()
        out: Dict[str, dict] = {}
        for cloud_id in self.provider.non_terminated_nodes():
            tags = self.provider.node_tags(cloud_id)
            node = nodes.get(tags.get("rt-node-id", ""))
            if node is None or node.get("state") != "ALIVE":
                out[cloud_id] = {"alive": False, "idle_s": 0.0, "free": {}}
                self._idle_since.pop(cloud_id, None)
                continue
            avail = dict(node.get("resources_available", {}))
            total = node.get("resources_total", {})
            idle = (
                avail == dict(total)
                and not node.get("demand_bundles")
            )
            if idle:
                self._idle_since.setdefault(cloud_id, now)
            else:
                self._idle_since.pop(cloud_id, None)
            out[cloud_id] = {
                "alive": True,
                "idle_s": now - self._idle_since.get(cloud_id, now),
                "free": avail,
            }
        return out


def gcs_demands(gcs_call):
    """demands_fn reading queued-task resource bundles from the GCS node
    table (the LoadMetrics role)."""

    def demands() -> List[Dict[str, float]]:
        out: List[Dict[str, float]] = []
        for n in gcs_call("get_nodes", {})["nodes"]:
            if n.get("state") == "ALIVE":
                out.extend(n.get("demand_bundles") or [])
        return out

    return demands


class Reconciler:
    """One tick: observe cloud + ray state, converge instances toward the
    schedule (reference: v2/instance_manager/reconciler.py).

    `ray_state_fn` abstracts the GCS view (reference:
    GcsAutoscalerStateManager): it returns
      {cloud_id: {"alive": bool, "idle_s": float, "free": {...}}}
    for every provider node whose raylet has (ever) registered.
    """

    def __init__(
        self,
        im: InstanceManager,
        provider: NodeProvider,
        node_types: Dict[str, dict],
        ray_state_fn,
        demands_fn,
        idle_timeout_s: float = 60.0,
        request_timeout_s: float = 600.0,
    ):
        self.im = im
        self.provider = provider
        self.node_types = node_types
        self.scheduler = Scheduler(node_types)
        self.ray_state_fn = ray_state_fn
        self.demands_fn = demands_fn
        self.idle_timeout_s = idle_timeout_s
        self.request_timeout_s = request_timeout_s

    # .. observation ........................................................
    def _sync_cloud(self):
        cloud_ids = set(self.provider.non_terminated_nodes())
        # REQUESTED whose VM appeared -> ALLOCATED; too old -> failed.
        for inst in self.im.instances((REQUESTED,)):
            if inst.cloud_id in cloud_ids:
                self.im.set_status(inst.instance_id, ALLOCATED)
            elif inst.age_in_status() > self.request_timeout_s:
                self.im.set_status(inst.instance_id, ALLOCATION_FAILED)
        # Anything we think is up but the cloud no longer lists -> gone.
        for inst in self.im.instances((ALLOCATED, RAY_RUNNING, RAY_STOPPED)):
            if inst.cloud_id not in cloud_ids:
                self.im.set_status(inst.instance_id, TERMINATING)
                self.im.set_status(inst.instance_id, TERMINATED)
        for inst in self.im.instances((TERMINATING,)):
            if inst.cloud_id not in cloud_ids:
                self.im.set_status(inst.instance_id, TERMINATED)

    def _sync_ray(self):
        state = self.ray_state_fn()
        now = time.monotonic()
        for inst in self.im.instances((ALLOCATED, RAY_RUNNING, RAY_STOPPED)):
            s = state.get(inst.cloud_id)
            if s is None:
                continue
            if s.get("alive") and inst.status in (ALLOCATED, RAY_STOPPED):
                self.im.set_status(inst.instance_id, RAY_RUNNING)
            elif not s.get("alive") and inst.status == RAY_RUNNING:
                self.im.set_status(inst.instance_id, RAY_STOPPED)
            if inst.status == RAY_RUNNING:
                idle_s = s.get("idle_s", 0.0)
                inst.idle_since = (now - idle_s) if idle_s > 0 else None

    # .. convergence ........................................................
    def _launch_queued(self):
        by_type: Dict[str, List[Instance]] = {}
        for inst in self.im.instances((QUEUED,)):
            by_type.setdefault(inst.node_type, []).append(inst)
        for t, insts in by_type.items():
            spec = self.node_types.get(t, {})
            try:
                cloud_ids = self.provider.create_node(t, spec, len(insts))
            except Exception:  # noqa: BLE001 — cloud hiccup: retry next tick
                continue
            for inst, cid in zip(insts, cloud_ids):
                inst.cloud_id = cid
                self.im.set_status(inst.instance_id, REQUESTED)

    def _scale_up(self):
        state = self.ray_state_fn()
        free = [
            dict(s.get("free", {})) for s in state.values() if s.get("alive")
        ]
        active: Dict[str, int] = {}
        for inst in self.im.instances(_ACTIVE):
            active[inst.node_type] = active.get(inst.node_type, 0) + 1
        for t, units in self.scheduler.desired_launches(
            list(self.demands_fn()), free, active
        ).items():
            slice_hosts = self.node_types.get(t, {}).get("slice_hosts", 1)
            for _ in range(units):
                group = uuid.uuid4().hex[:8] if slice_hosts > 1 else None
                for _ in range(slice_hosts):
                    self.im.create(t, slice_group=group)

    def _scale_down(self):
        now = time.monotonic()
        min_floor: Dict[str, int] = {
            t: spec.get("min_workers", 0) * spec.get("slice_hosts", 1)
            for t, spec in self.node_types.items()
        }
        active: Dict[str, int] = {}
        for inst in self.im.instances(_ACTIVE):
            active[inst.node_type] = active.get(inst.node_type, 0) + 1

        def expired(inst: Instance) -> bool:
            return (
                inst.idle_since is not None
                and now - inst.idle_since > self.idle_timeout_s
            )

        # Group instances by slice; a slice goes only when ALL its hosts
        # are idle past the timeout (slice-atomic invariant).
        groups: Dict[Tuple[str, Optional[str]], List[Instance]] = {}
        for inst in self.im.instances((RAY_RUNNING, RAY_STOPPED)):
            key = (inst.node_type, inst.slice_group or inst.instance_id)
            groups.setdefault(key, []).append(inst)
        for (t, _), insts in groups.items():
            if not all(
                expired(i) or i.status == RAY_STOPPED for i in insts
            ):
                continue
            if any(i.status == RAY_RUNNING for i in insts) and (
                active.get(t, 0) - len(insts) < min_floor.get(t, 0)
            ):
                continue  # would dip below min_workers
            for inst in insts:
                try:
                    self.provider.terminate_node(inst.cloud_id)
                except Exception:  # noqa: BLE001
                    continue
                self.im.set_status(inst.instance_id, TERMINATING)
                active[t] = active.get(t, 0) - 1

    def _retry_failed(self):
        for inst in self.im.instances((ALLOCATION_FAILED,)):
            # Requeue once; a type that keeps failing stays visible in the
            # report as repeated ALLOCATION_FAILED history.
            self.im.set_status(inst.instance_id, QUEUED)
            inst.cloud_id = None

    def step(self):
        """One reconciliation tick (observe, then converge)."""
        self._sync_cloud()
        self._sync_ray()
        self._retry_failed()
        self._scale_up()
        self._launch_queued()
        self._scale_down()

    def report(self) -> Dict[str, Dict[str, int]]:
        """{node_type: {status: count}} — the `rt status` v2 view."""
        out: Dict[str, Dict[str, int]] = {}
        for inst in self.im.instances():
            t = out.setdefault(inst.node_type, {})
            t[inst.status] = t.get(inst.status, 0) + 1
        return out


class Monitor:
    """Background autoscaling loop: runs reconciler ticks on a daemon
    thread (the reference's monitor.py process role — here a thread owned
    by whoever starts autoscaling, typically the head node)."""

    def __init__(self, reconciler: "Reconciler", interval_s: float = 1.0):
        import threading

        self.reconciler = reconciler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._errors: list = []
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="rt-autoscaler-v2"
        )

    def start(self) -> "Monitor":
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.reconciler.step()
            except Exception as e:  # noqa: BLE001 — keep scaling
                self._errors.append(f"{type(e).__name__}: {e}")
                del self._errors[:-20]

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)
