"""Arrow tensor extension: fixed-shape ndarrays as first-class columns.

Analog of the reference's ArrowTensorArray/ArrowTensorType
(python/ray/air/util/tensor_extensions/arrow.py): an (N, *shape) ndarray
becomes ONE arrow column (FixedSizeList storage + shape metadata), so
image/tensor datasets ride arrow blocks through the store — which is
what makes the zero-copy batch path (dataset._iter_numpy_batches) apply
to tensors too: batches are reshaped VIEWS over the block's buffer all
the way to device_put.
"""

from __future__ import annotations

import json

import numpy as np
import pyarrow as pa


class ArrowTensorType(pa.ExtensionType):
    """Fixed-shape tensor column: storage is FixedSizeList(prod(shape))
    of the element dtype; the element shape rides extension metadata."""

    def __init__(self, shape, value_type):
        self.shape = tuple(int(s) for s in shape)
        size = 1
        for s in self.shape:
            size *= s
        super().__init__(pa.list_(value_type, size), "ray_tpu.tensor")

    def __arrow_ext_serialize__(self) -> bytes:
        return json.dumps(list(self.shape)).encode()

    @classmethod
    def __arrow_ext_deserialize__(cls, storage_type, serialized):
        return cls(json.loads(serialized.decode()),
                   storage_type.value_type)

    def __reduce__(self):
        return (
            ArrowTensorType.__arrow_ext_deserialize__,
            (self.storage_type, self.__arrow_ext_serialize__()),
        )


try:  # idempotent across re-imports (pytest reloads)
    pa.register_extension_type(ArrowTensorType((1,), pa.float32()))
except pa.ArrowKeyError:
    pass


def tensor_array(arr: np.ndarray) -> pa.ExtensionArray:
    """(N, *shape) ndarray -> one tensor extension array (no per-row
    Python objects; the storage buffer is the array's own bytes)."""
    arr = np.ascontiguousarray(arr)
    n = len(arr)
    shape = arr.shape[1:]
    size = int(np.prod(shape)) if shape else 1
    values = pa.array(arr.reshape(-1))
    storage = pa.FixedSizeListArray.from_arrays(values, size)
    return pa.ExtensionArray.from_storage(
        ArrowTensorType(shape, values.type), storage
    )


def tensor_to_numpy(col) -> np.ndarray:
    """Tensor extension column -> (N, *shape) ndarray, zero-copy: a
    reshape of the storage values buffer."""
    if isinstance(col, pa.ChunkedArray):
        if col.num_chunks == 1:
            return tensor_to_numpy(col.chunk(0))
        return np.concatenate(
            [tensor_to_numpy(c) for c in col.chunks]
        )
    shape = col.type.shape
    flat = col.storage.flatten().to_numpy(zero_copy_only=True)
    return flat.reshape(len(col), *shape)


def is_tensor_type(t) -> bool:
    return isinstance(t, ArrowTensorType)


def table_with_tensors(columns: dict) -> pa.Table:
    """dict of name -> ndarray; multi-dim arrays become tensor columns,
    1-D arrays plain columns."""
    arrays, names = [], []
    for name, arr in columns.items():
        arr = np.asarray(arr)
        names.append(name)
        arrays.append(tensor_array(arr) if arr.ndim > 1 else pa.array(arr))
    return pa.Table.from_arrays(arrays, names=names)
