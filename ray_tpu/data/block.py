"""Blocks: the unit of distributed data.

Analog of the reference's block model (python/ray/data/block.py): a block
is a pyarrow Table (columnar rows) or a plain Python list (simple block,
for arbitrary objects). Batches convert to dict-of-numpy for ML feeding.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Union

import numpy as np
import pyarrow as pa

Block = Union[pa.Table, List[Any]]


def block_from_rows(rows: List[Any]) -> Block:
    """Rows of dicts -> arrow table; anything else -> simple block."""
    if rows and all(isinstance(r, dict) for r in rows):
        try:
            return pa.Table.from_pylist(rows)
        except (pa.ArrowInvalid, pa.ArrowTypeError, pa.ArrowNotImplementedError):
            return list(rows)
    return list(rows)


def block_num_rows(block: Block) -> int:
    return block.num_rows if isinstance(block, pa.Table) else len(block)


def block_to_rows(block: Block) -> List[Any]:
    return block.to_pylist() if isinstance(block, pa.Table) else list(block)


def block_slice(block: Block, start: int, end: int) -> Block:
    if isinstance(block, pa.Table):
        return block.slice(start, end - start)
    return block[start:end]


def block_concat(blocks: List[Block]) -> Block:
    if not blocks:
        return []
    if all(isinstance(b, pa.Table) for b in blocks):
        return pa.concat_tables(blocks)
    rows: List[Any] = []
    for b in blocks:
        rows.extend(block_to_rows(b))
    return block_from_rows(rows)


def block_to_batch(block: Block, batch_format: str = "numpy"):
    """Convert a block to a training batch."""
    if batch_format == "pyarrow":
        if isinstance(block, pa.Table):
            return block
        return pa.Table.from_pylist(
            [r if isinstance(r, dict) else {"item": r} for r in block_to_rows(block)]
        )
    if batch_format == "numpy":
        if isinstance(block, pa.Table):
            from ray_tpu.data.tensor import is_tensor_type, tensor_to_numpy

            out = {}
            for name, col in zip(block.column_names, block.columns):
                if is_tensor_type(col.type):
                    # (N, *shape) view over the storage buffer.
                    out[name] = tensor_to_numpy(col)
                else:
                    out[name] = np.asarray(
                        col.to_numpy(zero_copy_only=False)
                    )
            return out
        rows = block_to_rows(block)
        if rows and isinstance(rows[0], dict):
            keys = rows[0].keys()
            return {k: np.asarray([r[k] for r in rows]) for k in keys}
        return {"item": np.asarray(rows)}
    raise ValueError(f"unknown batch_format {batch_format!r}")


def block_schema(block: Block):
    if isinstance(block, pa.Table):
        return block.schema
    rows = block_to_rows(block)
    if rows and isinstance(rows[0], dict):
        return {k: type(v).__name__ for k, v in rows[0].items()}
    return {"item": type(rows[0]).__name__} if rows else None
