"""Streaming split iteration for train ingestion.

Analog of the reference's Dataset.streaming_split
(python/ray/data/dataset.py:1161) + StreamSplitDataIterator
(_internal/iterator/stream_split_iterator.py): one coordinator actor
drives the dataset's streaming executor per epoch and deals completed
output blocks to n consumer queues; each training worker holds a
DataIterator that pulls from its queue. Blocks flow while upstream tasks
are still running, and every epoch re-executes the pipeline (fresh
random_shuffle draws etc.).

`equal=True` balances splits by ROW count at block granularity (greedy
least-loaded dispatch); the reference additionally slices boundary blocks
for exact row equality.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Iterator, List, Optional

import ray_tpu as rt
from ray_tpu.data import block as B

def _split_queue_depth() -> int:
    # Undelivered blocks buffered per split before the producer stalls
    # (consumer backpressure; reference: per-split output queue bounds).
    from ray_tpu._private.config import get_config

    return get_config().data_split_queue_depth


def _block_rows(block) -> int:
    return B.block_num_rows(block)


@rt.remote
class _SplitCoordinator:
    """Owns one streaming execution per epoch and deals blocks to n
    split queues. max_concurrency must cover n blocked next_blocks()
    calls plus control calls (set at creation in streaming_split)."""

    def __init__(self, input_refs: List, stages_payload: bytes, n: int,
                 equal: bool):
        import cloudpickle

        self._input_refs = list(input_refs)
        self._stages = cloudpickle.loads(stages_payload)
        self._n = n
        self._equal = equal
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._epoch = -1          # epoch currently producing / produced
        self._queues: List[deque] = [deque() for _ in range(n)]
        self._rows: List[int] = [0] * n
        self._producer_done = True
        self._producer_error: Optional[str] = None

    def start_epoch(self, epoch: int) -> bool:
        """Idempotent across the n consumers: the first call for the next
        epoch starts its producer thread. Returns False (caller retries)
        while the previous epoch is still streaming OR any consumer still
        has undrained blocks — advancing then would silently truncate a
        slower consumer's epoch."""
        with self._lock:
            if epoch <= self._epoch:
                return True  # already started (or past)
            if not self._producer_done or any(self._queues):
                return False
            self._epoch = epoch
            self._queues = [deque() for _ in range(self._n)]
            self._rows = [0] * self._n
            self._producer_done = False
            self._producer_error = None
        t = threading.Thread(target=self._produce, args=(epoch,), daemon=True)
        t.start()
        return True

    def _produce(self, epoch: int):
        from ray_tpu.data.executor import StreamingExecutor

        # Fractional CPU: a row count must schedule even on a cluster
        # whose whole-CPU budget is held by trainer/accumulator actors.
        count_fn = rt.remote(_block_rows).options(
            max_retries=-1, num_cpus=0.01
        )
        try:
            executor = StreamingExecutor(list(self._stages))
            rr = 0
            for ref in executor.execute_iter(self._input_refs):
                if self._equal:
                    try:
                        nrows = rt.get(count_fn.remote(ref), timeout=120)
                    except Exception:  # noqa: BLE001 — fall back to RR
                        nrows = 1
                else:
                    nrows = 1
                with self._cond:
                    if self._equal:
                        target = min(range(self._n), key=lambda i: self._rows[i])
                    else:
                        target = rr % self._n
                        rr += 1
                    # Backpressure: stall until the chosen queue drains.
                    while (len(self._queues[target]) >= _split_queue_depth()
                           and self._epoch == epoch):
                        self._cond.wait(timeout=1.0)
                    if self._epoch != epoch:
                        return  # superseded (shutdown/restart)
                    self._queues[target].append(ref)
                    self._rows[target] += nrows
                    self._cond.notify_all()
        except Exception as e:  # noqa: BLE001 — surface to consumers
            with self._cond:
                self._producer_error = f"{type(e).__name__}: {e}"
        finally:
            with self._cond:
                self._producer_done = True
                self._cond.notify_all()

    def next_blocks(self, epoch: int, split_idx: int, max_blocks: int = 2):
        """Blocking pull: up to max_blocks refs for one split, or
        {"done": True} at end of the split's epoch stream."""
        with self._cond:
            while True:
                if self._producer_error:
                    raise RuntimeError(
                        f"streaming_split producer failed: {self._producer_error}"
                    )
                if epoch > self._epoch:
                    # Our epoch hasn't started yet (another consumer is
                    # still draining the previous one): wait for it.
                    self._cond.wait(timeout=1.0)
                    continue
                if epoch < self._epoch:
                    # Superseded. start_epoch refuses to advance while any
                    # queue holds blocks, so nothing was dropped — this
                    # consumer already drained its split.
                    return {"blocks": [], "done": True}
                q = self._queues[split_idx]
                if q:
                    out = [q.popleft() for _ in range(min(max_blocks, len(q)))]
                    self._cond.notify_all()
                    return {"blocks": out, "done": False}
                if self._producer_done:
                    return {"blocks": [], "done": True}
                self._cond.wait(timeout=1.0)

    def stats(self):
        with self._lock:
            return {"epoch": self._epoch, "rows_per_split": list(self._rows)}


class DataIterator:
    """Per-worker view of one split. Each iteration call (iter_rows /
    iter_batches / iter_blocks) consumes ONE epoch: the underlying
    pipeline re-executes per epoch, coordinated across the n iterators
    (reference: data/iterator.py DataIterator semantics)."""

    def __init__(self, coordinator, split_idx: int, n: int):
        self._coord = coordinator
        self._idx = split_idx
        self._n = n
        self._epoch = 0

    def iter_blocks(self) -> Iterator[Any]:
        import time as _time

        epoch = self._epoch
        self._epoch += 1
        # Idempotent across the n iterators; whoever arrives first starts
        # the epoch's producer. False = previous epoch still draining
        # elsewhere — retry until the coordinator can roll over.
        deadline = _time.monotonic() + 600
        while not rt.get(self._coord.start_epoch.remote(epoch)):
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"epoch {epoch} never started: another split is still "
                    "consuming the previous epoch"
                )
            _time.sleep(0.05)
        while True:
            out = rt.get(self._coord.next_blocks.remote(epoch, self._idx),
                         timeout=600)
            for ref in out["blocks"]:
                yield rt.get(ref)
            if out["done"]:
                return

    def stop(self):
        """Kill the shared coordinator actor (releases its hold on the
        dataset's input blocks). Call from the split's owner once ALL n
        iterators are finished — the trainer does this automatically."""
        try:
            rt.kill(self._coord)
        except Exception:  # noqa: BLE001 — already gone
            pass

    def iter_rows(self) -> Iterator[Any]:
        for block in self.iter_blocks():
            yield from B.block_to_rows(block)

    def iter_batches(self, batch_size: int = 256,
                     batch_format: str = "numpy") -> Iterator[Any]:
        rows: List[Any] = []
        for block in self.iter_blocks():
            rows.extend(B.block_to_rows(block))
            while len(rows) >= batch_size:
                chunk, rows = rows[:batch_size], rows[batch_size:]
                yield B.block_to_batch(B.block_from_rows(chunk), batch_format)
        if rows:
            yield B.block_to_batch(B.block_from_rows(rows), batch_format)

    def stats(self):
        return rt.get(self._coord.stats.remote())

    def __repr__(self):
        return f"DataIterator(split={self._idx}/{self._n}, epoch={self._epoch})"
