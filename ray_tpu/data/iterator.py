"""Streaming split iteration for train ingestion.

Analog of the reference's Dataset.streaming_split
(python/ray/data/dataset.py:1161) + StreamSplitDataIterator
(_internal/iterator/stream_split_iterator.py): one coordinator actor
drives the dataset's streaming executor per epoch and deals completed
output blocks to n consumer queues; each training worker holds a
DataIterator that pulls from its queue. Blocks flow while upstream tasks
are still running, and every epoch re-executes the pipeline (fresh
random_shuffle draws etc.).

`equal=True` is row-EXACT (the reference's semantics): blocks stream to
the least-loaded split, each split's most recent block is held back, and
at end of stream the holdbacks are sliced so every split delivers exactly
total // n rows (up to n-1 remainder rows dropped). Row-exact splits are
what keeps gang-SPMD training in lockstep — a skewed split means skewed
worker step counts and a stalled gang.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Iterator, List, Optional

import ray_tpu as rt
from ray_tpu.data import block as B

def _split_queue_depth() -> int:
    # Undelivered blocks buffered per split before the producer stalls
    # (consumer backpressure; reference: per-split output queue bounds).
    from ray_tpu._private.config import get_config

    return get_config().data_split_queue_depth


def _block_rows(block) -> int:
    return B.block_num_rows(block)


def _block_slice_rows(block, start: int, end: int):
    return B.block_slice(block, start, end)


@rt.remote
class _SplitCoordinator:
    """Owns one streaming execution per epoch and deals blocks to n
    split queues. max_concurrency must cover n blocked next_blocks()
    calls plus control calls (set at creation in streaming_split)."""

    def __init__(self, input_refs: List, stages_payload: bytes, n: int,
                 equal: bool):
        import cloudpickle

        self._input_refs = list(input_refs)
        self._stages = cloudpickle.loads(stages_payload)
        self._n = n
        self._equal = equal
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._epoch = -1          # epoch currently producing / produced
        self._queues: List[deque] = [deque() for _ in range(n)]
        self._rows: List[int] = [0] * n
        self._producer_done = True
        self._producer_error: Optional[str] = None

    def start_epoch(self, epoch: int) -> bool:
        """Idempotent across the n consumers: the first call for the next
        epoch starts its producer thread. Returns False (caller retries)
        while the previous epoch is still streaming OR any consumer still
        has undrained blocks — advancing then would silently truncate a
        slower consumer's epoch."""
        with self._lock:
            if epoch <= self._epoch:
                return True  # already started (or past)
            if not self._producer_done or any(self._queues):
                return False
            self._epoch = epoch
            self._queues = [deque() for _ in range(self._n)]
            self._rows = [0] * self._n
            self._producer_done = False
            self._producer_error = None
        t = threading.Thread(target=self._produce, args=(epoch,), daemon=True)
        t.start()
        return True

    def _deliver(self, epoch: int, target: int, ref) -> bool:
        """Queue one ref for a split with backpressure; False when the
        epoch was superseded."""
        with self._cond:
            while (len(self._queues[target]) >= _split_queue_depth()
                   and self._epoch == epoch):
                self._cond.wait(timeout=1.0)
            if self._epoch != epoch:
                return False
            self._queues[target].append(ref)
            self._cond.notify_all()
        return True

    def _produce(self, epoch: int):
        from ray_tpu.data.executor import StreamingExecutor

        # Fractional CPU: row counting / boundary slicing must schedule
        # even on a cluster whose whole-CPU budget is held by
        # trainer/accumulator actors.
        count_fn = rt.remote(_block_rows).options(
            max_retries=-1, num_cpus=0.01
        )
        try:
            executor = StreamingExecutor(list(self._stages))
            rr = 0
            # equal=True state: each split's most recent block stays held
            # back (ref, nrows) so end-of-stream can slice the boundary.
            holds: List = [None] * self._n
            delivered = [0] * self._n
            for ref in executor.execute_iter(self._input_refs):
                if not self._equal:
                    with self._cond:
                        target = rr % self._n
                        rr += 1
                    if not self._deliver(epoch, target, ref):
                        return
                    continue
                nrows = rt.get(count_fn.remote(ref), timeout=120)
                with self._lock:
                    target = min(range(self._n), key=lambda i: self._rows[i])
                    self._rows[target] += nrows
                if holds[target] is not None:
                    prev_ref, prev_rows = holds[target]
                    if not self._deliver(epoch, target, prev_ref):
                        return
                    delivered[target] += prev_rows
                holds[target] = (ref, nrows)
            if self._equal and not self._finish_equal(
                epoch, holds, delivered
            ):
                return
        except Exception as e:  # noqa: BLE001 — surface to consumers
            with self._cond:
                self._producer_error = f"{type(e).__name__}: {e}"
        finally:
            with self._cond:
                self._producer_done = True
                self._cond.notify_all()

    def _finish_equal(self, epoch: int, holds: List,
                      delivered: List[int]) -> bool:
        """End-of-stream equalizer: slice the held-back boundary blocks
        so every split delivers exactly total // n rows (reference:
        dataset.py:1161 equal=True semantics; up to n-1 remainder rows
        drop). The greedy least-loaded invariant guarantees each split's
        excess over the global share fits inside its own holdback."""
        slice_fn = rt.remote(_block_slice_rows).options(
            max_retries=-1, num_cpus=0.01
        )
        total = sum(delivered) + sum(h[1] for h in holds if h)
        share = total // self._n
        pool: deque = deque()  # (ref, offset, remaining) spare rows
        plans: List[List] = [[] for _ in range(self._n)]
        needs = [0] * self._n
        for i in range(self._n):
            need = share - delivered[i]
            if holds[i] is not None:
                ref, nrows = holds[i]
                take = min(need, nrows)
                if take == nrows:
                    plans[i].append((ref, nrows))
                elif take > 0:
                    plans[i].append((slice_fn.remote(ref, 0, take), take))
                if nrows - take > 0:
                    pool.append((ref, take, nrows - take))
                need -= take
            needs[i] = need
        for i in range(self._n):
            while needs[i] > 0:
                ref, off, rem = pool.popleft()
                take = min(needs[i], rem)
                plans[i].append(
                    (slice_fn.remote(ref, off, off + take), take)
                )
                needs[i] -= take
                if rem - take > 0:
                    pool.appendleft((ref, off + take, rem - take))
        for i, plan in enumerate(plans):
            for ref, nrows in plan:
                if not self._deliver(epoch, i, ref):
                    return False
        with self._lock:
            self._rows = [share] * self._n
        return True

    def next_blocks(self, epoch: int, split_idx: int, max_blocks: int = 2):
        """Blocking pull: up to max_blocks refs for one split, or
        {"done": True} at end of the split's epoch stream."""
        with self._cond:
            while True:
                if self._producer_error:
                    raise RuntimeError(
                        f"streaming_split producer failed: {self._producer_error}"
                    )
                if epoch > self._epoch:
                    # Our epoch hasn't started yet (another consumer is
                    # still draining the previous one): wait for it.
                    self._cond.wait(timeout=1.0)
                    continue
                if epoch < self._epoch:
                    # Superseded. start_epoch refuses to advance while any
                    # queue holds blocks, so nothing was dropped — this
                    # consumer already drained its split.
                    return {"blocks": [], "done": True}
                q = self._queues[split_idx]
                if q:
                    out = [q.popleft() for _ in range(min(max_blocks, len(q)))]
                    self._cond.notify_all()
                    return {"blocks": out, "done": False}
                if self._producer_done:
                    return {"blocks": [], "done": True}
                self._cond.wait(timeout=1.0)

    def stats(self):
        with self._lock:
            return {"epoch": self._epoch, "rows_per_split": list(self._rows)}


class DataIterator:
    """Per-worker view of one split. Each iteration call (iter_rows /
    iter_batches / iter_blocks) consumes ONE epoch: the underlying
    pipeline re-executes per epoch, coordinated across the n iterators
    (reference: data/iterator.py DataIterator semantics)."""

    def __init__(self, coordinator, split_idx: int, n: int,
                 prefetch_blocks: Optional[int] = None):
        from ray_tpu._private.config import get_config

        self._coord = coordinator
        self._idx = split_idx
        self._n = n
        self._epoch = 0
        # Blocks requested per coordinator round trip AND pulled ahead of
        # consumption; threaded from train.DataConfig(prefetch_blocks=...)
        # (config default: data_iterator_prefetch_blocks).
        self._prefetch_blocks = (
            get_config().data_iterator_prefetch_blocks
            if prefetch_blocks is None else int(prefetch_blocks)
        )

    def iter_blocks(self) -> Iterator[Any]:
        import time as _time

        epoch = self._epoch
        self._epoch += 1
        # Idempotent across the n iterators; whoever arrives first starts
        # the epoch's producer. False = previous epoch still draining
        # elsewhere — retry until the coordinator can roll over.
        deadline = _time.monotonic() + 600
        while not rt.get(self._coord.start_epoch.remote(epoch)):
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"epoch {epoch} never started: another split is still "
                    "consuming the previous epoch"
                )
            _time.sleep(0.05)
        max_blocks = max(1, self._prefetch_blocks)
        while True:
            out = rt.get(
                self._coord.next_blocks.remote(epoch, self._idx, max_blocks),
                timeout=600,
            )
            # Start every granted block's pull at once; the per-ref gets
            # below then overlap transfer with downstream batch work.
            rt.prefetch(out["blocks"])
            for ref in out["blocks"]:
                yield rt.get(ref)
            if out["done"]:
                return

    def stop(self):
        """Kill the shared coordinator actor (releases its hold on the
        dataset's input blocks). Call from the split's owner once ALL n
        iterators are finished — the trainer does this automatically."""
        try:
            rt.kill(self._coord)
        except Exception:  # noqa: BLE001 — already gone
            pass

    def iter_rows(self) -> Iterator[Any]:
        for block in self.iter_blocks():
            yield from B.block_to_rows(block)

    def iter_batches(self, batch_size: int = 256,
                     batch_format: str = "numpy",
                     prefetch_batches: Optional[int] = None) -> Iterator[Any]:
        """Re-batch this split's epoch stream. By default (prefetch_batches
        = config.data_feed_prefetch_batches) the pull + assembly runs on a
        background producer thread that stays that many ready batches
        ahead of the training step (data/feed.py), so trainer workers get
        the pipelined feed through session.get_dataset_shard with no code
        change; 0 assembles inline. Feed timings land in feed_stats()."""
        if prefetch_batches is None:
            from ray_tpu._private.config import get_config

            prefetch_batches = get_config().data_feed_prefetch_batches
        if prefetch_batches and prefetch_batches > 0:
            from ray_tpu.data.feed import FeedStats, _DevicePrefetcher

            self._last_feed_stats = FeedStats()
            return _DevicePrefetcher(
                lambda: self._iter_batches_local(batch_size, batch_format),
                depth=prefetch_batches,
                stats=self._last_feed_stats,
                name=f"split{self._idx}",
            )
        return self._iter_batches_local(batch_size, batch_format)

    def _iter_batches_local(self, batch_size: int,
                            batch_format: str) -> Iterator[Any]:
        rows: List[Any] = []
        for block in self.iter_blocks():
            rows.extend(B.block_to_rows(block))
            while len(rows) >= batch_size:
                chunk, rows = rows[:batch_size], rows[batch_size:]
                yield B.block_to_batch(B.block_from_rows(chunk), batch_format)
        if rows:
            yield B.block_to_batch(B.block_from_rows(rows), batch_format)

    def feed_stats(self):
        """Snapshot of the newest prefetching iter_batches pipeline's
        timings (None before one runs)."""
        stats = getattr(self, "_last_feed_stats", None)
        return None if stats is None else stats.snapshot()

    def stats(self):
        return rt.get(self._coord.stats.remote())

    def __repr__(self):
        return f"DataIterator(split={self._idx}/{self._n}, epoch={self._epoch})"
