"""Aggregate functions: distributed partial-aggregate / merge / finalize.

Analog of the reference's AggregateFn family (python/ray/data/aggregate.py:
Count/Sum/Min/Max/Mean/Std...). Each block computes a partial state in a
remote task; the driver merges the (tiny) states and finalizes — rows
never pass through the driver.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from ray_tpu.data import block as B


class AggregateFn:
    """One aggregation: block -> partial state, state x state -> state,
    state -> value."""

    name = "agg"

    def partial(self, rows: List[dict]) -> Any:
        raise NotImplementedError

    def merge(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def finalize(self, state: Any) -> Any:
        return state


class Count(AggregateFn):
    name = "count()"

    def partial(self, rows):
        return len(rows)

    def merge(self, a, b):
        return a + b


class Sum(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        self.name = f"sum({on})"

    def partial(self, rows):
        return sum(r[self.on] for r in rows)

    def merge(self, a, b):
        return a + b


class Min(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        self.name = f"min({on})"

    def partial(self, rows):
        return min((r[self.on] for r in rows), default=None)

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return min(a, b)


class Max(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        self.name = f"max({on})"

    def partial(self, rows):
        return max((r[self.on] for r in rows), default=None)

    def merge(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return max(a, b)


class Mean(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        self.name = f"mean({on})"

    def partial(self, rows) -> Tuple[float, int]:
        return (sum(r[self.on] for r in rows), len(rows))

    def merge(self, a, b):
        return (a[0] + b[0], a[1] + b[1])

    def finalize(self, state):
        total, count = state
        return total / count if count else None


class Std(AggregateFn):
    """Sample standard deviation via Chan et al.'s parallel variance
    merge (count/mean/M2 states combine exactly across blocks)."""

    def __init__(self, on: str, ddof: int = 1):
        self.on = on
        self.ddof = ddof
        self.name = f"std({on})"

    def partial(self, rows) -> Tuple[int, float, float]:
        n, mean, m2 = 0, 0.0, 0.0
        for r in rows:
            x = float(r[self.on])
            n += 1
            d = x - mean
            mean += d / n
            m2 += d * (x - mean)
        return (n, mean, m2)

    def merge(self, a, b):
        na, ma, m2a = a
        nb, mb, m2b = b
        n = na + nb
        if n == 0:
            return (0, 0.0, 0.0)
        delta = mb - ma
        mean = ma + delta * nb / n
        m2 = m2a + m2b + delta * delta * na * nb / n
        return (n, mean, m2)

    def finalize(self, state):
        n, _, m2 = state
        if n <= self.ddof:
            return None
        return (m2 / (n - self.ddof)) ** 0.5


def partial_states(block, aggs: List[AggregateFn]) -> List[Any]:
    """Remote-task body: all aggregates' partial states for one block."""
    rows = B.block_to_rows(block)
    return [agg.partial(rows) for agg in aggs]


def merge_states(states: List[List[Any]], aggs: List[AggregateFn]) -> List[Any]:
    """Driver-side merge of per-block partial states, then finalize."""
    out = []
    for i, agg in enumerate(aggs):
        acc: Optional[Any] = None
        first = True
        for s in states:
            acc = s[i] if first else agg.merge(acc, s[i])
            first = False
        out.append(agg.finalize(acc) if not first else None)
    return out
