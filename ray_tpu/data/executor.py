"""Streaming executor: pulls blocks through operator stages with bounded
in-flight work.

Analog of the reference's StreamingExecutor
(data/_internal/execution/streaming_executor.py:57; scheduling loop :242)
over PhysicalOperators (execution/interfaces/physical_operator.py:136) with
backpressure (execution/backpressure_policy/): each map stage keeps at most
`max_in_flight` block tasks outstanding; completed output refs flow to the
next stage immediately (no stage barrier).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

import ray_tpu as rt


@dataclass
class MapStage:
    """A per-block transform executed as remote tasks."""

    fn: Callable  # Block -> Block
    name: str = "map"
    max_in_flight: int = 4
    resources: Optional[dict] = None


@dataclass
class AllToAllStage:
    """A barrier stage consuming all blocks at once (shuffle/sort/repartition)."""

    fn: Callable  # List[block_ref] -> List[block_ref]
    name: str = "all_to_all"


def _apply_block_fn(fn, block):
    return fn(block)


class StreamingExecutor:
    def __init__(self, stages: List[Any], max_in_flight: int = 4):
        self.stages = stages
        self.max_in_flight = max_in_flight

    def execute(self, input_refs: List) -> List:
        """Run the stage pipeline over input block refs; returns output refs."""
        refs = list(input_refs)
        pending_stages = list(self.stages)
        for stage in pending_stages:
            if isinstance(stage, AllToAllStage):
                refs = stage.fn(refs)
            else:
                refs = self._run_map_stage(stage, refs)
        return refs

    def _run_map_stage(self, stage: MapStage, input_refs: List) -> List:
        """Bounded-concurrency map over blocks (backpressure policy)."""
        remote_fn = rt.remote(_apply_block_fn)
        if stage.resources:
            remote_fn = remote_fn.options(resources=stage.resources)
        out: List = []
        in_flight: List = []
        queue = list(input_refs)
        while queue or in_flight:
            while queue and len(in_flight) < max(stage.max_in_flight, 1):
                block_ref = queue.pop(0)
                in_flight.append(remote_fn.remote(stage.fn, block_ref))
            ready, in_flight = rt.wait(
                in_flight, num_returns=1, timeout=60.0
            )
            out.extend(ready)
            if not ready and in_flight:
                # Nothing completed within the window; keep waiting.
                time.sleep(0.01)
        return out
