"""Streaming executor: blocks flow through operator chains with bounded
in-flight work and no per-stage barrier.

Analog of the reference's StreamingExecutor
(data/_internal/execution/streaming_executor.py:57; scheduling loop :242)
over PhysicalOperators (execution/interfaces/physical_operator.py:136) with
backpressure (execution/backpressure_policy/):

  * consecutive map stages are CHAINED per block — block i's stage-2 task
    is submitted the moment its stage-1 task is, with the stage-1 output
    ref as a dependency, so stage 2 starts on block i while block j is
    still in stage 1 (true streaming, no stage barrier);
  * at most `max_in_flight` blocks ride the chain at once — completed
    chains admit new blocks (bounded memory: with spilling this is the
    out-of-core path);
  * AllToAllStages (shuffle/sort/repartition) are inherent barriers.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

import ray_tpu as rt


@dataclass
class MapStage:
    """A per-block transform executed as remote tasks."""

    fn: Callable  # Block -> Block  (or (Block, index) with with_index)
    name: str = "map"
    max_in_flight: int = 4
    resources: Optional[dict] = None
    # fn receives the block's position as a second arg (e.g. per-block
    # seed salting for sampling).
    with_index: bool = False


@dataclass
class AllToAllStage:
    """A barrier stage consuming all blocks at once (shuffle/sort/repartition)."""

    fn: Callable  # List[block_ref] -> List[block_ref]
    name: str = "all_to_all"


def _apply_block_fn(fn, block):
    return fn(block)


def _apply_block_fn_indexed(fn, block, index):
    return fn(block, index)


class StreamingExecutor:
    def __init__(self, stages: List[Any], max_in_flight: int = 4):
        self.stages = stages
        self.max_in_flight = max_in_flight
        # Per-stage-run execution stats (reference: Dataset.stats(),
        # _internal/stats.py): [{"stage", "blocks", "wall_s"}].
        self.stats: List[dict] = []

    def execute(self, input_refs: List) -> List:
        """Run the stage pipeline over input block refs; returns output refs."""
        refs = list(input_refs)
        # Split into runs of map stages separated by all-to-all barriers.
        run: List[MapStage] = []
        for stage in self.stages:
            if isinstance(stage, AllToAllStage):
                if run:
                    refs = self._timed(
                        "+".join(s.name for s in run),
                        lambda r=run, x=refs: self._run_map_chain(r, x),
                        len(refs),
                    )
                    run = []
                refs = self._timed(
                    stage.name, lambda s=stage, x=refs: s.fn(x), len(refs)
                )
            else:
                run.append(stage)
        if run:
            refs = self._timed(
                "+".join(s.name for s in run),
                lambda r=run, x=refs: self._run_map_chain(r, x),
                len(refs),
            )
        return refs

    def _timed(self, name: str, fn, n_blocks: int):
        start = time.perf_counter()
        out = fn()
        self.stats.append({
            "stage": name,
            "blocks": n_blocks,
            "wall_s": round(time.perf_counter() - start, 4),
        })
        return out

    def _run_map_chain(self, stages: List[MapStage], input_refs: List) -> List:
        """Pipeline a run of map stages: per-block task chains, bounded
        number of blocks in flight (the backpressure window)."""
        remote_fns = []
        for st in stages:
            # Block transforms are deterministic + idempotent: retry
            # worker crashes forever (the reference's data-task default).
            f = rt.remote(
                _apply_block_fn_indexed if st.with_index else _apply_block_fn
            ).options(max_retries=-1)
            if st.resources:
                f = f.options(resources=st.resources)
            remote_fns.append((f, st.fn, st.with_index))
        cap = max(min(st.max_in_flight for st in stages), 1)
        queue = deque(enumerate(input_refs))
        pending: dict = {}  # chained ref -> original block index
        out: List = [None] * len(input_refs)
        while queue or pending:
            while queue and len(pending) < cap:
                idx, ref = queue.popleft()
                for f, fn, with_index in remote_fns:
                    if with_index:
                        ref = f.remote(fn, ref, idx)
                    else:
                        ref = f.remote(fn, ref)
                pending[ref] = idx
            ready, _ = rt.wait(list(pending), num_returns=1, timeout=60.0)
            for r in ready:
                # Results land at their ORIGINAL positions: consumers (zip,
                # ordered iteration) rely on block order surviving the
                # completion-order wait.
                out[pending.pop(r)] = r
            if not ready and pending:
                time.sleep(0.01)
        return out
