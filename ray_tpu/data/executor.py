"""Streaming executor: blocks flow through an operator graph with
per-operator in-flight windows and no stage barriers.

Analog of the reference's StreamingExecutor
(data/_internal/execution/streaming_executor.py:57; scheduling loop :242)
over PhysicalOperators (execution/interfaces/physical_operator.py:136),
with TaskPool/ActorPool map operators
(execution/operators/actor_pool_map_operator.py) and backpressure
policies (execution/backpressure_policy/). Design differences are
deliberate: the logical plan is the list of Stage dataclasses a Dataset
accumulates, compiled here into physical operators — fusion merges
adjacent compatible map stages into one task per block, and the driver
loop moves blocks between operator queues as completions arrive.

Execution model per scheduling tick:
  1. drain completed tasks from every operator into its output queue;
  2. pull outputs downstream while the downstream operator has queue
     room (per-operator backpressure: a slow operator's backlog stalls
     its upstream, not the whole pipeline);
  3. submit new work for any operator with input + window room, subject
     to a global in-flight budget derived from cluster CPUs
     (resource-aware backpressure, ConcurrencyCapBackpressurePolicy
     analog);
  4. block in rt.wait on the union of in-flight refs.

ActorPoolMapOperator keeps stateful workers (e.g. a compiled TPU model
loaded once in the actor's __init__) and routes blocks to the
least-loaded live actor — the TPU batch-inference path.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import ray_tpu as rt


# ---------------------------------------------------------------------------
# Logical stages (what Dataset accumulates)
# ---------------------------------------------------------------------------


@dataclass
class MapStage:
    """A per-block transform executed as remote tasks."""

    fn: Callable  # Block -> Block  (or (Block, index) with with_index)
    name: str = "map"
    max_in_flight: int = 4
    resources: Optional[dict] = None
    # fn receives the block's position as a second arg (e.g. per-block
    # seed salting for sampling).
    with_index: bool = False


@dataclass
class ActorPoolStage:
    """A per-block transform on a pool of stateful actors.

    `factory` builds the per-actor state once (e.g. load + jit a model);
    `fn(state, block)` transforms each block. The reference expresses
    this as a callable class + ActorPoolStrategy
    (actor_pool_map_operator.py)."""

    factory: Callable[[], Any]
    fn: Callable[[Any, Any], Any]
    name: str = "actor_map"
    pool_size: int = 2
    max_in_flight_per_actor: int = 2
    resources: Optional[dict] = None


@dataclass
class ActorPoolStrategy:
    """User-facing knob for `Dataset.map_batches(..., compute=...)` —
    run the UDF as a pool of stateful actors (reference:
    ray.data.ActorPoolStrategy)."""

    size: int = 2
    max_tasks_in_flight_per_actor: int = 2


@dataclass
class AllToAllStage:
    """A barrier stage consuming all blocks at once (shuffle/sort/repartition)."""

    fn: Callable  # List[block_ref] -> List[block_ref]
    name: str = "all_to_all"


def _apply_fused(fns, block, index=None):
    for fn, with_index in fns:
        block = fn(block, index) if with_index else fn(block)
    return block


# ---------------------------------------------------------------------------
# Physical operators
# ---------------------------------------------------------------------------


class _PhysicalOp:
    """One node of the physical plan: input queue -> tasks -> output queue."""

    name: str = "op"

    def __init__(self, max_in_flight: int):
        self.inq: deque = deque()  # (idx, ref)
        self.outq: deque = deque()  # (idx, ref)
        self.inflight: Dict[Any, int] = {}  # result ref -> idx
        self.max_in_flight = max_in_flight
        self.upstream_done = False
        self.submitted = 0

    # -- scheduling interface -------------------------------------------
    def can_submit(self) -> bool:
        # Backlog guard: stop feeding tasks when our consumer is behind —
        # the per-operator backpressure that bounds intermediate memory.
        return (
            bool(self.inq)
            and len(self.inflight) < self.max_in_flight
            and len(self.outq) < 2 * self.max_in_flight
        )

    def submit_one(self) -> None:
        raise NotImplementedError

    def drain_completed(self, ready: set) -> None:
        for ref in [r for r in self.inflight if r in ready]:
            self.outq.append((self.inflight.pop(ref), ref))

    def done(self) -> bool:
        return self.upstream_done and not self.inq and not self.inflight

    def wait_refs(self) -> List:
        return list(self.inflight)

    def close(self) -> None:
        pass


class TaskMapOperator(_PhysicalOp):
    """Fused run of map stages: ONE task per block applies every fn."""

    def __init__(self, stages: List[MapStage]):
        super().__init__(max(min(s.max_in_flight for s in stages), 1))
        self.name = "+".join(s.name for s in stages)
        self._fns = [(s.fn, s.with_index) for s in stages]
        self._needs_index = any(s.with_index for s in stages)
        resources = stages[0].resources
        # Deterministic + idempotent block transforms: retry worker
        # crashes forever (the reference's data-task default).
        f = rt.remote(_apply_fused).options(max_retries=-1)
        if resources:
            f = f.options(resources=resources)
        self._remote = f

    def submit_one(self) -> None:
        idx, ref = self.inq.popleft()
        if self._needs_index:
            out = self._remote.remote(self._fns, ref, idx)
        else:
            out = self._remote.remote(self._fns, ref)
        self.inflight[out] = idx
        self.submitted += 1


class _PoolActor:
    """Generic stateful block worker (module level so workers can
    unpickle it by reference)."""

    def __init__(self, factory):
        self.state = factory()

    def apply(self, fn, block):
        return fn(self.state, block)


class ActorPoolMapOperator(_PhysicalOp):
    """Routes blocks to a fixed pool of stateful actors, least-loaded
    first (actor_pool_map_operator.py; power-of-two is unnecessary here —
    the driver sees exact per-actor in-flight counts)."""

    def __init__(self, stage: ActorPoolStage):
        super().__init__(
            max(stage.pool_size * stage.max_in_flight_per_actor, 1)
        )
        self.name = stage.name
        self._stage = stage
        self._actors: List = []
        self._per_actor: Dict[int, int] = {}  # actor index -> in-flight
        self._ref_actor: Dict[Any, int] = {}  # result ref -> actor index
        self._started = False

    def _ensure_pool(self) -> None:
        if self._started:
            return
        self._started = True
        cls = rt.remote(_PoolActor)
        if self._stage.resources:
            cls = cls.options(resources=self._stage.resources)
        for i in range(self._stage.pool_size):
            self._actors.append(cls.remote(self._stage.factory))
            self._per_actor[i] = 0

    def can_submit(self) -> bool:
        if not super().can_submit():
            return False
        self._ensure_pool()
        cap = self._stage.max_in_flight_per_actor
        return any(v < cap for v in self._per_actor.values())

    def submit_one(self) -> None:
        idx, ref = self.inq.popleft()
        ai = min(self._per_actor, key=self._per_actor.get)
        out = self._actors[ai].apply.remote(self._stage.fn, ref)
        self._per_actor[ai] += 1
        self._ref_actor[out] = ai
        self.inflight[out] = idx
        self.submitted += 1

    def drain_completed(self, ready: set) -> None:
        for ref in [r for r in self.inflight if r in ready]:
            self.outq.append((self.inflight.pop(ref), ref))
            ai = self._ref_actor.pop(ref, None)
            if ai is not None:
                self._per_actor[ai] -= 1

    def close(self) -> None:
        for a in self._actors:
            try:
                rt.kill(a)
            except Exception:  # noqa: BLE001
                pass
        self._actors.clear()


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------


def _fuse(stages: List[Any]) -> List[Any]:
    """Merge adjacent MapStages with identical resource shapes into one
    operator — one task per block instead of one per stage per block
    (the reference's OperatorFusionRule, _internal/logical/rules)."""
    out: List[Any] = []
    run: List[MapStage] = []
    for s in stages:
        if isinstance(s, MapStage) and (
            not run or run[-1].resources == s.resources
        ):
            run.append(s)
            continue
        if run:
            out.append(TaskMapOperator(run))
            run = []
        if isinstance(s, MapStage):
            run = [s]
        elif isinstance(s, ActorPoolStage):
            out.append(ActorPoolMapOperator(s))
        else:
            out.append(s)  # AllToAllStage stays logical (barrier)
    if run:
        out.append(TaskMapOperator(run))
    return out


class StreamingExecutor:
    def __init__(self, stages: List[Any], max_in_flight: int = 4,
                 cpu_budget: Optional[int] = None):
        self.stages = stages
        self.max_in_flight = max_in_flight
        # Global concurrency budget: total in-flight block tasks across
        # every operator is capped near the cluster's CPU count so a deep
        # pipeline cannot oversubscribe the node (resource-aware
        # backpressure; reference: backpressure_policy/concurrency_cap).
        self._cpu_budget = cpu_budget
        # Per-stage-run execution stats (reference: Dataset.stats(),
        # _internal/stats.py): [{"stage", "blocks", "wall_s", "tasks"}].
        self.stats: List[dict] = []

    def _budget(self) -> int:
        if self._cpu_budget is None:
            try:
                cpus = rt.cluster_resources().get("CPU", 4)
            except Exception:  # noqa: BLE001
                cpus = 4
            from ray_tpu._private.config import get_config

            self._cpu_budget = max(
                int(cpus * get_config().data_cpu_budget_factor), 4
            )
        return self._cpu_budget

    def execute(self, input_refs: List) -> List:
        """Run the stage pipeline over input block refs; returns output refs."""
        refs = list(input_refs)
        plan = _fuse(self.stages)
        # Split at barriers; each segment streams internally.
        segment: List[_PhysicalOp] = []
        for op in plan:
            if isinstance(op, AllToAllStage):
                if segment:
                    refs = self._timed_ops(segment, refs)
                    segment = []
                refs = self._timed(op.name, lambda o=op, x=refs: o.fn(x),
                                   len(refs))
            else:
                segment.append(op)
        if segment:
            refs = self._timed_ops(segment, refs)
        return refs

    def _timed(self, name: str, fn, n_blocks: int):
        start = time.perf_counter()
        out = fn()
        self.stats.append({
            "stage": name,
            "blocks": n_blocks,
            "wall_s": round(time.perf_counter() - start, 4),
        })
        return out

    def _timed_ops(self, ops: List[_PhysicalOp], refs: List) -> List:
        start = time.perf_counter()
        out = self._run_segment(ops, refs)
        self.stats.append({
            "stage": "->".join(op.name for op in ops),
            "blocks": len(refs),
            "tasks": sum(op.submitted for op in ops),
            "wall_s": round(time.perf_counter() - start, 4),
        })
        return out

    def execute_iter(self, input_refs: List) -> "Iterator":
        """Streaming variant of execute(): yields output block refs of the
        FINAL pipeline segment as they complete (completion order), so a
        consumer (streaming_split's coordinator) can hand blocks to
        trainers while upstream tasks are still running. Barrier stages
        (all-to-all) still synchronize internally."""
        refs = list(input_refs)
        plan = _fuse(self.stages)
        segments: List = []
        cur: List[_PhysicalOp] = []
        for op in plan:
            if isinstance(op, AllToAllStage):
                if cur:
                    segments.append(("ops", cur))
                    cur = []
                segments.append(("barrier", op))
            else:
                cur.append(op)
        if cur:
            segments.append(("ops", cur))
        if not segments:
            yield from refs
            return
        for kind, seg in segments[:-1]:
            refs = seg.fn(refs) if kind == "barrier" else (
                self._run_segment(seg, refs)
            )
        kind, last = segments[-1]
        if kind == "barrier":
            yield from last.fn(refs)
        else:
            for _idx, ref in self._run_segment_iter(last, refs):
                yield ref

    def _run_segment(self, ops: List[_PhysicalOp], input_refs: List) -> List:
        """Drive a barrier-free run of operators to completion; results in
        input order."""
        out: List = [None] * len(input_refs)
        for idx, ref in self._run_segment_iter(ops, input_refs):
            out[idx] = ref
        return out

    def _run_segment_iter(self, ops: List[_PhysicalOp], input_refs: List):
        """Generator core: yields (input_index, output_ref) as blocks
        finish the segment."""
        source = deque(enumerate(input_refs))
        budget = self._budget()
        n_done = 0
        try:
            while n_done < len(input_refs):
                # 1+2. Move data downstream (last op first so freshly
                # drained outputs don't double-hop in one tick).
                for i in range(len(ops) - 1, -1, -1):
                    op = ops[i]
                    sink = ops[i + 1] if i + 1 < len(ops) else None
                    while op.outq:
                        if sink is not None:
                            if len(sink.inq) >= 2 * sink.max_in_flight:
                                break  # downstream backlog: stall upstream
                            sink.inq.append(op.outq.popleft())
                        else:
                            idx, ref = op.outq.popleft()
                            # _run_segment lands results at their ORIGINAL
                            # positions: consumers (zip, ordered
                            # iteration) rely on block order surviving
                            # completion order.
                            n_done += 1
                            yield idx, ref
                # Feed the first operator from the source.
                first = ops[0]
                while source and len(first.inq) < 2 * first.max_in_flight:
                    first.inq.append(source.popleft())
                first.upstream_done = not source
                for i in range(1, len(ops)):
                    ops[i].upstream_done = ops[i - 1].done()
                # 3. Submit under the global budget.
                total_inflight = sum(len(op.inflight) for op in ops)
                for op in ops:
                    while op.can_submit() and total_inflight < budget:
                        op.submit_one()
                        total_inflight += 1
                if n_done >= len(input_refs):
                    break
                # 4. Wait for any completion anywhere.
                all_refs = [r for op in ops for r in op.wait_refs()]
                if not all_refs:
                    time.sleep(0.005)
                    continue
                ready, _ = rt.wait(all_refs, num_returns=1, timeout=60.0)
                ready_set = set(ready)
                for op in ops:
                    op.drain_completed(ready_set)
        finally:
            for op in ops:
                op.close()
