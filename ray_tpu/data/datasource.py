"""Datasource / Datasink plugin surface.

Analog of the reference's pluggable IO layer
(python/ray/data/datasource/datasource.py: Datasource.get_read_tasks /
ReadTask, and datasink.py: Datasink.on_write_start/write/on_write_complete).
A Datasource turns itself into independent read tasks (each a plain
callable producing blocks, executed in remote workers so rows never pass
through the driver); a Datasink receives one write call per block plus
job-level start/complete/failed hooks.

The built-in file formats (parquet/csv/json/text/binary/numpy/range) are
implemented on this surface — the same extension point user formats use.
"""

from __future__ import annotations

import glob as _glob
import os
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Iterable, List, Optional

from ray_tpu.data import block as B


class ReadTask:
    """One unit of parallel read work: a no-arg callable returning an
    iterable of blocks, plus optional metadata (row-count/size estimates
    used for scheduling hints)."""

    def __init__(self, read_fn: Callable[[], Iterable[Any]],
                 metadata: Optional[Dict] = None):
        self.read_fn = read_fn
        self.metadata = metadata or {}

    def __call__(self) -> List[Any]:
        return list(self.read_fn())


class Datasource(ABC):
    """Produces ReadTasks for parallel ingestion (reference:
    datasource.py:Datasource)."""

    @abstractmethod
    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        """Split this source into up to `parallelism` independent reads."""

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def get_name(self) -> str:
        return type(self).__name__


class Datasink(ABC):
    """Receives blocks from parallel write tasks (reference:
    datasink.py:Datasink)."""

    def on_write_start(self) -> None:
        """Driver-side hook before any write task runs."""

    @abstractmethod
    def write(self, block: Any, ctx: Dict) -> Any:
        """Write one block (runs in a remote worker). `ctx` carries
        {"task_index": int}. Returns a result collected by
        on_write_complete."""

    def on_write_complete(self, write_results: List[Any]) -> None:
        """Driver-side hook after every write task succeeded."""

    def on_write_failed(self, error: Exception) -> None:
        """Driver-side hook when any write task failed."""

    def get_name(self) -> str:
        return type(self).__name__


# ---------------------------------------------------------------------------
# File-based sources
# ---------------------------------------------------------------------------


class FileBasedDatasource(Datasource):
    """Shared machinery for one-file-per-read-task formats: expands a
    path or directory glob, one ReadTask per file (reference:
    file_based_datasource.py). Cloud URIs (s3:// gs:// hdfs:// ...)
    resolve through pyarrow.fs — the same layer checkpoint storage and
    spilling ride; an explicit `filesystem` overrides resolution (tests
    inject local fakes for cloud-shaped paths)."""

    _GLOB = "*"

    def __init__(self, path: str, filesystem=None):
        self.path = path
        self.filesystem = filesystem

    def _fs(self):
        """(pyarrow FileSystem, fs-local base path) or (None, local path)."""
        if self.filesystem is not None:
            return self.filesystem, self.path.split("://", 1)[-1]
        if "://" in self.path and not self.path.startswith("file://"):
            import pyarrow.fs as pafs

            return pafs.FileSystem.from_uri(self.path)
        return None, self.path.removeprefix("file://")

    def _paths(self) -> List[str]:
        import fnmatch

        fs, base = self._fs()
        if fs is None:
            if os.path.isdir(base):
                paths = sorted(_glob.glob(os.path.join(base, self._GLOB)))
            else:
                paths = sorted(_glob.glob(base)) or [base]
        else:
            import pyarrow.fs as pafs

            info = fs.get_file_info(base)
            if info.type == pafs.FileType.Directory:
                sel = pafs.FileSelector(base, recursive=False)
                paths = sorted(
                    f.path for f in fs.get_file_info(sel)
                    if f.is_file and fnmatch.fnmatch(
                        os.path.basename(f.path), self._GLOB
                    )
                )
            else:
                paths = [base]
        if not paths:
            raise FileNotFoundError(
                f"no {self._GLOB} files under {self.path!r}"
            )
        return paths

    @abstractmethod
    def _read_file(self, path: str) -> Any:
        """Parse one file into a block (runs in a remote worker). `path`
        is opened through _open (local or pyarrow.fs)."""

    def _open(self, path: str, mode: str = "rb", seekable: bool = False):
        fs, _ = self._fs()
        if fs is None:
            return open(path, mode)
        # Parquet readers need random access; sequential formats stream.
        return (fs.open_input_file(path) if seekable
                else fs.open_input_stream(path))

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        read = self._read_file
        return [
            ReadTask(
                (lambda p=p: [read(p)]),
                {"path": p, "size_bytes": self._safe_size(p)},
            )
            for p in self._paths()
        ]

    def _safe_size(self, p: str) -> Optional[int]:
        fs, _ = self._fs()
        if fs is None:
            return _safe_size(p)
        try:
            info = fs.get_file_info(p)
            return info.size if info.is_file else None
        except Exception:  # noqa: BLE001
            return None

    def estimate_inmemory_data_size(self) -> Optional[int]:
        try:
            return sum(self._safe_size(p) or 0 for p in self._paths())
        except FileNotFoundError:
            return None


def _safe_size(p: str) -> Optional[int]:
    try:
        return os.path.getsize(p)
    except OSError:
        return None


class ParquetDatasource(FileBasedDatasource):
    _GLOB = "*.parquet"

    def _read_file(self, path: str):
        import pyarrow.parquet as pq

        with self._open(path, seekable=True) as f:
            return pq.read_table(f)


class CSVDatasource(FileBasedDatasource):
    _GLOB = "*.csv"

    def _read_file(self, path: str):
        import pyarrow.csv as pacsv

        with self._open(path) as f:
            return pacsv.read_csv(f)


class JSONDatasource(FileBasedDatasource):
    _GLOB = "*.jsonl"

    def _read_file(self, path: str):
        import pyarrow.json as pajson

        with self._open(path) as f:
            return pajson.read_json(f)


class TextDatasource(FileBasedDatasource):
    _GLOB = "*"

    def _read_file(self, path: str):
        import io

        with self._open(path) as f:
            text = io.TextIOWrapper(f, encoding="utf-8") if not isinstance(
                f, io.TextIOBase) else f
            return B.block_from_rows(
                [{"text": line.rstrip("\n")} for line in text]
            )


class BinaryDatasource(FileBasedDatasource):
    """Whole-file bytes rows: {"path", "bytes"} (reference:
    binary_datasource.py)."""

    _GLOB = "*"

    def _read_file(self, path: str):
        with self._open(path) as f:
            return B.block_from_rows([{"path": path, "bytes": f.read()}])


class ImageDatasource(FileBasedDatasource):
    """Decoded image rows: {"path", "image"} as uint8 numpy arrays
    (reference: data/datasource/image_datasource.py). Default mode="RGB"
    so every row is (H, W, 3) regardless of source format (palette GIFs,
    grayscale PNGs, RGBA) — batches stack cleanly; pass mode="L" for
    (H, W) grayscale or mode=None to keep each file's native mode.
    Optional size=(h, w) resizes at read time. Decode happens IN the
    read tasks, so a directory of images streams through the executor
    without driver-side decoding."""

    _GLOB = "*"
    _EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")

    def __init__(self, path: str, filesystem=None, size=None, mode="RGB"):
        super().__init__(path, filesystem)
        self.size = size
        self.mode = mode

    def _paths(self):
        all_paths = super()._paths()
        paths = [p for p in all_paths if p.lower().endswith(self._EXTS)]
        if not paths:
            raise FileNotFoundError(
                f"no image files ({', '.join(self._EXTS)}) under "
                f"{self.path!r}"
            )
        return paths

    def _read_file(self, path: str):
        import io

        import numpy as np
        from PIL import Image

        with self._open(path) as f:
            img = Image.open(io.BytesIO(f.read()))
            if self.mode is not None:
                img = img.convert(self.mode)
            if self.size is not None:
                img = img.resize((self.size[1], self.size[0]))
            return B.block_from_rows(
                [{"path": path, "image": np.asarray(img)}]
            )


class NpyDatasource(FileBasedDatasource):
    """One row per .npy file: {"path", "data"} (reference:
    numpy_datasource.py reading .npy files)."""

    _GLOB = "*.npy"

    def _read_file(self, path: str):
        import io

        import numpy as np

        with self._open(path) as f:
            arr = np.load(io.BytesIO(f.read()), allow_pickle=False)
        return B.block_from_rows([{"path": path, "data": arr}])


# ---------------------------------------------------------------------------
# Synthetic / in-memory sources
# ---------------------------------------------------------------------------


class RangeDatasource(Datasource):
    """Rows {"id": i} for i in [0, n) generated IN the read tasks — no
    driver-side materialization (reference: range_datasource.py)."""

    def __init__(self, n: int):
        self.n = n

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        parallelism = max(1, min(parallelism, self.n or 1))
        per = (self.n + parallelism - 1) // parallelism
        tasks = []
        for i in range(parallelism):
            lo, hi = i * per, min((i + 1) * per, self.n)
            if lo >= hi and i > 0:
                continue
            tasks.append(ReadTask(
                (lambda lo=lo, hi=hi:
                 [B.block_from_rows([{"id": j} for j in range(lo, hi)])]),
                {"num_rows": hi - lo},
            ))
        return tasks

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return self.n * 8


class NumpyDatasource(Datasource):
    """Columnar numpy arrays split into row-range read tasks."""

    def __init__(self, arrays: Dict[str, Any]):
        self.arrays = arrays

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        keys = list(self.arrays.keys())
        n = len(self.arrays[keys[0]]) if keys else 0
        parallelism = max(1, min(parallelism, n or 1))
        per = (n + parallelism - 1) // parallelism
        arrays = self.arrays

        import numpy as _np

        multi_dim = any(
            getattr(_np.asarray(arrays[k]), "ndim", 1) > 1 for k in keys
        )

        def make(lo, hi):
            def read():
                if multi_dim:
                    # Tensor columns: the slice stays ONE arrow column
                    # (FixedSizeList storage) instead of N row objects —
                    # zero-copy batching then applies to tensors too.
                    from ray_tpu.data.tensor import table_with_tensors

                    return [table_with_tensors(
                        {k: arrays[k][lo:hi] for k in keys}
                    )]
                rows = [
                    {k: _np_item(arrays[k][i]) for k in keys}
                    for i in range(lo, hi)
                ]
                return [B.block_from_rows(rows)]

            return read

        return [
            ReadTask(make(i * per, min((i + 1) * per, n)),
                     {"num_rows": min((i + 1) * per, n) - i * per})
            for i in range(parallelism)
            if i * per < n or i == 0
        ]


def _np_item(v):
    return v.item() if hasattr(v, "item") and getattr(v, "ndim", 1) == 0 else v


# ---------------------------------------------------------------------------
# File-based sinks
# ---------------------------------------------------------------------------


class FileBasedDatasink(Datasink):
    """One file per block under a directory (reference: the
    _FileDatasink write model). Cloud URIs resolve through pyarrow.fs;
    an explicit `filesystem` overrides resolution."""

    _EXT = "bin"

    def __init__(self, path: str, filesystem=None):
        self.filesystem = filesystem
        if filesystem is not None:
            self.path = path.split("://", 1)[-1]
            self._uri_prefix = path.rsplit(self.path, 1)[0]
        elif "://" in path and not path.startswith("file://"):
            import pyarrow.fs as pafs

            self.filesystem, self.path = pafs.FileSystem.from_uri(path)
            self._uri_prefix = path[: len(path) - len(self.path)]
        else:
            self.path = os.path.abspath(path.removeprefix("file://"))
            self._uri_prefix = ""

    def on_write_start(self) -> None:
        if self.filesystem is not None:
            self.filesystem.create_dir(self.path, recursive=True)
        else:
            os.makedirs(self.path, exist_ok=True)

    @abstractmethod
    def _write_rows(self, rows: List[Any], file_path: str) -> None:
        """Persist one block's rows (runs in a remote worker); open the
        target through _open_output."""

    def _open_output(self, file_path: str, text: bool = False):
        if self.filesystem is not None:
            stream = self.filesystem.open_output_stream(file_path)
            if text:
                import io

                return io.TextIOWrapper(stream, encoding="utf-8")
            return stream
        return open(file_path, "w" if text else "wb")

    def write(self, block: Any, ctx: Dict) -> Any:
        rows = B.block_to_rows(block)
        if not rows:
            return None
        fp = f"{self.path}/part-{ctx['task_index']:05d}.{self._EXT}"
        self._write_rows(rows, fp)
        return self._uri_prefix + fp if self._uri_prefix else fp


class ParquetDatasink(FileBasedDatasink):
    _EXT = "parquet"

    def _write_rows(self, rows, file_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        with self._open_output(file_path) as f:
            pq.write_table(pa.Table.from_pylist(rows), f)


class CSVDatasink(FileBasedDatasink):
    _EXT = "csv"

    def _write_rows(self, rows, file_path):
        import pyarrow as pa
        import pyarrow.csv as pacsv

        with self._open_output(file_path) as f:
            pacsv.write_csv(pa.Table.from_pylist(rows), f)


class JSONDatasink(FileBasedDatasink):
    _EXT = "jsonl"

    def _write_rows(self, rows, file_path):
        import json as _json

        from ray_tpu.data.dataset import _json_fallback

        with self._open_output(file_path, text=True) as f:
            for r in rows:
                f.write(_json.dumps(r, default=_json_fallback) + "\n")
