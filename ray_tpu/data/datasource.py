"""Datasource / Datasink plugin surface.

Analog of the reference's pluggable IO layer
(python/ray/data/datasource/datasource.py: Datasource.get_read_tasks /
ReadTask, and datasink.py: Datasink.on_write_start/write/on_write_complete).
A Datasource turns itself into independent read tasks (each a plain
callable producing blocks, executed in remote workers so rows never pass
through the driver); a Datasink receives one write call per block plus
job-level start/complete/failed hooks.

The built-in file formats (parquet/csv/json/text/binary/numpy/range) are
implemented on this surface — the same extension point user formats use.
"""

from __future__ import annotations

import glob as _glob
import os
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Iterable, List, Optional

from ray_tpu.data import block as B


class ReadTask:
    """One unit of parallel read work: a no-arg callable returning an
    iterable of blocks, plus optional metadata (row-count/size estimates
    used for scheduling hints)."""

    def __init__(self, read_fn: Callable[[], Iterable[Any]],
                 metadata: Optional[Dict] = None):
        self.read_fn = read_fn
        self.metadata = metadata or {}

    def __call__(self) -> List[Any]:
        return list(self.read_fn())


class Datasource(ABC):
    """Produces ReadTasks for parallel ingestion (reference:
    datasource.py:Datasource)."""

    @abstractmethod
    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        """Split this source into up to `parallelism` independent reads."""

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def get_name(self) -> str:
        return type(self).__name__


class Datasink(ABC):
    """Receives blocks from parallel write tasks (reference:
    datasink.py:Datasink)."""

    def on_write_start(self) -> None:
        """Driver-side hook before any write task runs."""

    @abstractmethod
    def write(self, block: Any, ctx: Dict) -> Any:
        """Write one block (runs in a remote worker). `ctx` carries
        {"task_index": int}. Returns a result collected by
        on_write_complete."""

    def on_write_complete(self, write_results: List[Any]) -> None:
        """Driver-side hook after every write task succeeded."""

    def on_write_failed(self, error: Exception) -> None:
        """Driver-side hook when any write task failed."""

    def get_name(self) -> str:
        return type(self).__name__


# ---------------------------------------------------------------------------
# File-based sources
# ---------------------------------------------------------------------------


class FileBasedDatasource(Datasource):
    """Shared machinery for one-file-per-read-task formats: expands a
    path or directory glob, one ReadTask per file (reference:
    file_based_datasource.py)."""

    _GLOB = "*"

    def __init__(self, path: str):
        self.path = path

    def _paths(self) -> List[str]:
        if os.path.isdir(self.path):
            paths = sorted(_glob.glob(os.path.join(self.path, self._GLOB)))
        else:
            paths = sorted(_glob.glob(self.path)) or [self.path]
        if not paths:
            raise FileNotFoundError(
                f"no {self._GLOB} files under {self.path!r}"
            )
        return paths

    @abstractmethod
    def _read_file(self, path: str) -> Any:
        """Parse one file into a block (runs in a remote worker)."""

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        read = self._read_file
        return [
            ReadTask(
                (lambda p=p: [read(p)]),
                {"path": p, "size_bytes": _safe_size(p)},
            )
            for p in self._paths()
        ]

    def estimate_inmemory_data_size(self) -> Optional[int]:
        try:
            return sum(_safe_size(p) or 0 for p in self._paths())
        except FileNotFoundError:
            return None


def _safe_size(p: str) -> Optional[int]:
    try:
        return os.path.getsize(p)
    except OSError:
        return None


class ParquetDatasource(FileBasedDatasource):
    _GLOB = "*.parquet"

    def _read_file(self, path: str):
        import pyarrow.parquet as pq

        return pq.read_table(path)


class CSVDatasource(FileBasedDatasource):
    _GLOB = "*.csv"

    def _read_file(self, path: str):
        import pyarrow.csv as pacsv

        return pacsv.read_csv(path)


class JSONDatasource(FileBasedDatasource):
    _GLOB = "*.jsonl"

    def _read_file(self, path: str):
        import pyarrow.json as pajson

        return pajson.read_json(path)


class TextDatasource(FileBasedDatasource):
    _GLOB = "*"

    def _read_file(self, path: str):
        with open(path) as f:
            return B.block_from_rows(
                [{"text": line.rstrip("\n")} for line in f]
            )


class BinaryDatasource(FileBasedDatasource):
    """Whole-file bytes rows: {"path", "bytes"} (reference:
    binary_datasource.py)."""

    _GLOB = "*"

    def _read_file(self, path: str):
        with open(path, "rb") as f:
            return B.block_from_rows([{"path": path, "bytes": f.read()}])


# ---------------------------------------------------------------------------
# Synthetic / in-memory sources
# ---------------------------------------------------------------------------


class RangeDatasource(Datasource):
    """Rows {"id": i} for i in [0, n) generated IN the read tasks — no
    driver-side materialization (reference: range_datasource.py)."""

    def __init__(self, n: int):
        self.n = n

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        parallelism = max(1, min(parallelism, self.n or 1))
        per = (self.n + parallelism - 1) // parallelism
        tasks = []
        for i in range(parallelism):
            lo, hi = i * per, min((i + 1) * per, self.n)
            if lo >= hi and i > 0:
                continue
            tasks.append(ReadTask(
                (lambda lo=lo, hi=hi:
                 [B.block_from_rows([{"id": j} for j in range(lo, hi)])]),
                {"num_rows": hi - lo},
            ))
        return tasks

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return self.n * 8


class NumpyDatasource(Datasource):
    """Columnar numpy arrays split into row-range read tasks."""

    def __init__(self, arrays: Dict[str, Any]):
        self.arrays = arrays

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        keys = list(self.arrays.keys())
        n = len(self.arrays[keys[0]]) if keys else 0
        parallelism = max(1, min(parallelism, n or 1))
        per = (n + parallelism - 1) // parallelism
        arrays = self.arrays

        def make(lo, hi):
            def read():
                rows = [
                    {k: _np_item(arrays[k][i]) for k in keys}
                    for i in range(lo, hi)
                ]
                return [B.block_from_rows(rows)]

            return read

        return [
            ReadTask(make(i * per, min((i + 1) * per, n)),
                     {"num_rows": min((i + 1) * per, n) - i * per})
            for i in range(parallelism)
            if i * per < n or i == 0
        ]


def _np_item(v):
    return v.item() if hasattr(v, "item") and getattr(v, "ndim", 1) == 0 else v


# ---------------------------------------------------------------------------
# File-based sinks
# ---------------------------------------------------------------------------


class FileBasedDatasink(Datasink):
    """One file per block under a directory (reference: the
    _FileDatasink write model)."""

    _EXT = "bin"

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    def on_write_start(self) -> None:
        os.makedirs(self.path, exist_ok=True)

    @abstractmethod
    def _write_rows(self, rows: List[Any], file_path: str) -> None:
        """Persist one block's rows (runs in a remote worker)."""

    def write(self, block: Any, ctx: Dict) -> Any:
        rows = B.block_to_rows(block)
        if not rows:
            return None
        fp = os.path.join(self.path, f"part-{ctx['task_index']:05d}.{self._EXT}")
        self._write_rows(rows, fp)
        return fp


class ParquetDatasink(FileBasedDatasink):
    _EXT = "parquet"

    def _write_rows(self, rows, file_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        pq.write_table(pa.Table.from_pylist(rows), file_path)


class CSVDatasink(FileBasedDatasink):
    _EXT = "csv"

    def _write_rows(self, rows, file_path):
        import pyarrow as pa
        import pyarrow.csv as pacsv

        pacsv.write_csv(pa.Table.from_pylist(rows), file_path)


class JSONDatasink(FileBasedDatasink):
    _EXT = "jsonl"

    def _write_rows(self, rows, file_path):
        import json as _json

        from ray_tpu.data.dataset import _json_fallback

        with open(file_path, "w") as f:
            for r in rows:
                f.write(_json.dumps(r, default=_json_fallback) + "\n")
