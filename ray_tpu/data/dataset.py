"""Dataset: lazy logical plans over distributed blocks.

Analog of the reference's Dataset (python/ray/data/dataset.py:142): a
logical plan (data/_internal/plan.py:35) of operations over blocks stored
in the shared-memory object store, executed lazily by the streaming
executor. Covers the core transform surface: map / map_batches / filter /
flat_map / repartition / random_shuffle / sort / union / limit /
groupby-aggregate, consumption (take / count / iter_rows / iter_batches),
and train-ingest splitting (split(n) feeding one shard per worker,
reference: data/iterator.py + train/_internal/data_config.py).
"""

from __future__ import annotations

import os
import random as _random
import zlib

import numpy as np
from typing import Any, Callable, Dict, Iterator, List, Optional

import ray_tpu as rt
from ray_tpu.data import block as B
from ray_tpu.data.executor import (
    ActorPoolStage,
    ActorPoolStrategy,
    AllToAllStage,
    MapStage,
    StreamingExecutor,
)


class Dataset:
    def __init__(self, input_refs: List, stages: Optional[List] = None):
        self._input_refs = list(input_refs)
        self._stages = list(stages or [])
        self._materialized: Optional[List] = None
        self._stats: List[Dict] = []
        self._last_feed_stats = None  # FeedStats of the newest feed pipeline

    # -- plan building ---------------------------------------------------
    def _with_stage(self, stage) -> "Dataset":
        return Dataset(self._input_refs, self._stages + [stage])

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        def block_fn(block):
            return B.block_from_rows([fn(r) for r in B.block_to_rows(block)])

        return self._with_stage(MapStage(block_fn, name="map"))

    def map_batches(
        self,
        fn: Callable,
        batch_format: str = "numpy",
        compute: Optional["ActorPoolStrategy"] = None,
        fn_constructor_args: tuple = (),
        fn_constructor_kwargs: Optional[dict] = None,
        resources: Optional[dict] = None,
    ) -> "Dataset":
        """Transform batches. With `compute=ActorPoolStrategy(size=N)`,
        `fn` is a CLASS constructed once per pool actor (state — e.g. a
        compiled TPU model — loads once and serves every batch routed to
        that actor); otherwise `fn` runs as stateless tasks."""
        if compute is not None:
            ctor_kwargs = fn_constructor_kwargs or {}

            def factory(cls=fn, a=tuple(fn_constructor_args), kw=ctor_kwargs):
                return cls(*a, **kw)

            def pool_fn(state, block, _fmt=batch_format):
                batch = B.block_to_batch(block, _fmt)
                return _batch_out_to_block(state(batch))

            return self._with_stage(ActorPoolStage(
                factory=factory,
                fn=pool_fn,
                name="map_batches(actors)",
                pool_size=compute.size,
                max_in_flight_per_actor=compute.max_tasks_in_flight_per_actor,
                resources=resources,
            ))

        def block_fn(block):
            batch = B.block_to_batch(block, batch_format)
            return _batch_out_to_block(fn(batch))

        return self._with_stage(
            MapStage(block_fn, name="map_batches", resources=resources)
        )

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        def block_fn(block):
            return B.block_from_rows(
                [r for r in B.block_to_rows(block) if fn(r)]
            )

        return self._with_stage(MapStage(block_fn, name="filter"))

    def flat_map(self, fn: Callable[[Any], List[Any]]) -> "Dataset":
        def block_fn(block):
            rows = []
            for r in B.block_to_rows(block):
                rows.extend(fn(r))
            return B.block_from_rows(rows)

        return self._with_stage(MapStage(block_fn, name="flat_map"))

    def repartition(self, num_blocks: int) -> "Dataset":
        def all_fn(refs):
            return _repartition_refs(refs, num_blocks)

        return self._with_stage(AllToAllStage(all_fn, name="repartition"))

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        def all_fn(refs):
            return _shuffle_refs(refs, seed)

        return self._with_stage(AllToAllStage(all_fn, name="random_shuffle"))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        def all_fn(refs):
            return _sort_refs(refs, key, descending)

        return self._with_stage(AllToAllStage(all_fn, name="sort"))

    def union(self, other: "Dataset") -> "Dataset":
        left = self.materialize()
        right = other.materialize()
        return Dataset(left._input_refs + right._input_refs)

    def limit(self, n: int) -> "Dataset":
        """First n rows, formed from block refs: whole blocks pass by
        reference, the boundary block is sliced in a remote task."""
        refs = self._executed_refs()
        count_fn = rt.remote(_block_count).options(max_retries=-1)
        counts = rt.get([count_fn.remote(r) for r in refs])
        slice_fn = rt.remote(_slice_block).options(max_retries=-1)
        out: List = []
        remaining = n
        for ref, c in zip(refs, counts):
            if remaining <= 0:
                break
            if c <= remaining:
                out.append(ref)
                remaining -= c
            else:
                out.append(slice_fn.remote(ref, 0, remaining))
                remaining = 0
        return Dataset(out if out else [rt.put(B.block_from_rows([]))])

    def train_test_split(self, test_size, *, shuffle: bool = False,
                         seed: Optional[int] = None):
        """Split into (train, test) Datasets (reference:
        Dataset.train_test_split). test_size: float fraction of rows or
        absolute int count; shuffle applies a random_shuffle first.
        Formed from block refs like limit(): whole blocks pass by
        reference, boundary blocks slice in remote tasks."""
        ds = self.random_shuffle(seed=seed) if shuffle else self
        refs = ds._executed_refs()
        count_fn = rt.remote(_block_count).options(max_retries=-1)
        counts = rt.get([count_fn.remote(r) for r in refs])
        total = sum(counts)
        if isinstance(test_size, (float, np.floating)):
            if not 0.0 < test_size < 1.0:
                raise ValueError("float test_size must be in (0, 1)")
            test_n = int(total * float(test_size))
        elif isinstance(test_size, (int, np.integer)) and not isinstance(
            test_size, bool
        ):
            test_n = int(test_size)
        else:
            raise TypeError(
                f"test_size must be a float fraction or int count, "
                f"got {type(test_size).__name__}"
            )
        if not 0 <= test_n <= total:
            raise ValueError(
                f"test_size {test_size} out of range for {total} rows"
            )
        train_n = total - test_n
        slice_fn = rt.remote(_slice_block).options(max_retries=-1)
        train_refs: List = []
        test_refs: List = []
        seen = 0
        for ref, c in zip(refs, counts):
            lo, hi = seen, seen + c
            seen = hi
            if hi <= train_n:
                train_refs.append(ref)
            elif lo >= train_n:
                test_refs.append(ref)
            else:  # boundary block straddles the split point
                train_refs.append(slice_fn.remote(ref, 0, train_n - lo))
                test_refs.append(slice_fn.remote(ref, train_n - lo, c))
        empty = lambda: [rt.put(B.block_from_rows([]))]  # noqa: E731
        return (
            Dataset(train_refs or empty()),
            Dataset(test_refs or empty()),
        )

    def add_column(self, name: str, fn: Callable[[Any], Any]) -> "Dataset":
        """Row -> value for a new column (reference: Dataset.add_column)."""
        def block_fn(block):
            return B.block_from_rows(
                [{**r, name: fn(r)} for r in B.block_to_rows(block)]
            )

        return self._with_stage(MapStage(block_fn, name="add_column"))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        drop = set(cols)

        def block_fn(block):
            return B.block_from_rows(
                [{k: v for k, v in r.items() if k not in drop}
                 for r in B.block_to_rows(block)]
            )

        return self._with_stage(MapStage(block_fn, name="drop_columns"))

    def select_columns(self, cols: List[str]) -> "Dataset":
        keep = list(cols)

        def block_fn(block):
            return B.block_from_rows(
                [{k: r[k] for k in keep} for r in B.block_to_rows(block)]
            )

        return self._with_stage(MapStage(block_fn, name="select_columns"))

    def random_sample(self, fraction: float,
                      seed: Optional[int] = None) -> "Dataset":
        """Bernoulli row sample (reference: Dataset.random_sample). The
        seed salts per block (the repo's _shuffle_map_block convention) so
        blocks draw independent sequences, not one repeated mask."""
        def block_fn(block, index):
            rows = B.block_to_rows(block)
            rng = _random.Random(None if seed is None else seed + index)
            return B.block_from_rows(
                [r for r in rows if rng.random() < fraction]
            )

        return self._with_stage(
            MapStage(block_fn, name="random_sample", with_index=True)
        )

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-wise zip of two equal-length datasets (reference:
        Dataset.zip). Clashing right-side column names are suffixed with
        "_1" (left value kept under the original name). When per-block row
        counts align, blocks zip pairwise in remote tasks; otherwise one
        remote task merges (rows never pass through the driver)."""
        left = self.materialize()
        right = other.materialize()
        lrefs, rrefs = left._input_refs, right._input_refs
        count_fn = rt.remote(_block_count).options(max_retries=-1)
        lc = rt.get([count_fn.remote(r) for r in lrefs])
        rc = rt.get([count_fn.remote(r) for r in rrefs])
        if sum(lc) != sum(rc):
            raise ValueError(
                f"zip requires equal lengths, got {sum(lc)} vs {sum(rc)}"
            )
        zip_fn = rt.remote(_zip_blocks).options(max_retries=-1)
        if lc == rc:
            return Dataset(
                [zip_fn.remote(a, b) for a, b in zip(lrefs, rrefs)]
            )
        # Misaligned blocks: one worker-side merge (driver touches refs).
        merged = rt.remote(_zip_all).options(num_returns=1).remote(
            len(lrefs), *lrefs, *rrefs
        )
        return Dataset([merged])

    # -- aggregation -----------------------------------------------------
    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def aggregate(self, *aggs) -> Dict[str, Any]:
        """Distributed aggregation: one remote partial-state task per
        block, tiny states merged on the driver (reference:
        Dataset.aggregate over AggregateFn, data/aggregate.py)."""
        from ray_tpu.data import aggregate as A

        aggs = list(aggs)
        fn = rt.remote(A.partial_states).options(max_retries=-1)
        state_refs = [fn.remote(ref, aggs) for ref in self._executed_refs()]
        values = A.merge_states(rt.get(state_refs), aggs)
        return {agg.name: v for agg, v in zip(aggs, values)}

    def sum(self, column: str):
        from ray_tpu.data.aggregate import Sum

        return self.aggregate(Sum(column))[f"sum({column})"]

    def mean(self, column: str):
        from ray_tpu.data.aggregate import Mean

        return self.aggregate(Mean(column))[f"mean({column})"]

    def min(self, column: str):
        from ray_tpu.data.aggregate import Min

        return self.aggregate(Min(column))[f"min({column})"]

    def max(self, column: str):
        from ray_tpu.data.aggregate import Max

        return self.aggregate(Max(column))[f"max({column})"]

    def std(self, column: str, ddof: int = 1):
        from ray_tpu.data.aggregate import Std

        return self.aggregate(Std(column, ddof))[f"std({column})"]

    def unique(self, column: str) -> List[Any]:
        """Distinct values of a column (reference: Dataset.unique) —
        per-block distinct sets in remote tasks, union on the driver."""
        fn = rt.remote(_distinct_block).options(max_retries=-1)
        sets = rt.get([fn.remote(ref, column) for ref in self._executed_refs()])
        out = set()
        for s in sets:
            out |= s
        return sorted(out)

    # -- execution -------------------------------------------------------
    def materialize(self) -> "Dataset":
        """Execute the plan; the result holds only input refs."""
        if not self._stages:
            return self
        executor = StreamingExecutor(self._stages)
        refs = executor.execute(self._input_refs)
        out = Dataset(refs)
        out._stats = self._stats + executor.stats
        return out

    def stats(self) -> str:
        """Per-stage execution timing of the last materialization
        (reference: Dataset.stats / _internal/stats.py)."""
        if not self._stats and self._stages:
            self._executed_refs()
        lines = [
            f"Stage {i}: {s['stage']}: {s['blocks']} blocks, {s['wall_s']}s"
            for i, s in enumerate(self._stats)
        ]
        if self._last_feed_stats is not None:
            lines.append(self._last_feed_stats.render())
        return "\n".join(lines) if lines else "(no executed stages)"

    def _feed_stats(self):
        """Fresh FeedStats for a new feed pipeline, kept so stats() can
        report wait/assemble/h2d/stall numbers for the newest iterator."""
        from ray_tpu.data.feed import FeedStats

        self._last_feed_stats = FeedStats()
        return self._last_feed_stats

    def _executed_refs(self) -> List:
        if self._materialized is None:
            m = self.materialize()
            self._materialized = m._input_refs
            self._stats = m._stats
        return self._materialized

    def _iter_blocks(self, prefetch_blocks: int = 1) -> Iterator:
        """Yield blocks; with prefetch_blocks > 0 the next k blocks' pulls
        START (rt.prefetch, a real background pull — a zero-timeout
        rt.wait was only a poll) while the current block is consumed, so
        cross-node transfer overlaps compute (reference: prefetching block
        iterator, data/iterator.py)."""
        refs = self._executed_refs()
        for i, ref in enumerate(refs):
            if prefetch_blocks > 0 and i + 1 < len(refs):
                rt.prefetch(refs[i + 1 : i + 1 + prefetch_blocks])
            yield rt.get(ref)

    # -- consumption -----------------------------------------------------
    def count(self) -> int:
        """Row count via per-block remote counts — blocks never move to
        the driver (reference: count() off metadata)."""
        fn = rt.remote(_block_count).options(max_retries=-1)
        return sum(
            rt.get([fn.remote(r) for r in self._executed_refs()])
        )

    def take(self, n: int = 20) -> List[Any]:
        out = []
        for block in self._iter_blocks():
            for r in B.block_to_rows(block):
                out.append(r)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> List[Any]:
        out = []
        for block in self._iter_blocks():
            out.extend(B.block_to_rows(block))
        return out

    def iter_rows(self) -> Iterator[Any]:
        for block in self._iter_blocks():
            yield from B.block_to_rows(block)

    def iter_batches(self, batch_size: int = 256,
                     batch_format: str = "numpy",
                     prefetch_blocks: int = 1,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     prefetch_batches: int = 0) -> Iterator:
        """Re-batch across block boundaries (reference: data/iterator.py).

        local_shuffle_buffer_size enables the reference's windowed local
        shuffle: rows accumulate in a buffer of at least that size and
        batches draw from its seeded permutation — cheap randomization
        without a full distributed shuffle.

        prefetch_batches > 0 moves block pull + batch assembly onto a
        background producer thread that stays that many ready batches
        ahead (see data/feed.py), so the consumer's step time and the
        feed overlap instead of serializing.
        """
        if prefetch_batches and prefetch_batches > 0:
            from ray_tpu.data.feed import _DevicePrefetcher

            return _DevicePrefetcher(
                lambda: self._iter_batches_local(
                    batch_size, batch_format, prefetch_blocks,
                    local_shuffle_buffer_size, local_shuffle_seed,
                ),
                depth=prefetch_batches,
                stats=self._feed_stats(),
            )
        return self._iter_batches_local(
            batch_size, batch_format, prefetch_blocks,
            local_shuffle_buffer_size, local_shuffle_seed,
        )

    def _iter_batches_local(self, batch_size: int, batch_format: str,
                            prefetch_blocks: int,
                            local_shuffle_buffer_size: Optional[int],
                            local_shuffle_seed: Optional[int]) -> Iterator:
        """Inline (consumer-thread) batch assembly."""
        if batch_format == "numpy" and not local_shuffle_buffer_size:
            yield from self._iter_numpy_batches(batch_size, prefetch_blocks)
            return
        rng = (
            _random.Random(local_shuffle_seed)
            if local_shuffle_buffer_size else None
        )
        threshold = max(local_shuffle_buffer_size or 0, batch_size)
        carry: List[Any] = []
        for block in self._iter_blocks(prefetch_blocks=prefetch_blocks):
            carry.extend(B.block_to_rows(block))
            if rng is not None and len(carry) >= threshold:
                # One permutation per buffer refill (O(buffer)), then
                # batches peel off it — not a re-shuffle per batch, which
                # made the draw loop O(buffer) PER BATCH. Seeded runs stay
                # deterministic: same seed, same refill sequence.
                rng.shuffle(carry)
            while len(carry) >= threshold:
                chunk, carry = carry[:batch_size], carry[batch_size:]
                yield B.block_to_batch(B.block_from_rows(chunk), batch_format)
        if rng is not None and carry:
            rng.shuffle(carry)
        while carry:
            chunk, carry = carry[:batch_size], carry[batch_size:]
            yield B.block_to_batch(B.block_from_rows(chunk), batch_format)

    def _iter_numpy_batches(self, batch_size: int,
                            prefetch_blocks: int) -> Iterator:
        """Zero-copy numpy batching (SURVEY §7 "Plasma<->HBM boundary").

        Arrow blocks come out of the shared-memory store as zero-copy
        views (pickle5 out-of-band buffers); columns convert to numpy as
        views over the same buffers, and every batch fully inside one
        block is a SLICE of those views — no host->host copy anywhere on
        the path, so a downstream device_put is the feed's only copy
        (host->HBM). Only batches STRADDLING a block boundary pay one
        np.concatenate."""
        import numpy as _np

        carry: Optional[dict] = None
        carry_rows = 0
        for block in self._iter_blocks(prefetch_blocks=prefetch_blocks):
            cols = B.block_to_batch(block, "numpy")
            if not cols:
                continue
            n = len(next(iter(cols.values())))
            start = 0
            if carry_rows:
                if set(cols) != set(carry):
                    # A batch straddling blocks with different column
                    # sets cannot concatenate; fail with the schemas
                    # instead of a bare KeyError from the carry merge.
                    raise ValueError(
                        "schema mismatch across blocks: a batch "
                        f"straddles columns {sorted(carry)} vs "
                        f"{sorted(cols)}; make block schemas "
                        "consistent (e.g. map() filling missing "
                        "fields) or use iter_rows()"
                    )
                need = batch_size - carry_rows
                if n < need:
                    carry = {
                        k: _np.concatenate([carry[k], v])
                        for k, v in cols.items()
                    }
                    carry_rows += n
                    continue
                yield {
                    k: _np.concatenate([carry[k], v[:need]])
                    for k, v in cols.items()
                }
                carry, carry_rows = None, 0
                start = need
            while start + batch_size <= n:
                yield {k: v[start:start + batch_size]
                       for k, v in cols.items()}
                start += batch_size
            if start < n:
                carry = {k: v[start:] for k, v in cols.items()}
                carry_rows = n - start
        if carry_rows:
            yield carry

    def iter_jax_batches(self, batch_size: int = 256, sharding=None,
                         prefetch_blocks: int = 1,
                         prefetch_batches: Optional[int] = None,
                         **kwargs) -> Iterator:
        """numpy batches placed onto JAX devices, staged ahead of the
        consumer (the TPU input-pipeline shape: host->HBM copy of batch
        i+1 overlaps the step on batch i). Reference analog:
        iter_torch_batches (data/iterator.py) rebuilt for JAX: pass
        sharding=NamedSharding(...) to lay each batch out across a mesh.

        By default (prefetch_batches=config.data_feed_prefetch_batches)
        the whole feed — block pull, batch assembly AND the device_put
        dispatch — runs on a background producer thread that stays that
        many device-resident batches ahead. prefetch_batches=0 falls back
        to inline assembly with one device transfer in flight.
        """
        import jax

        def put(batch):
            if sharding is None:
                return jax.tree.map(jax.device_put, batch)
            return jax.tree.map(
                lambda x: jax.device_put(x, sharding), batch
            )

        if prefetch_batches is None:
            from ray_tpu._private.config import get_config

            prefetch_batches = get_config().data_feed_prefetch_batches
        if prefetch_batches and prefetch_batches > 0:
            from ray_tpu.data.feed import _DevicePrefetcher

            return _DevicePrefetcher(
                lambda: self._iter_batches_local(
                    batch_size, "numpy", prefetch_blocks,
                    kwargs.get("local_shuffle_buffer_size"),
                    kwargs.get("local_shuffle_seed"),
                ),
                depth=prefetch_batches,
                transform=put,
                stats=self._feed_stats(),
            )
        return self._iter_jax_inline(batch_size, put, prefetch_blocks,
                                     **kwargs)

    def _iter_jax_inline(self, batch_size: int, put, prefetch_blocks: int,
                         **kwargs) -> Iterator:
        pending = None
        for batch in self.iter_batches(
            batch_size=batch_size, batch_format="numpy",
            prefetch_blocks=prefetch_blocks, **kwargs,
        ):
            nxt = put(batch)  # async dispatch; transfer proceeds in background
            if pending is not None:
                yield pending
            pending = nxt
        if pending is not None:
            yield pending

    def iter_torch_batches(self, batch_size: int = 256,
                           dtypes=None, device: str = "cpu",
                           **kwargs) -> Iterator:
        """numpy batches as torch tensors (reference: iter_torch_batches,
        data/iterator.py). `dtypes` maps column -> torch dtype; columns
        default to torch.as_tensor inference. Interop surface for
        torch-side consumers; the TPU path is iter_jax_batches."""
        import torch

        for batch in self.iter_batches(
            batch_size=batch_size, batch_format="numpy", **kwargs
        ):
            out = {}
            for k, v in batch.items():
                t = torch.as_tensor(v)
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                out[k] = t.to(device) if device != "cpu" else t
            yield out

    def schema(self):
        for block in self._iter_blocks():
            return B.block_schema(block)
        return None

    def num_blocks(self) -> int:
        return len(self._executed_refs())

    # -- train ingest ----------------------------------------------------
    def split(self, n: int) -> List["Dataset"]:
        """Split into n equal shards, one per training worker (reference:
        Dataset.split(equal=True) feeding Train workers).

        Shards are formed from block REFS: whole blocks pass by reference
        and only the blocks straddling a shard boundary are sliced — in
        remote tasks. Rows never move through the driver, so the split
        scales with the cluster.
        """
        refs = self.materialize()._input_refs
        count_fn = rt.remote(_block_count).options(max_retries=-1)
        counts = rt.get([count_fn.remote(r) for r in refs])
        total = sum(counts)
        boundaries = [total * i // n for i in range(n + 1)]
        slice_fn = rt.remote(_slice_block).options(max_retries=-1)
        shard_refs: List[List] = [[] for _ in range(n)]
        offset = 0  # global row index of the current block's first row
        for ref, c in zip(refs, counts):
            if c == 0:
                continue
            for i in range(n):
                lo = max(boundaries[i], offset)
                hi = min(boundaries[i + 1], offset + c)
                if lo >= hi:
                    continue
                if lo == offset and hi == offset + c:
                    shard_refs[i].append(ref)  # whole block, no copy
                else:
                    shard_refs[i].append(
                        slice_fn.remote(ref, lo - offset, hi - offset)
                    )
            offset += c
        return [Dataset(sr if sr else [rt.put(B.block_from_rows([]))])
                for sr in shard_refs]

    def to_arrow(self):
        """Materialize as ONE pyarrow Table (reference:
        Dataset.to_arrow_refs, concatenated)."""
        blocks = rt.get(list(self._executed_refs()))
        return B.block_to_batch(B.block_concat(blocks), "pyarrow")

    def to_pandas(self, limit: Optional[int] = None):
        """Materialize as a pandas DataFrame (reference:
        Dataset.to_pandas; `limit` guards accidental huge pulls)."""
        ds = self.limit(limit) if limit is not None else self
        return ds.to_arrow().to_pandas()

    def streaming_split(self, n: int, equal: bool = True,
                        locality_hints: Optional[List] = None,
                        prefetch_blocks: Optional[int] = None) -> List:
        """n coordinated per-worker iterators over ONE shared streaming
        execution per epoch (reference: dataset.py:1161 streaming_split +
        StreamSplitDataIterator). Each DataIterator's iter_rows /
        iter_batches call consumes one epoch; the pipeline re-executes
        per epoch. equal=True balances splits by rows at block
        granularity. Input blocks are promoted to the shared store up
        front; pipeline stages stream. prefetch_blocks sets how many
        blocks each iterator requests (and pulls) ahead of consumption
        (default: config.data_iterator_prefetch_blocks)."""
        import cloudpickle

        from ray_tpu.data.iterator import DataIterator, _SplitCoordinator

        coord = _SplitCoordinator.options(
            num_cpus=0.01, max_concurrency=2 * n + 4
        ).remote(
            self._input_refs, cloudpickle.dumps(self._stages), n, equal
        )
        return [DataIterator(coord, i, n, prefetch_blocks=prefetch_blocks)
                for i in range(n)]

    # -- output ----------------------------------------------------------
    def write_datasink(self, sink) -> List[Any]:
        """Write through the Datasink plugin surface: one remote write
        task per block, with driver-side start/complete/failed hooks
        (reference: datasink.py + plan_write_op)."""
        sink.on_write_start()
        write_fn = rt.remote(_run_write_task).options(max_retries=-1)
        refs = [
            write_fn.remote(sink, ref, i)
            for i, ref in enumerate(self._executed_refs())
        ]
        try:
            results = [r for r in rt.get(refs) if r is not None]
        except Exception as e:  # noqa: BLE001 — sink sees the failure
            sink.on_write_failed(e)
            raise
        sink.on_write_complete(results)
        return results

    def write_parquet(self, path: str, filesystem=None) -> List[str]:
        """One parquet file per block under `path` (reference:
        Dataset.write_parquet)."""
        from ray_tpu.data.datasource import ParquetDatasink

        return self.write_datasink(ParquetDatasink(path, filesystem))

    def write_csv(self, path: str, filesystem=None) -> List[str]:
        from ray_tpu.data.datasource import CSVDatasink

        return self.write_datasink(CSVDatasink(path, filesystem))

    def write_json(self, path: str, filesystem=None) -> List[str]:
        from ray_tpu.data.datasource import JSONDatasink

        return self.write_datasink(JSONDatasink(path, filesystem))

    def __repr__(self):
        return (
            f"Dataset(blocks={len(self._input_refs)}, "
            f"pending_stages={[getattr(s, 'name', '?') for s in self._stages]})"
        )


class GroupedData:
    """Distributed groupby (reference: data grouped_data.py).

    Hash-partitions rows by key across remote reduce tasks (each key's
    rows land in exactly one partition), then each partition groups and
    aggregates locally — the reference's hash-shuffle groupby exchange.
    Rows never pass through the driver.
    """

    def __init__(self, ds: Dataset, key: str):
        self.ds = ds
        self.key = key

    def _shuffled_partitions(self) -> List:
        refs = self.ds.materialize()._input_refs
        n = max(len(refs), 1)
        map_fn = rt.remote(_hash_partition_block).options(max_retries=-1)
        pieces: List[List] = []
        for ref in refs:
            out = map_fn.options(num_returns=n).remote(ref, n, self.key)
            pieces.append([out] if n == 1 else list(out))
        return [[pieces[i][j] for i in range(len(refs))] for j in range(n)]

    def _reduce(self, reduce_fn, *args) -> Dataset:
        rfn = rt.remote(reduce_fn).options(max_retries=-1)
        out = [
            rfn.remote(self.key, *args, *partition)
            for partition in self._shuffled_partitions()
        ]
        return Dataset(out)

    def aggregate(self, *aggs) -> Dataset:
        """One result row per group with one column per AggregateFn."""
        return self._reduce(_group_aggregate, list(aggs))

    def count(self) -> Dataset:
        from ray_tpu.data.aggregate import Count

        return self.aggregate(Count())

    def sum(self, column: str) -> Dataset:
        from ray_tpu.data.aggregate import Sum

        return self.aggregate(Sum(column))

    def mean(self, column: str) -> Dataset:
        from ray_tpu.data.aggregate import Mean

        return self.aggregate(Mean(column))

    def min(self, column: str) -> Dataset:
        from ray_tpu.data.aggregate import Min

        return self.aggregate(Min(column))

    def max(self, column: str) -> Dataset:
        from ray_tpu.data.aggregate import Max

        return self.aggregate(Max(column))

    def std(self, column: str, ddof: int = 1) -> Dataset:
        from ray_tpu.data.aggregate import Std

        return self.aggregate(Std(column, ddof))

    def map_groups(self, fn: Callable[[List[dict]], Any]) -> Dataset:
        """Apply a UDF to each group's row list; the UDF returns a row or
        a list of rows (reference: GroupedData.map_groups)."""
        return self._reduce(_group_map, fn)


def _stable_hash(value) -> int:
    """Process-stable, equality-consistent hash for shuffle keys.

    Python salts only str/bytes hashing per process (PYTHONHASHSEED), so
    those are rehashed with crc32; numeric types keep the builtin hash,
    which is unsalted and consistent across numeric types (True == 1 ==
    1.0 all co-partition, matching dict semantics)."""
    if isinstance(value, bytes):
        return zlib.crc32(value)
    if isinstance(value, str):
        return zlib.crc32(value.encode("utf-8", "surrogatepass"))
    if isinstance(value, tuple):
        h = 2166136261  # FNV-1a fold over element hashes
        for el in value:
            h = ((h ^ _stable_hash(el)) * 16777619) & 0xFFFFFFFF
        return h
    return hash(value) & 0xFFFFFFFF


def _hash_partition_block(block, n: int, key: str):
    """Partition one block's rows by hash(key) across n pieces."""
    parts: List[List] = [[] for _ in range(n)]
    for r in B.block_to_rows(block):
        parts[_stable_hash(r[key]) % n].append(r)
    out = tuple(B.block_from_rows(p) for p in parts)
    return out if n > 1 else out[0]


def _collect_groups(key: str, pieces) -> Dict[Any, List]:
    groups: Dict[Any, List] = {}
    for blk in pieces:
        for r in B.block_to_rows(blk):
            groups.setdefault(r[key], []).append(r)
    return groups


def _group_aggregate(key: str, aggs, *pieces):
    rows = []
    for k, group_rows in sorted(_collect_groups(key, pieces).items()):
        row = {key: k}
        for agg in aggs:
            row[agg.name] = agg.finalize(agg.partial(group_rows))
        rows.append(row)
    return B.block_from_rows(rows)


def _group_map(key: str, fn, *pieces):
    rows = []
    for _, group_rows in sorted(_collect_groups(key, pieces).items()):
        out = fn(group_rows)
        if isinstance(out, list):
            rows.extend(out)
        else:
            rows.append(out)
    return B.block_from_rows(rows)


def _distinct_block(block, column: str) -> set:
    return {r[column] for r in B.block_to_rows(block)}


def _slice_block(block, start: int, end: int):
    return B.block_slice(block, start, end)


# ---------------------------------------------------------------------------
# all-to-all implementations: push-based distributed shuffle
#
# Reference analog: push_based_shuffle_task_scheduler.py:382 — map tasks
# partition each block into N pieces (multi-return), reduce tasks merge
# piece i from every map. The driver only moves REFS; rows never pass
# through it, so shuffles scale with the cluster, not the driver.
# ---------------------------------------------------------------------------


def _shuffle_map_block(block, n: int, mode: str, key, seed, salt: int):
    """Partition one block's rows into n pieces (runs as a remote task)."""
    rows = B.block_to_rows(block)
    parts: List[List] = [[] for _ in range(n)]
    if mode == "random":
        rng = _random.Random(None if seed is None else seed + salt)
        for r in rows:
            parts[rng.randrange(n)].append(r)
    elif mode == "round_robin":
        for i, r in enumerate(rows):
            parts[i % n].append(r)
    else:  # range partition by sorted boundary list in `key`=(col, bounds)
        col, bounds = key
        import bisect

        for r in rows:
            parts[bisect.bisect_right(bounds, r[col])].append(r)
    out = tuple(B.block_from_rows(p) for p in parts)
    return out if n > 1 else out[0]


def _shuffle_reduce(mode: str, key, seed, salt: int, *pieces):
    """Merge piece blocks from every map task (runs as a remote task)."""
    rows: List = []
    for b in pieces:
        rows.extend(B.block_to_rows(b))
    if mode == "random":
        _random.Random(None if seed is None else seed + 7919 * (salt + 1)).shuffle(rows)
    elif mode == "range":
        col, descending = key
        rows.sort(key=lambda r: r[col], reverse=descending)
    return B.block_from_rows(rows)


def _concat_pieces(*pieces):
    """Order-preserving concat of piece blocks (shuffle combine step)."""
    rows: List = []
    for b in pieces:
        rows.extend(B.block_to_rows(b))
    return B.block_from_rows(rows)


# Max object args per reduce/combine task. A 1000-block shuffle would
# otherwise hand every reduce task 1000 object arguments (resolved and
# held in memory at once); the tree combine bounds per-task fan-in the
# way the reference's multi-round push-based shuffle bounds merge width
# (push_based_shuffle_task_scheduler.py: merge factor).
_SHUFFLE_FANIN = 64


def _push_shuffle(refs: List, n_out: int, mode: str, map_key, reduce_key,
                  seed=None) -> List:
    if not refs:
        return refs
    n_out = max(n_out, 1)
    map_fn = rt.remote(_shuffle_map_block).options(max_retries=-1)
    reduce_fn = rt.remote(_shuffle_reduce).options(max_retries=-1)
    combine_fn = rt.remote(_concat_pieces).options(max_retries=-1)
    pieces: List[List] = []  # [map][partition] -> ref
    for i, ref in enumerate(refs):
        out = map_fn.options(num_returns=n_out).remote(
            ref, n_out, mode, map_key, seed, i
        )
        pieces.append([out] if n_out == 1 else list(out))
    outs = []
    for j in range(n_out):
        parts = [pieces[i][j] for i in range(len(refs))]
        # Contiguous slices keep concat order stable, so seeded random
        # shuffles stay deterministic regardless of tree depth.
        while len(parts) > _SHUFFLE_FANIN:
            parts = [
                combine_fn.remote(*parts[k:k + _SHUFFLE_FANIN])
                for k in range(0, len(parts), _SHUFFLE_FANIN)
            ]
        outs.append(reduce_fn.remote(mode, reduce_key, seed, j, *parts))
    return outs


def _repartition_refs(refs: List, num_blocks: int) -> List:
    return _push_shuffle(refs, num_blocks, "round_robin", None, None)


def _shuffle_refs(refs: List, seed: Optional[int]) -> List:
    return _push_shuffle(refs, len(refs), "random", None, None, seed=seed)


def _sort_refs(refs: List, key: str, descending: bool) -> List:
    """Distributed range-partitioned sort: sample boundaries, range-shuffle,
    sort each partition (the reference's sort exchange, _internal/sort.py)."""
    n = max(len(refs), 1)
    # Sample keys from every block to pick n-1 partition boundaries
    # (all sample tasks in flight at once; one batched get).
    sample_fn = rt.remote(_sample_keys).options(max_retries=-1)
    sample_refs = [sample_fn.remote(ref, key, 16) for ref in refs]
    samples: List = [s for chunk in rt.get(sample_refs) for s in chunk]
    samples.sort()
    bounds = [
        samples[(i + 1) * len(samples) // n]
        for i in range(n - 1)
    ] if samples else []
    out = _push_shuffle(
        refs, n, "range", (key, bounds), (key, descending)
    )
    if descending:
        out = list(reversed(out))
    return out


def _block_count(block) -> int:
    return B.block_num_rows(block)


def _merge_zip_rows(a_rows, b_rows):
    rows = []
    for a, b in zip(a_rows, b_rows):
        merged = dict(a)
        for k, v in b.items():
            merged[k if k not in a else k + "_1"] = v
        rows.append(merged)
    return rows


def _zip_blocks(a_block, b_block):
    return B.block_from_rows(
        _merge_zip_rows(B.block_to_rows(a_block), B.block_to_rows(b_block))
    )


def _zip_all(n_left, *blocks):
    a_rows, b_rows = [], []
    for blk in blocks[:n_left]:
        a_rows.extend(B.block_to_rows(blk))
    for blk in blocks[n_left:]:
        b_rows.extend(B.block_to_rows(blk))
    return B.block_from_rows(_merge_zip_rows(a_rows, b_rows))


def _sample_keys(block, key: str, k: int):
    rows = B.block_to_rows(block)
    if len(rows) <= k:
        return [r[key] for r in rows]
    step = len(rows) / k
    return [rows[int(i * step)][key] for i in range(k)]


def _np_item(x):
    import numpy as np

    if isinstance(x, np.generic):
        return x.item()
    return x


def _batch_out_to_block(out):
    """Convert a map_batches UDF's return (column dict or row iterable)
    back to a block."""
    if isinstance(out, dict):
        keys = list(out.keys())
        n = len(out[keys[0]])
        rows = [{k: _np_item(out[k][i]) for k in keys} for i in range(n)]
        return B.block_from_rows(rows)
    return B.block_from_rows(list(out))


def _json_fallback(x):
    """json.dumps default= hook: arrays become lists; anything else raises
    (returning the object unchanged would recurse forever)."""
    import numpy as np

    if isinstance(x, np.generic):
        return x.item()
    if isinstance(x, np.ndarray):
        return x.tolist()
    raise TypeError(f"not JSON serializable: {type(x).__name__}")


# ---------------------------------------------------------------------------
# creation APIs
# ---------------------------------------------------------------------------


def from_items(items: List[Any], parallelism: int = 4) -> Dataset:
    items = list(items)
    if not items:
        return Dataset([rt.put(B.block_from_rows([]))])
    parallelism = max(1, min(parallelism, len(items)))
    per = (len(items) + parallelism - 1) // parallelism
    refs = [
        rt.put(B.block_from_rows(items[i * per : (i + 1) * per]))
        for i in range(parallelism)
        if items[i * per : (i + 1) * per]
    ]
    return Dataset(refs)


def range_dataset(n: int, parallelism: int = 4) -> Dataset:
    """Rows {"id": i}; generated inside read tasks, not on the driver."""
    from ray_tpu.data.datasource import RangeDatasource

    return read_datasource(RangeDatasource(n), parallelism)


def from_pandas(dfs, parallelism: int = 4) -> Dataset:
    """DataFrame(s) -> Dataset, one arrow block per frame (reference:
    ray.data.from_pandas)."""
    import pyarrow as pa

    if not isinstance(dfs, (list, tuple)):
        dfs = [dfs]
    refs = [rt.put(pa.Table.from_pandas(df, preserve_index=False))
            for df in dfs]
    ds = Dataset(refs)
    if len(refs) < parallelism:
        ds = ds.repartition(parallelism)
    return ds


def from_arrow(tables, parallelism: int = 4) -> Dataset:
    """pyarrow Table(s) -> Dataset; tables ARE the block format, so this
    is zero-conversion (reference: ray.data.from_arrow)."""
    if not isinstance(tables, (list, tuple)):
        tables = [tables]
    refs = [rt.put(t) for t in tables]
    ds = Dataset(refs)
    if len(refs) < parallelism:
        ds = ds.repartition(parallelism)
    return ds


def from_numpy(arrays: Dict[str, Any], parallelism: int = 4) -> Dataset:
    from ray_tpu.data.datasource import NumpyDatasource

    return read_datasource(NumpyDatasource(arrays), parallelism)


def _run_write_task(sink, block, index: int):
    """Remote-task body: hand one block to the Datasink."""
    return sink.write(block, {"task_index": index})


def _run_read_task(task):
    """Remote-task body: execute one ReadTask; concat its blocks."""
    blocks = task()
    if not blocks:
        return B.block_from_rows([])
    if len(blocks) == 1:
        return blocks[0]
    return B.block_concat(blocks)


def read_datasource(datasource, parallelism: int = 4) -> Dataset:
    """Parallel ingestion through the Datasource plugin surface: the
    source plans ReadTasks, each executes in a remote worker (reference:
    read_api.py:read_datasource -> plan_read_op)."""
    tasks = datasource.get_read_tasks(parallelism)
    if not tasks:
        return Dataset([rt.put(B.block_from_rows([]))])
    read_fn = rt.remote(_run_read_task).options(max_retries=-1)
    ds = Dataset([read_fn.remote(t) for t in tasks])
    if len(tasks) < parallelism:
        ds = ds.repartition(parallelism)
    return ds


def read_parquet(path: str, parallelism: int = 4, filesystem=None) -> Dataset:
    from ray_tpu.data.datasource import ParquetDatasource

    return read_datasource(ParquetDatasource(path, filesystem), parallelism)


def read_csv(path: str, parallelism: int = 4, filesystem=None) -> Dataset:
    from ray_tpu.data.datasource import CSVDatasource

    return read_datasource(CSVDatasource(path, filesystem), parallelism)


def read_json(path: str, parallelism: int = 4, filesystem=None) -> Dataset:
    from ray_tpu.data.datasource import JSONDatasource

    return read_datasource(JSONDatasource(path, filesystem), parallelism)


def read_binary_files(path: str, parallelism: int = 4, filesystem=None) -> Dataset:
    """One row per file: {"path", "bytes"} (reference: read_binary_files)."""
    from ray_tpu.data.datasource import BinaryDatasource

    return read_datasource(BinaryDatasource(path, filesystem), parallelism)


def read_images(path: str, parallelism: int = 4, filesystem=None,
                size=None, mode="RGB") -> Dataset:
    """Decoded image rows {"path", "image"} (reference: read_images);
    size=(h, w) resizes; mode="RGB" (default) makes every row (H, W, 3)
    uint8, mode="L" grayscale, mode=None keeps native per-file modes."""
    from ray_tpu.data.datasource import ImageDatasource

    return read_datasource(
        ImageDatasource(path, filesystem, size=size, mode=mode), parallelism
    )


def read_numpy(path: str, parallelism: int = 4, filesystem=None) -> Dataset:
    """One row per .npy file: {"path", "data"} (reference: read_numpy)."""
    from ray_tpu.data.datasource import NpyDatasource

    return read_datasource(NpyDatasource(path, filesystem), parallelism)


def read_text(path: str, parallelism: int = 4) -> Dataset:
    """One row per line: {"text": line} (reference: data read_text)."""
    from ray_tpu.data.datasource import TextDatasource

    class _TxtSource(TextDatasource):
        _GLOB = "*.txt"

    return read_datasource(_TxtSource(path), parallelism)


def read_sql(sql: str, connection_factory, parallelism: int = 1,
             shard_column: str = None) -> Dataset:
    """Rows from a SQL query over a DB-API connection factory
    (reference: ray.data.read_sql). shard_column enables hash-sharded
    parallel reads."""
    from ray_tpu.data.connectors import SQLDatasource

    return read_datasource(
        SQLDatasource(sql, connection_factory, shard_column), parallelism
    )


def read_tfrecords(path: str, parallelism: int = 4, filesystem=None,
                   raw: bool = False) -> Dataset:
    """TFRecord files of tf.train.Examples -> feature-dict rows
    (reference: ray.data.read_tfrecords; no tensorflow needed — the
    Example wire codec is built in)."""
    from ray_tpu.data.connectors import TFRecordDatasource

    return read_datasource(
        TFRecordDatasource(path, filesystem, raw=raw), parallelism
    )


def read_webdataset(path: str, parallelism: int = 4,
                    filesystem=None) -> Dataset:
    """WebDataset tar shards -> one row per sample stem (reference:
    ray.data.read_webdataset)."""
    from ray_tpu.data.connectors import WebDatasetDatasource

    return read_datasource(
        WebDatasetDatasource(path, filesystem), parallelism
    )


def read_mongo(db: str, collection: str, client_factory,
               filter: dict = None,  # noqa: A002 — pymongo name
               parallelism: int = 1) -> Dataset:
    """Documents from a MongoDB collection via an injectable pymongo-
    surface client factory (reference: ray.data.read_mongo)."""
    from ray_tpu.data.connectors import MongoDatasource

    return read_datasource(
        MongoDatasource(db, collection, client_factory, filter), parallelism
    )


def read_bigquery(sql: str, client, parallelism: int = 1) -> Dataset:
    """Rows from a BigQuery query via an injectable client (reference:
    ray.data.read_bigquery)."""
    from ray_tpu.data.connectors import BigQueryDatasource

    return read_datasource(BigQueryDatasource(sql, client), parallelism)
