"""Connector datasources/sinks beyond the file formats.

Fills the breadth slots of the reference's datasource tree
(python/ray/data/datasource/: sql_datasource.py, tfrecords_datasource.py,
webdataset_datasource.py, mongo_datasource.py, bigquery_datasource.py)
on this repo's Datasource/Datasink ABC. Design stance, matching the GKE
provider pattern: every connector's IO goes through an injectable
client/connection factory so the logic is fully testable offline —
SQL tests run against stdlib sqlite3 (a real DB-API driver), Mongo and
BigQuery against recorded fakes.

TFRecord support includes a dependency-free tf.train.Example wire codec
(protobuf wire format is stable and simple: Features is a map field of
oneof bytes/float/int64 lists), so TFRecord files round-trip real
feature dicts without tensorflow in the image.
"""

from __future__ import annotations

import io
import struct
import tarfile
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.data import block as B
from ray_tpu.data.datasource import Datasink, Datasource, FileBasedDatasource, ReadTask


# ---------------------------------------------------------------------------
# SQL (DB-API 2.0)
# ---------------------------------------------------------------------------


class SQLDatasource(Datasource):
    """Rows from a SQL query over any DB-API 2.0 driver (reference:
    data/datasource/sql_datasource.py — same connection_factory seam).

    `shard_column` mode splits the query into parallelism hash-sharded
    reads (WHERE COALESCE(abs(col), 0) % N = i — NULL keys land in
    shard 0, never dropped); without it the query runs as one read task
    (the reference's default too: arbitrary SQL cannot be split
    safely). SQL emitted uses qmark placeholders and AS-aliased
    subqueries — the broadest common DB-API dialect (sqlite3, duckdb,
    mariadb); pyformat-only drivers (psycopg2) need a qmark wrapper."""

    def __init__(self, sql: str, connection_factory: Callable[[], Any],
                 shard_column: Optional[str] = None):
        self.sql = sql
        self.connection_factory = connection_factory
        self.shard_column = shard_column

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        factory, sql = self.connection_factory, self.sql

        def run_query(query: str, params=()):
            conn = factory()
            try:
                cur = conn.cursor()
                cur.execute(query, params)
                names = [d[0] for d in cur.description]
                rows = [dict(zip(names, r)) for r in cur.fetchall()]
                return [B.block_from_rows(rows)]
            finally:
                conn.close()

        if self.shard_column is None or parallelism <= 1:
            return [ReadTask(lambda: run_query(sql))]
        col, n = self.shard_column, parallelism
        tasks = []
        for i in range(n):
            shard_sql = (
                f"SELECT * FROM ({sql}) AS _rt_shard WHERE "  # noqa: S608
                f"COALESCE(abs({col}), 0) % {n} = {i}"
            )
            tasks.append(
                ReadTask(lambda q=shard_sql: run_query(q))
            )
        return tasks


class SQLDatasink(Datasink):
    """INSERTs each block's rows (reference: Dataset.write_sql)."""

    def __init__(self, table: str, connection_factory: Callable[[], Any]):
        self.table = table
        self.connection_factory = connection_factory

    def write(self, blk: Any, ctx: Dict) -> Any:
        rows = B.block_to_rows(blk)
        if not rows:
            return 0
        cols = list(rows[0].keys())
        placeholders = ", ".join("?" for _ in cols)
        sql = (
            f"INSERT INTO {self.table} ({', '.join(cols)}) "  # noqa: S608
            f"VALUES ({placeholders})"
        )
        conn = self.connection_factory()
        try:
            cur = conn.cursor()
            cur.executemany(sql, [tuple(r[c] for c in cols) for r in rows])
            conn.commit()
            return len(rows)
        finally:
            conn.close()


# ---------------------------------------------------------------------------
# TFRecords + tf.train.Example wire codec
# ---------------------------------------------------------------------------

# crc32c (Castagnoli), table-driven; TFRecord frames each record as
# [len u64][masked crc32c(len) u32][data][masked crc32c(data) u32].
_CRC_TABLE = []


def _crc_table():
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def _crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def _write_record(out, data: bytes) -> None:
    header = struct.pack("<Q", len(data))
    out.write(header)
    out.write(struct.pack("<I", _masked_crc(header)))
    out.write(data)
    out.write(struct.pack("<I", _masked_crc(data)))


def _iter_records(buf: bytes):
    off = 0
    while off < len(buf):
        (length,) = struct.unpack_from("<Q", buf, off)
        off += 12  # len + len-crc
        yield buf[off:off + length]
        off += length + 4  # data + data-crc


# -- minimal protobuf wire helpers (only what tf.train.Example needs) ----


def _varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _read_varint(buf: bytes, off: int):
    result = shift = 0
    while True:
        b = buf[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, off
        shift += 7


def _len_field(field_no: int, payload: bytes) -> bytes:
    return _varint(field_no << 3 | 2) + _varint(len(payload)) + payload


def encode_example(features: Dict[str, Any]) -> bytes:
    """dict -> serialized tf.train.Example. Values: bytes/str ->
    bytes_list, float -> float_list, int -> int64_list; lists of those
    encode element-wise. Numpy scalars/arrays normalize to their Python
    equivalents first — list-of-rows blocks carry np.int64/np.float32
    straight from map() outputs (Arrow blocks convert via to_pylist,
    but the row path must not reject what the table path accepts)."""
    import numpy as np

    feat_entries = b""
    for name, value in features.items():
        if isinstance(value, np.ndarray):
            value = value.tolist()
        values = value if isinstance(value, (list, tuple)) else [value]
        # np.generic scalars are host memory; .item() here is a pure
        # unboxing (never a device sync).
        values = [v.item() if isinstance(v, np.generic) else v  # rtlint: disable=RT001
                  for v in values]
        if all(isinstance(v, (bytes, str)) for v in values):
            items = b"".join(
                _len_field(1, v.encode() if isinstance(v, str) else v)
                for v in values
            )
            feature = _len_field(1, items)  # Feature.bytes_list
        elif all(isinstance(v, bool) or isinstance(v, int) for v in values):
            packed = b"".join(_varint(int(v) & (2 ** 64 - 1)) for v in values)
            # Int64List.value is packed repeated varint (field 1).
            feature = _len_field(3, _len_field(1, packed))
        elif all(isinstance(v, (int, float)) for v in values):
            packed = b"".join(struct.pack("<f", float(v)) for v in values)
            feature = _len_field(2, _len_field(1, packed))  # FloatList
        else:
            raise TypeError(f"unsupported feature value for {name!r}")
        entry = _len_field(1, name.encode()) + _len_field(2, feature)
        feat_entries += _len_field(1, entry)  # Features.feature map entry
    return _len_field(1, feat_entries)  # Example.features


def decode_example(buf: bytes) -> Dict[str, Any]:
    """Serialized tf.train.Example -> {name: list-of-values}."""

    def fields(b: bytes):
        off = 0
        while off < len(b):
            tag, off = _read_varint(b, off)
            field_no, wire = tag >> 3, tag & 7
            if wire == 2:
                length, off = _read_varint(b, off)
                yield field_no, b[off:off + length]
                off += length
            elif wire == 0:
                value, off = _read_varint(b, off)
                yield field_no, value
            elif wire == 5:
                yield field_no, b[off:off + 4]
                off += 4
            else:  # pragma: no cover - not produced by Example
                raise ValueError(f"unsupported wire type {wire}")

    out: Dict[str, Any] = {}
    for fno, features_buf in fields(buf):
        if fno != 1:
            continue
        for entry_no, entry in fields(features_buf):
            if entry_no != 1:
                continue
            name, feature = None, None
            for k, v in fields(entry):
                if k == 1:
                    name = v.decode()
                elif k == 2:
                    feature = v
            if name is None or feature is None:
                continue
            for list_no, list_buf in fields(feature):
                values: List[Any] = []
                if list_no == 1:  # BytesList
                    values = [v for _, v in fields(list_buf)]
                elif list_no == 2:  # FloatList (packed floats)
                    for _, packed in fields(list_buf):
                        values.extend(
                            struct.unpack_from("<f", packed, i)[0]
                            for i in range(0, len(packed), 4)
                        )
                elif list_no == 3:  # Int64List (packed or unpacked)
                    def _signed(v):
                        return v - 2 ** 64 if v >= 2 ** 63 else v

                    for _, packed in fields(list_buf):
                        if isinstance(packed, int):  # unpacked varint
                            values.append(_signed(packed))
                            continue
                        off = 0
                        while off < len(packed):
                            v, off = _read_varint(packed, off)
                            values.append(_signed(v))
                out[name] = values
    return out


class TFRecordDatasource(FileBasedDatasource):
    """TFRecord files -> one row per record (reference:
    tfrecords_datasource.py). Records decode as tf.train.Example feature
    dicts; single-element lists unwrap to scalars (the reference's
    behavior). Pass raw=True for {"bytes": record} rows instead."""

    _GLOB = "*.tfrecord*"

    def __init__(self, path: str, filesystem=None, raw: bool = False):
        super().__init__(path, filesystem)
        self.raw = raw

    def _read_file(self, path: str):
        with self._open(path) as f:
            data = f.read()
        rows = []
        for rec in _iter_records(data):
            if self.raw:
                rows.append({"bytes": rec})
            else:
                decoded = decode_example(rec)
                rows.append({
                    k: (v[0] if len(v) == 1 else v)
                    for k, v in decoded.items()
                })
        return B.block_from_rows(rows)


class TFRecordDatasink(Datasink):
    """Blocks -> TFRecord shard files of tf.train.Examples (reference:
    Dataset.write_tfrecords)."""

    def __init__(self, path: str):
        import os

        self.path = path
        os.makedirs(path, exist_ok=True)

    def write(self, blk: Any, ctx: Dict) -> Any:
        import os

        rows = B.block_to_rows(blk)
        out_path = os.path.join(
            self.path, f"part-{ctx['task_index']:05d}.tfrecord"
        )
        with open(out_path, "wb") as f:
            for row in rows:
                _write_record(f, encode_example(row))
        return len(rows)


# ---------------------------------------------------------------------------
# WebDataset (tar shards)
# ---------------------------------------------------------------------------


class WebDatasetDatasource(FileBasedDatasource):
    """Tar shards where files sharing a basename stem form one sample
    (reference: webdataset_datasource.py): shard-0.tar containing
    {a.jpg, a.cls, b.jpg, b.cls} yields rows {"__key__": "a", "jpg": ...,
    "cls": ...}. Members decode by suffix: known image suffixes via PIL
    (uint8 arrays), "cls"/"txt"/"json" as text/int/json, everything else
    raw bytes."""

    _GLOB = "*.tar"
    _IMAGE_SUFFIXES = ("jpg", "jpeg", "png", "bmp", "webp")

    def _decode(self, suffix: str, data: bytes):
        if suffix in self._IMAGE_SUFFIXES:
            import numpy as np
            from PIL import Image

            return np.asarray(Image.open(io.BytesIO(data)))
        if suffix == "cls":
            return int(data.decode().strip())
        if suffix in ("txt", "text"):
            return data.decode()
        if suffix == "json":
            import json as _json

            return _json.loads(data)
        return data

    def _read_file(self, path: str):
        with self._open(path) as f:
            raw = io.BytesIO(f.read())
        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(fileobj=raw, mode="r") as tar:
            for member in tar:
                if not member.isfile():
                    continue
                base = member.name.split("/")[-1]
                stem, _, suffix = base.rpartition(".")
                if not stem:
                    stem, suffix = base, ""
                if stem not in samples:
                    samples[stem] = {"__key__": stem}
                    order.append(stem)
                data = tar.extractfile(member).read()
                samples[stem][suffix.lower()] = self._decode(
                    suffix.lower(), data
                )
        # Rows stay a plain list (ragged ndarray members don't fit an
        # arrow table without the tensor extension).
        return [samples[k] for k in order]


# ---------------------------------------------------------------------------
# Mongo / BigQuery (injectable clients)
# ---------------------------------------------------------------------------


class MongoDatasource(Datasource):
    """Documents from a MongoDB collection (reference:
    mongo_datasource.py). `client_factory() -> client` where
    client[db][collection].find(filter) yields dicts (pymongo's
    surface). Reads run as ONE task: arbitrary filters cannot be
    sharded without server-side cooperation (the reference partitions
    on _id ranges via pymongoarrow, out of scope here)."""

    def __init__(self, db: str, collection: str,
                 client_factory: Callable[[], Any],
                 filter: Optional[Dict] = None):  # noqa: A002 — pymongo name
        self.db = db
        self.collection = collection
        self.client_factory = client_factory
        self.filter = filter or {}

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        factory = self.client_factory
        db, coll, flt = self.db, self.collection, dict(self.filter)

        def read():
            client = factory()
            docs = list(client[db][coll].find(flt))
            return [B.block_from_rows(docs)]

        return [ReadTask(read)]


class BigQueryDatasource(Datasource):
    """Rows from a BigQuery query (reference: bigquery_datasource.py).
    `client.query(sql).result()` yields row dicts (the google-cloud-
    bigquery surface); inject a fake for offline tests."""

    def __init__(self, sql: str, client: Any):
        self.sql = sql
        self.client = client

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        client, sql = self.client, self.sql

        def read():
            rows = [dict(r) for r in client.query(sql).result()]
            return [B.block_from_rows(rows)]

        return [ReadTask(read)]
