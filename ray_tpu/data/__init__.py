"""ray_tpu.data: distributed datasets.

Public surface mirrors the reference's ray.data creation APIs:
range / from_items / from_numpy / read_parquet / read_csv / read_json.
"""

from ray_tpu.data import aggregate
from ray_tpu.data.aggregate import Count, Max, Mean, Min, Std, Sum
from ray_tpu.data.datasource import (
    Datasink,
    Datasource,
    FileBasedDatasink,
    FileBasedDatasource,
    ReadTask,
)
from ray_tpu.data.executor import ActorPoolStrategy
from ray_tpu.data.feed import FeedStats
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.dataset import (
    Dataset,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range_dataset as range,  # noqa: A001 — mirrors ray.data.range
    read_bigquery,
    read_binary_files,
    read_images,
    read_mongo,
    read_numpy,
    read_csv,
    read_datasource,
    read_json,
    read_parquet,
    read_sql,
    read_text,
    read_tfrecords,
    read_webdataset,
)

__all__ = [
    "ActorPoolStrategy",
    "DataIterator",
    "Datasink",
    "Datasource",
    "Dataset",
    "FeedStats",
    "FileBasedDatasink",
    "FileBasedDatasource",
    "ReadTask",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "read_binary_files",
    "read_images",
    "read_numpy",
    "read_datasource",
    "read_parquet",
    "read_text",
    "read_csv",
    "read_json",
    "aggregate",
    "Count",
    "Sum",
    "Min",
    "Max",
    "Mean",
    "Std",
]
