"""Background device-feed pipeline: data blocks -> host batches -> HBM.

The paper's north star is a step loop that never waits on the host. This
module supplies the host half of that contract for input pipelines: a
bounded producer thread pulls blocks (rt.prefetch + rt.get overlap the
cross-node transfer), assembles zero-copy numpy batches, and optionally
stages `jax.device_put` so batch i+1's H2D transfer is in flight while
step i computes. The consumer iterates batches off a depth-k queue; when
the queue is empty on arrival that's a feed stall — counted and timed so
a starved step loop is diagnosable from Dataset.stats() and the
`data_feed_*` metrics rather than by profiler archaeology.

Reference analog: ray.data's prefetching block iterator
(python/ray/data/_internal/block_batching/iter_batches.py) collapsed to
one thread + one bounded queue.

Thread discipline (rtlint RT006): the producer is a module-level
function that communicates with the consumer ONLY through the queue
(("batch", v) / ("error", exc) / ("done", None) tuples), a stop Event,
and the lock-guarded FeedStats. No instance attribute is written on one
side and read on the other.
"""

from __future__ import annotations

import queue
import threading
import time
import weakref
from typing import Any, Callable, Iterable, Iterator, Optional, Tuple

from ray_tpu.util import metrics as _metrics
from ray_tpu.util import tracing as _tracing

# Wall seconds the consumer spent blocked on an empty feed queue (the
# step loop outran the producer). One observation per stall.
_STALL_SECONDS = _metrics.get_or_create(
    _metrics.Histogram,
    "data_feed_stall_seconds",
    "Consumer wait per feed stall (queue empty when the step loop "
    "asked for a batch)",
    boundaries=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0),
)
_BATCHES_TOTAL = _metrics.get_or_create(
    _metrics.Counter,
    "data_feed_batches_total",
    "Batches delivered through the background device-feed pipeline",
)


class FeedStats:
    """Per-iterator feed timings, written from both sides of the pipe.

    wait_s/stall_count are consumer-side (time blocked on the queue);
    assemble_s (block pull + batch slicing) and h2d_s (device_put
    dispatch) are producer-side. All mutation is lock-guarded; read a
    consistent view with snapshot().
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._wait_s = 0.0
        self._assemble_s = 0.0
        self._h2d_s = 0.0
        self._stall_count = 0
        self._batches = 0

    def add_wait(self, seconds: float):
        with self._lock:
            self._wait_s += seconds
            self._stall_count += 1
        _STALL_SECONDS.observe(seconds)

    def add_assemble(self, seconds: float):
        with self._lock:
            self._assemble_s += seconds

    def add_h2d(self, seconds: float):
        with self._lock:
            self._h2d_s += seconds

    def add_batch(self):
        with self._lock:
            self._batches += 1
        _BATCHES_TOTAL.inc()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "wait_s": self._wait_s,
                "assemble_s": self._assemble_s,
                "h2d_s": self._h2d_s,
                "stall_count": self._stall_count,
                "batches": self._batches,
            }

    def render(self) -> str:
        s = self.snapshot()
        return (
            f"feed: {s['batches']} batches, "
            f"assemble {s['assemble_s'] * 1e3:.1f}ms, "
            f"h2d {s['h2d_s'] * 1e3:.1f}ms, "
            f"stalls {s['stall_count']} ({s['wait_s'] * 1e3:.1f}ms waiting)"
        )


def _q_put(q: "queue.Queue", item: Tuple[str, Any],
           stop_event: threading.Event) -> bool:
    """put() that never wedges on a full queue after the consumer left:
    poll the stop event between bounded attempts."""
    while not stop_event.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def _produce(source_factory: Callable[[], Iterable],
             transform: Optional[Callable[[Any], Any]],
             q: "queue.Queue", stop_event: threading.Event,
             stats: FeedStats, trace_ctx=None) -> None:
    """Producer-thread body. Terminates by enqueueing ("done", None) /
    ("error", exc), or silently when the stop event fires.

    trace_ctx is the span context active when the prefetcher was built:
    trace context is thread-local, so without re-attaching it here every
    span the pull/assembly path opens (rt.get, rt.prefetch) would root a
    detached trace instead of joining the request tree.
    """
    try:
        with _tracing.attach(trace_ctx):
            it = iter(source_factory())
            while not stop_event.is_set():
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    break
                stats.add_assemble(time.perf_counter() - t0)
                if transform is not None:
                    t1 = time.perf_counter()
                    item = transform(item)
                    stats.add_h2d(time.perf_counter() - t1)
                if not _q_put(q, ("batch", item), stop_event):
                    return
            _q_put(q, ("done", None), stop_event)
    except BaseException as e:  # noqa: BLE001 — shipped to the consumer
        _q_put(q, ("error", e), stop_event)


def _shutdown(q: "queue.Queue", stop_event: threading.Event,
              thread: threading.Thread) -> None:
    """Idempotent teardown (stop() and GC finalizer): wake the producer
    out of any blocking put by draining, then join."""
    stop_event.set()
    try:
        while True:
            q.get_nowait()
    except queue.Empty:
        pass
    if thread.is_alive():
        thread.join(timeout=5.0)


class _DevicePrefetcher:
    """Iterator that runs its source on a background thread, `depth`
    batches ahead of the consumer (plus the one being assembled).

    `transform` runs producer-side — pass the device_put staging there so
    the H2D transfer for batch i+1 is dispatched while the consumer is
    still inside step i. Exceptions from the source or transform
    re-raise at the consumer's next(); stop() (also wired to GC) joins
    the thread.
    """

    def __init__(self, source_factory: Callable[[], Iterable],
                 depth: int,
                 transform: Optional[Callable[[Any], Any]] = None,
                 stats: Optional[FeedStats] = None,
                 name: str = "feed"):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._stats = stats if stats is not None else FeedStats()
        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop_event = threading.Event()
        self._thread = threading.Thread(
            target=_produce,
            args=(source_factory, transform, self._queue, self._stop_event,
                  self._stats, _tracing.current()),
            name=f"rt-data-{name}",
            daemon=True,
        )
        # The finalizer must not capture self, or it would keep the
        # prefetcher alive and GC could never trigger it.
        self._finalizer = weakref.finalize(
            self, _shutdown, self._queue, self._stop_event, self._thread
        )
        self._finished = False
        self._thread.start()

    @property
    def stats(self) -> FeedStats:
        return self._stats

    def stop(self) -> None:
        """Stop the producer and join its thread (idempotent)."""
        self._finished = True
        self._finalizer()

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        try:
            kind, payload = self._queue.get_nowait()
        except queue.Empty:
            # Feed stall: the consumer outran the producer.
            t0 = time.perf_counter()
            kind, payload = self._blocking_get()
            self._stats.add_wait(time.perf_counter() - t0)
        if kind == "batch":
            self._stats.add_batch()
            return payload
        self.stop()
        if kind == "error":
            raise payload
        raise StopIteration  # "done"

    def _blocking_get(self) -> Tuple[str, Any]:
        while True:
            try:
                return self._queue.get(timeout=0.5)
            except queue.Empty:
                if self._stop_event.is_set() or not self._thread.is_alive():
                    # Producer died without a terminal item (or an external
                    # stop raced us): end the stream instead of wedging.
                    self._finished = True
                    raise StopIteration from None
