"""Autoregressive generation with a KV cache.

The inference half of the model stack: prefill runs the full forward once
(flash attention), then decode steps append one token at a time against a
preallocated KV cache — static shapes throughout so the decode step
compiles once and stays on the TPU (`lax.scan` over steps, masked
attention against the cache).

The reference has no analog (models live in user code); this is what
`serve`-ing an LLM on TPU needs: one jitted `prefill` + one jitted
`decode_step` per (batch, max_len) shape.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.models.transformer import (
    TransformerConfig,
    _act,
    _embed_tokens,
    project_logits,
)
from ray_tpu.ops import apply_rope, rmsnorm, rope_frequencies

NEG_INF = -1e30


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int) -> Dict:
    """Preallocated [layers, batch, max_len, kv_heads, head_dim] cache."""
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype=cfg.dtype),
        "v": jnp.zeros(shape, dtype=cfg.dtype),
        "length": jnp.zeros((), dtype=jnp.int32),
    }


def _cached_attention(q, k_cache, v_cache, cache_len):
    """q: [B, Lq, H, D] against cache [B, Lmax, KVH, D] (first cache_len
    valid). GQA via grouped einsum — decode is HBM-bandwidth-bound, so the
    cache must be read at its native size, never repeat-materialized in
    the hot loop. Causal masking by absolute position."""
    b, lq, h, d = q.shape
    kvh = k_cache.shape[2]
    group = h // kvh
    lmax = k_cache.shape[1]
    scale = d ** -0.5
    # Query i sits at absolute position cache_len - lq + i; key j at j.
    q_pos = cache_len - lq + jax.lax.broadcasted_iota(
        jnp.int32, (lq, lmax), 0
    )
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (lq, lmax), 1)
    valid = (k_pos <= q_pos) & (k_pos < cache_len)
    kf = k_cache.astype(jnp.float32)
    vf = v_cache.astype(jnp.float32)
    if group == 1:  # MHA: plain 4-D einsum (the 5-D form costs ~10%)
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), kf) * scale
        s = jnp.where(valid[None, None], s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, vf)
        return out.astype(q.dtype)
    qg = q.reshape(b, lq, kvh, group, d).astype(jnp.float32)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, kf) * scale
    s = jnp.where(valid[None, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", p, vf).reshape(b, lq, h, d)
    return out.astype(q.dtype)


def _forward_with_cache(params, tokens, cache, cfg: TransformerConfig):
    """Forward over `tokens` (appended at cache['length']); returns
    (logits for the final position, updated cache)."""
    if cfg.num_experts:
        raise ValueError("generation supports dense configs (MoE TBD)")
    x = _embed_tokens(params, tokens, cfg)
    b, lq = tokens.shape
    lmax = cache["k"].shape[2]
    cos, sin = rope_frequencies(cfg.head_dim, lmax, cfg.rope_theta)
    start = cache["length"]
    positions = start + jnp.arange(lq, dtype=jnp.int32)[None, :]

    def layer(carry, inputs):
        x = carry
        lp, k_cache_l, v_cache_l = inputs
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ lp["wq"]).reshape(b, lq, cfg.n_heads, cfg.head_dim)
        k = (h @ lp["wk"]).reshape(b, lq, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"]).reshape(b, lq, cfg.n_kv_heads, cfg.head_dim)
        if cfg.qk_norm:
            q = rmsnorm(q, lp["q_norm"], cfg.norm_eps, use_pallas=False)
            k = rmsnorm(k, lp["k_norm"], cfg.norm_eps, use_pallas=False)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        k_cache_l = jax.lax.dynamic_update_slice(
            k_cache_l, k.astype(k_cache_l.dtype), (0, start, 0, 0)
        )
        v_cache_l = jax.lax.dynamic_update_slice(
            v_cache_l, v.astype(v_cache_l.dtype), (0, start, 0, 0)
        )
        attn = _cached_attention(q, k_cache_l, v_cache_l, start + lq)
        x = x + (attn.reshape(b, lq, -1) @ lp["wo"]).astype(x.dtype)
        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        gate = _act(cfg)((h @ lp["w_gate"]).astype(jnp.float32))
        up = (h @ lp["w_up"]).astype(jnp.float32)
        x = x + (((gate * up).astype(x.dtype)) @ lp["w_down"])
        return x, (k_cache_l, v_cache_l)

    x, (k_new, v_new) = jax.lax.scan(
        layer, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = project_logits(x[:, -1], params, cfg)  # [B, vocab]
    new_cache = {"k": k_new, "v": v_new, "length": start + lq}
    return logits, new_cache


def prefill(params, tokens, cache, cfg: TransformerConfig):
    """Run the prompt through the model, filling the cache.

    Returns (last-position logits [B, vocab], cache).
    """
    return _forward_with_cache(params, tokens, cache, cfg)


def decode_step(params, token, cache, cfg: TransformerConfig):
    """One incremental decode step. token: [B] int32."""
    return _forward_with_cache(params, token[:, None], cache, cfg)


def _filter_top_k(logits: jax.Array, k: int) -> jax.Array:
    """Mask all but the k highest logits to -inf (compiler-friendly:
    lax.top_k + threshold compare, no gather/scatter)."""
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


def _filter_top_p(logits: jax.Array, p: float) -> jax.Array:
    """Nucleus filtering: keep the smallest prefix of the probability-
    sorted vocab whose mass reaches p; mask the rest to -inf."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Position i is kept while the mass BEFORE it is < p (so the token
    # that crosses p stays included — standard nucleus convention).
    keep = (cum - probs) < p
    # Threshold logit = smallest kept sorted logit per row.
    threshold = jnp.min(
        jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < threshold, -jnp.inf, logits)


@functools.lru_cache(maxsize=64)
def _compiled_generate(cfg: TransformerConfig, max_new_tokens: int,
                       temperature: float, top_k: Optional[int],
                       top_p: Optional[float], eos_id: Optional[int]):
    """One jitted end-to-end program (prefill + scanned decode + pick)
    per (config, sampling signature); jax.jit's own cache handles
    distinct prompt shapes underneath. Without this, generate() ran
    eagerly — every layer op a separate dispatch, every decode step a
    host round trip — which is why the warmed static serving probe
    measured ~27x slower than raw batched decode (BENCH_INFER r5:
    11.5 tok/s vs 308.9 raw at batch 1)."""

    def run(params, prompt, rng):
        b, lp = prompt.shape
        max_len = lp + max_new_tokens
        cache = init_kv_cache(cfg, b, max_len)
        logits, cache = prefill(params, prompt, cache, cfg)

        def pick(logits, key):
            if temperature and temperature > 0.0:
                logits = logits / temperature
                if top_k is not None:
                    logits = _filter_top_k(logits, top_k)
                if top_p is not None and top_p < 1.0:
                    logits = _filter_top_p(logits, top_p)
                return jax.random.categorical(key, logits, axis=-1)
            return jnp.argmax(logits, axis=-1)

        rng, key0 = jax.random.split(rng)
        first = pick(logits, key0).astype(jnp.int32)
        done0 = (
            first == eos_id if eos_id is not None
            else jnp.zeros((b,), dtype=bool)
        )

        def step(carry, key):
            token, cache, done = carry
            logits, cache = decode_step(params, token, cache, cfg)
            nxt = pick(logits, key).astype(jnp.int32)
            if eos_id is not None:
                nxt = jnp.where(done, jnp.int32(eos_id), nxt)
                done = done | (nxt == eos_id)
            return (nxt, cache, done), nxt

        if max_new_tokens == 1:
            return first[:, None]
        keys = jax.random.split(rng, max_new_tokens - 1)
        (_, _, _), rest = jax.lax.scan(
            step, (first, cache, done0), keys
        )
        return jnp.concatenate([first[:, None], rest.T], axis=1)

    return jax.jit(run)


def generate(
    params,
    prompt: jax.Array,  # [B, Lp] int32
    cfg: TransformerConfig,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    rng: Optional[jax.Array] = None,
    eos_id: Optional[int] = None,
) -> jax.Array:
    """Greedy (temperature=0) or sampled generation with optional top-k /
    nucleus (top-p) filtering; returns [B, max_new_tokens] generated ids
    (padded with eos after stopping). The whole pipeline — prefill,
    the scanned decode loop, and token picks — is ONE jitted program,
    cached per (config, sampling signature): repeat calls at the same
    shapes pay a single dispatch, no per-step host traffic.
    """
    b, _ = prompt.shape
    if max_new_tokens <= 0:
        return jnp.zeros((b, 0), dtype=jnp.int32)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    fn = _compiled_generate(
        cfg, int(max_new_tokens),
        float(temperature) if temperature else 0.0,
        None if top_k is None else int(top_k),
        None if top_p is None else float(top_p),
        None if eos_id is None else int(eos_id),
    )
    return fn(params, jnp.asarray(prompt, dtype=jnp.int32), rng)
