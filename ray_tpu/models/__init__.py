"""Model zoo: TPU-first functional models.

The flagship family is the Llama-style decoder (`transformer.py`) with
full sharding annotations (dp/fsdp/tp/sp axes), a MoE variant, and small
MLP/conv models for trainer tests. Everything is plain functional JAX
(params = pytrees) so the same code paths run under pjit, shard_map, and
the pipeline scheduler.
"""

from ray_tpu.models.conv import (
    ATARI_FILTERS,
    RESNET_CONFIGS,
    ResNetConfig,
    TINY_FILTERS,
    cnn_torso_forward,
    init_cnn_torso,
    init_resnet,
    resnet_forward,
    resnet_loss,
    resnet_param_logical_axes,
)
from ray_tpu.models.transformer import (
    TransformerConfig,
    init_params,
    forward,
    forward_pipelined,
    loss_fn,
    param_logical_axes,
)
from ray_tpu.models import configs
from ray_tpu.models.generate import decode_step, generate, init_kv_cache, prefill

__all__ = [
    "ResNetConfig",
    "RESNET_CONFIGS",
    "init_resnet",
    "resnet_forward",
    "resnet_loss",
    "resnet_param_logical_axes",
    "init_cnn_torso",
    "cnn_torso_forward",
    "ATARI_FILTERS",
    "TINY_FILTERS",
    "TransformerConfig",
    "init_params",
    "forward",
    "forward_pipelined",
    "loss_fn",
    "param_logical_axes",
    "configs",
    "generate",
    "prefill",
    "decode_step",
    "init_kv_cache",
]
