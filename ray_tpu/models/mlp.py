"""Small models for trainer/RL tests: MLP classifier and policy/value nets."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp


def init_mlp(key, sizes: Sequence[int], dtype=jnp.float32):
    params = []
    keys = jax.random.split(key, len(sizes) - 1)
    for k, (fan_in, fan_out) in zip(keys, zip(sizes[:-1], sizes[1:])):
        w = jax.random.normal(k, (fan_in, fan_out), dtype=jnp.float32)
        params.append(
            {
                "w": (w * (2.0 / fan_in) ** 0.5).astype(dtype),
                "b": jnp.zeros((fan_out,), dtype=dtype),
            }
        )
    return params


def mlp_forward(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x


def mlp_classifier_loss(params, batch):
    logits = mlp_forward(params, batch["x"])
    labels = batch["y"]
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
    acc = (logits.argmax(-1) == labels).mean()
    return loss, {"loss": loss, "accuracy": acc}
