"""Llama-family decoder-only transformer, TPU-first.

Design choices for the TPU:
  * params are a pytree with the layer stack as a leading axis and the
    forward pass is a `lax.scan` over layers — one compiled layer body,
    O(1) compile time in depth, and the natural substrate for pipeline
    parallelism (the "stage" axis shards over "pp").
  * every parameter carries logical sharding axes (param_logical_axes) so
    DP/FSDP/TP are pure annotations; GSPMD inserts the collectives.
  * attention is the fused flash kernel (ops/flash_attention.py) by
    default, ring attention (parallel/ring_attention.py) when the config
    enables sequence sharding.
  * bfloat16 activations/params by default — MXU native.

This is the model stack the reference lacks natively (it delegates to
torch models inside user train loops; SURVEY.md §2.4) — here it is part of
the framework so JaxTrainer/Serve/RL all share it.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops import apply_rope, flash_attention, rmsnorm, rope_frequencies, softmax_cross_entropy


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    d_ff: int = 11008
    max_seq: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    # MoE (0 experts = dense)
    num_experts: int = 0
    experts_per_token: int = 2
    # attention implementation: "flash" | "ring" | "ulysses"
    attn_impl: str = "flash"
    # Flash-attention Pallas block sizes. bk=512 benches ~7% faster than
    # 256 on v5e (fewer kv-loop iterations per MXU-resident q block);
    # larger blocks blow the ~16MB VMEM scoped budget.
    attn_block_q: int = 256
    attn_block_k: int = 512
    remat: bool = True
    # Rematerialization policy under remat=True: "full" recomputes the
    # whole layer (min memory, the safe default); "dots_nobatch" saves
    # non-batch matmul outputs
    # (jax.checkpoint_policies.dots_with_no_batch_dims_saveable) — ~12%
    # faster than full on the 0.8B bench at the cost of activation memory;
    # "dots" saves every matmul. Opt in per config/run.
    remat_policy: str = "full"
    # Pipeline parallelism: microbatches per step when the mesh has pp>1
    # (0 = auto: 2*stages when the batch divides, else stages, else 1).
    pp_microbatches: int = 0
    # Chunked lm_head + cross-entropy: compute the loss in sequence
    # chunks of this many tokens so the full [B, S, vocab] logits tensor
    # (1.5GB at the 0.8B bench shape) is never materialized — the
    # backward recomputes each chunk's logits (~3% extra FLOPs) in
    # exchange for the freed HBM. 0 = off (single fused matmul).
    ce_chunk: int = 0
    # Family knobs beyond Llama (Gemma et al., arXiv:2403.08295):
    # MLP activation ("silu" = Llama SwiGLU, "gelu" = Gemma GeGLU),
    # tanh softcap on final logits (0 = off), input/output embedding
    # tying, and sqrt(d_model) embedding scaling.
    activation: str = "silu"
    final_logit_softcap: float = 0.0
    tie_embeddings: bool = False
    scale_embeddings: bool = False
    # Qwen3-style QK-norm (arXiv:2505.09388): learned per-head-dim
    # RMSNorm on q and k before RoPE, stabilizing attention logits at
    # scale (replaces Qwen2's QKV bias).
    qk_norm: bool = False
    # Explicit head dim when it differs from d_model/n_heads (Qwen3
    # uses 128-wide heads at every scale). 0 = derive from d_model.
    custom_head_dim: int = 0

    @property
    def head_dim(self) -> int:
        return self.custom_head_dim or self.d_model // self.n_heads


def _dense_init(key, shape, scale, dtype):
    return (jax.random.normal(key, shape, dtype=jnp.float32) * scale).astype(dtype)


def init_params(key: jax.Array, cfg: TransformerConfig) -> Dict:
    """Initialize the full parameter pytree (layers stacked on axis 0)."""
    keys = jax.random.split(key, 10)
    d, h, kvh, hd, ff = (
        cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.d_ff
    )
    L = cfg.n_layers
    scale = d ** -0.5

    def stack(k, shape, scale):
        ks = jax.random.split(k, L)
        return jnp.stack([_dense_init(ks[i], shape, scale, cfg.dtype) for i in range(L)])

    layer = {
        "attn_norm": jnp.ones((L, d), dtype=cfg.dtype),
        "wq": stack(keys[0], (d, h * hd), scale),
        "wk": stack(keys[1], (d, kvh * hd), scale),
        "wv": stack(keys[2], (d, kvh * hd), scale),
        "wo": stack(keys[3], (h * hd, d), scale * (2 * L) ** -0.5),
        "mlp_norm": jnp.ones((L, d), dtype=cfg.dtype),
    }
    if cfg.qk_norm:
        layer["q_norm"] = jnp.ones((L, hd), dtype=cfg.dtype)
        layer["k_norm"] = jnp.ones((L, hd), dtype=cfg.dtype)
    if cfg.num_experts == 0:
        layer.update(
            {
                "w_gate": stack(keys[4], (d, ff), scale),
                "w_up": stack(keys[5], (d, ff), scale),
                "w_down": stack(keys[6], (ff, d), scale * (2 * L) ** -0.5),
            }
        )
    else:
        E = cfg.num_experts
        sub = jax.random.split(keys[4], 3)
        layer.update(
            {
                "router": stack(keys[7], (d, E), scale),
                "w_gate": stack(sub[0], (E, d, ff), scale),
                "w_up": stack(sub[1], (E, d, ff), scale),
                "w_down": stack(sub[2], (E, ff, d), scale * (2 * L) ** -0.5),
            }
        )
    params = {
        "embed": _dense_init(keys[8], (cfg.vocab_size, d), 1.0, cfg.dtype),
        "layers": layer,
        "final_norm": jnp.ones((d,), dtype=cfg.dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = _dense_init(
            keys[9], (d, cfg.vocab_size), scale, cfg.dtype
        )
    return params


def param_logical_axes(cfg: TransformerConfig) -> Dict:
    """Logical sharding axes mirroring init_params' tree.

    Mapped through parallel.mesh.DEFAULT_RULES: "embed"->fsdp, "mlp"/
    "heads"/"vocab"->tp, "expert"->ep, layer-stack axis -> "stage" (pp).
    """
    layer = {
        "attn_norm": ("stage", None),
        "wq": ("stage", "embed", "heads"),
        "wk": ("stage", "embed", "heads"),
        "wv": ("stage", "embed", "heads"),
        "wo": ("stage", "heads", "embed"),
        "mlp_norm": ("stage", None),
    }
    if cfg.qk_norm:
        layer["q_norm"] = ("stage", None)
        layer["k_norm"] = ("stage", None)
    if cfg.num_experts == 0:
        layer.update(
            {
                "w_gate": ("stage", "embed", "mlp"),
                "w_up": ("stage", "embed", "mlp"),
                "w_down": ("stage", "mlp", "embed"),
            }
        )
    else:
        layer.update(
            {
                "router": ("stage", "embed", None),
                "w_gate": ("stage", "expert", "embed", "mlp"),
                "w_up": ("stage", "expert", "embed", "mlp"),
                "w_down": ("stage", "expert", "mlp", "embed"),
            }
        )
    axes = {
        "embed": ("vocab", "embed"),
        "layers": layer,
        "final_norm": (None,),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def _act(cfg: TransformerConfig):
    if cfg.activation == "silu":
        return jax.nn.silu
    if cfg.activation == "gelu":
        return lambda x: jax.nn.gelu(x, approximate=True)
    raise ValueError(f"unknown activation {cfg.activation!r}")


def _embed_tokens(params, tokens, cfg: TransformerConfig):
    x = params["embed"][tokens].astype(cfg.dtype)
    if cfg.scale_embeddings:  # Gemma normalizes the embedding scale
        x = x * jnp.asarray(cfg.d_model ** 0.5, dtype=cfg.dtype)
    return x


def lm_head_weight(params, cfg: TransformerConfig):
    """[D, V] output projection (the embedding transposed when tied)."""
    if cfg.tie_embeddings:
        return params["embed"].T
    return params["lm_head"]


def project_logits(x, params, cfg: TransformerConfig):
    logits = x @ lm_head_weight(params, cfg)
    if cfg.final_logit_softcap:
        cap = cfg.final_logit_softcap
        logits = cap * jnp.tanh(logits.astype(jnp.float32) / cap)
    return logits


def _attention(cfg: TransformerConfig, q, k, v, mesh, positions):
    if cfg.attn_impl == "ring" and mesh is not None and mesh.shape.get("sp", 1) > 1:
        from ray_tpu.parallel.ring_attention import ring_attention
        from jax.sharding import PartitionSpec as P

        spec = P(("dp", "fsdp"), "sp", "tp", None)
        return ring_attention(q, k, v, mesh, axis_name="sp", causal=True,
                              query_spec=spec)
    if cfg.attn_impl == "ulysses" and mesh is not None and mesh.shape.get("sp", 1) > 1:
        from ray_tpu.parallel.ulysses import ulysses_attention
        from jax.sharding import PartitionSpec as P

        spec = P(("dp", "fsdp"), "sp", "tp", None)
        return ulysses_attention(q, k, v, mesh, axis_name="sp", causal=True,
                                 query_spec=spec)
    return flash_attention(q, k, v, causal=True,
                           block_q=cfg.attn_block_q, block_k=cfg.attn_block_k)


def _layer_fn(cfg: TransformerConfig, mesh, cos, sin, positions):
    """Build the per-layer body used by lax.scan."""

    def body(x, lp):
        # x: [B, L, D]
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        b, l, d = h.shape
        q = (h @ lp["wq"]).reshape(b, l, cfg.n_heads, cfg.head_dim)
        k = (h @ lp["wk"]).reshape(b, l, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"]).reshape(b, l, cfg.n_kv_heads, cfg.head_dim)
        if cfg.qk_norm:
            # Elementwise over head_dim: XLA fuses it into the rope/attn
            # pipeline (the pallas rmsnorm kernel targets [.., D] rows).
            q = rmsnorm(q, lp["q_norm"], cfg.norm_eps, use_pallas=False)
            k = rmsnorm(k, lp["k_norm"], cfg.norm_eps, use_pallas=False)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        attn = _attention(cfg, q, k, v, mesh, positions)
        x = x + (attn.reshape(b, l, -1) @ lp["wo"]).astype(x.dtype)

        h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        act = _act(cfg)
        if cfg.num_experts == 0:
            gate = act((h @ lp["w_gate"]).astype(jnp.float32))
            up = (h @ lp["w_up"]).astype(jnp.float32)
            mlp_out = ((gate * up).astype(x.dtype)) @ lp["w_down"]
            aux = jnp.zeros((), dtype=jnp.float32)
        else:
            from ray_tpu.parallel.moe import moe_layer

            def expert_fn(w, xin):  # xin: [E, C, D]
                g = act(jnp.einsum("ecd,edf->ecf", xin, w["gate"]))
                u = jnp.einsum("ecd,edf->ecf", xin, w["up"])
                return jnp.einsum("ecf,efd->ecd", g * u, w["down"])

            flat = h.reshape(b * l, d)
            mlp_flat, aux = moe_layer(
                flat.astype(jnp.float32),
                lp["router"].astype(jnp.float32),
                expert_fn,
                {"gate": lp["w_gate"], "up": lp["w_up"], "down": lp["w_down"]},
                k=cfg.experts_per_token,
            )
            mlp_out = mlp_flat.reshape(b, l, d).astype(x.dtype)
        x = x + mlp_out
        return x, aux

    if cfg.remat:
        if cfg.remat_policy == "dots_nobatch":
            body = jax.checkpoint(
                body,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            )
        elif cfg.remat_policy == "dots":
            body = jax.checkpoint(
                body, policy=jax.checkpoint_policies.dots_saveable
            )
        elif cfg.remat_policy == "full":
            body = jax.checkpoint(body)  # recompute everything (min memory)
        else:
            raise ValueError(
                f"unknown remat_policy {cfg.remat_policy!r}: "
                "expected 'full', 'dots', or 'dots_nobatch'"
            )
    return body


def forward(
    params: Dict,
    tokens: jax.Array,  # [batch, seq] int32
    cfg: TransformerConfig,
    mesh=None,
    positions: Optional[jax.Array] = None,
    return_hidden: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (logits [B, L, vocab], aux_loss scalar); with
    return_hidden, the pre-lm_head hidden states [B, L, D] instead of
    logits (the chunked-CE loss applies lm_head itself)."""
    x = _embed_tokens(params, tokens, cfg)
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    body = _layer_fn(cfg, mesh, cos, sin, positions)
    x, auxes = jax.lax.scan(body, x, params["layers"])
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    if return_hidden:
        return x, auxes.sum()
    return project_logits(x, params, cfg), auxes.sum()


def forward_pipelined(
    params: Dict,
    tokens: jax.Array,  # [batch, seq] int32
    cfg: TransformerConfig,
    mesh,
    num_microbatches: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Pipeline-parallel forward: the layer stack shards over "pp" stages.

    Each pp rank holds n_layers/S contiguous layers; microbatches stream
    through the GPipe schedule of parallel.pipeline.pipeline_stages (all
    stages inside one compiled program, activations rotated with ppermute).
    Embedding and the LM head are replicated — they run on every rank, but
    only the layer stack (the bulk of the FLOPs) is pipelined.
    """
    from ray_tpu.parallel.pipeline import pipeline_stages

    S = mesh.shape["pp"]
    dp_extent = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
    b, l = tokens.shape
    M = num_microbatches or cfg.pp_microbatches
    if not M:
        # Auto: prefer 2*S microbatches, but each microbatch's batch dim
        # must still split over dp/fsdp.
        for cand in (2 * S, S, 1):
            if b % cand == 0 and (b // cand) % dp_extent == 0:
                M = cand
                break
        else:
            raise ValueError(
                f"batch {b} cannot form pp microbatches divisible by the "
                f"dp extent {dp_extent}; pick batch = k * {S} * {dp_extent}"
            )
    if b % M != 0:
        raise ValueError(f"batch {b} not divisible by {M} pp microbatches")
    if (b // M) % dp_extent != 0:
        raise ValueError(
            f"microbatch size {b // M} not divisible by dp extent "
            f"{dp_extent} (batch {b}, {M} microbatches)"
        )
    if cfg.n_layers % S != 0:
        raise ValueError(f"n_layers {cfg.n_layers} not divisible by pp={S}")
    if cfg.num_experts:
        raise ValueError(
            "pipeline parallelism currently supports dense layers only "
            "(the MoE aux loss does not thread through the pp schedule)"
        )

    x = _embed_tokens(params, tokens, cfg)
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    body = _layer_fn(cfg, mesh, cos, sin, None)

    def stage_fn(stage_layers, act):
        # stage_layers: leaves [n_layers/S, ...] — this rank's stage.
        act, _ = jax.lax.scan(body, act, stage_layers)
        return act

    xm = x.reshape(M, b // M, l, x.shape[-1])
    # pp composes with data parallelism: each microbatch's batch dim
    # splits over dp/fsdp inside the pipeline shard_map, so a dp×pp mesh
    # runs dp-many replicas of every pipeline stage.
    from jax.sharding import PartitionSpec as P

    dp_axes = tuple(
        a for a in ("dp", "fsdp") if mesh.shape.get(a, 1) > 1
    )
    x_spec = P(None, dp_axes) if dp_axes else P()
    ym = pipeline_stages(
        stage_fn, params["layers"], xm, mesh, axis_name="pp", x_spec=x_spec
    )
    x = ym.reshape(b, l, x.shape[-1])
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    return project_logits(x, params, cfg), jnp.zeros((), dtype=jnp.float32)


def loss_fn(params, tokens, cfg: TransformerConfig, mesh=None,
            aux_weight: float = 0.01):
    """Next-token LM loss. tokens: [B, L]; predicts tokens[:, 1:].

    With a pp>1 mesh the forward runs the GPipe microbatch pipeline; the
    backward differentiates straight through it (static-bound scan), which
    is what makes MeshConfig(pp=...) a real training capability.
    """
    labels = tokens[:, 1:]
    if mesh is not None and mesh.shape.get("pp", 1) > 1:
        logits, aux = forward_pipelined(params, tokens[:, :-1], cfg, mesh)
    elif cfg.ce_chunk:
        from ray_tpu.ops.cross_entropy import chunked_lm_head_ce

        hidden, aux = forward(params, tokens[:, :-1], cfg, mesh,
                              return_hidden=True)
        loss = chunked_lm_head_ce(
            hidden, lm_head_weight(params, cfg), labels, cfg.ce_chunk,
            softcap=cfg.final_logit_softcap,
        )
        return loss + aux_weight * aux
    else:
        logits, aux = forward(params, tokens[:, :-1], cfg, mesh)
    loss = softmax_cross_entropy(logits, labels).mean()
    return loss + aux_weight * aux
