"""Conv model family: CNN torsos and ResNet classifiers, TPU-first.

Fills the vision slots of the reference's model zoo — the conv nets
rllib's catalog builds from ``conv_filters``/``conv_activation``
(reference: rllib/models/catalog.py:105-116) and the ResNet configs the
vision trainers use (reference: python/ray/train/examples/ — the
"JaxTrainer ResNet data-parallel" north-star config).

TPU-first choices:
  * NHWC layout end-to-end — XLA's preferred conv layout on TPU (the
    MXU consumes (spatial, channel) tiles directly; NCHW forces
    transposes).
  * GroupNorm instead of BatchNorm: no mutable running statistics, so
    the model stays a pure function of (params, batch) — jit/pjit-able
    with zero state plumbing — and no cross-replica stat sync is needed
    under data parallelism (BatchNorm's sync is an all-reduce XLA can't
    fuse into the conv).
  * Everything is plain functional JAX over a params pytree, like the
    flagship transformer, so the same code runs under jit, pjit/GSPMD,
    and inside learner actors.
  * `resnet_param_logical_axes` annotates channel dims for fsdp/tp
    sharding through parallel.mesh.DEFAULT_RULES (conv kernels shard
    their output-channel dim the way dense kernels shard theirs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

# NHWC activations x HWIO kernels -> NHWC.
_DN = ("NHWC", "HWIO", "NHWC")


def init_conv(key, kh: int, kw: int, cin: int, cout: int,
              dtype=jnp.float32) -> Dict:
    """He-initialized conv kernel + bias (HWIO)."""
    fan_in = kh * kw * cin
    w = jax.random.normal(key, (kh, kw, cin, cout), dtype=jnp.float32)
    return {
        "w": (w * (2.0 / fan_in) ** 0.5).astype(dtype),
        "b": jnp.zeros((cout,), dtype=dtype),
    }


def conv_forward(p: Dict, x: jax.Array, stride: int = 1,
                 padding: str = "SAME") -> jax.Array:
    out = jax.lax.conv_general_dilated(
        x, p["w"], window_strides=(stride, stride), padding=padding,
        dimension_numbers=_DN,
    )
    return out + p["b"]


def init_group_norm(c: int, dtype=jnp.float32) -> Dict:
    return {"scale": jnp.ones((c,), dtype=dtype),
            "bias": jnp.zeros((c,), dtype=dtype)}


def group_norm(p: Dict, x: jax.Array, groups: int = 8,
               eps: float = 1e-5) -> jax.Array:
    """GroupNorm over NHWC (groups divide C; falls back to the largest
    divisor <= groups so narrow stems still normalize)."""
    c = x.shape[-1]
    g = groups
    while c % g:
        g -= 1
    shape = x.shape[:-1] + (g, c // g)
    xg = x.reshape(shape)
    mean = xg.mean(axis=(-4, -3, -1), keepdims=True)
    var = xg.var(axis=(-4, -3, -1), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    return xg.reshape(x.shape) * p["scale"] + p["bias"]


# ---------------------------------------------------------------------------
# CNN torso (the catalog's conv_filters model): feature extractor for RL
# policies over image observations.
# ---------------------------------------------------------------------------

# (out_channels, kernel, stride) per layer — the catalog's default shape
# family for 84x84 Atari frames (reference: catalog.py conv_filters).
ATARI_FILTERS: Tuple[Tuple[int, int, int], ...] = (
    (32, 8, 4), (64, 4, 2), (64, 3, 1),
)
# A small family for tiny test envs (12x12-ish frames).
TINY_FILTERS: Tuple[Tuple[int, int, int], ...] = ((16, 3, 2), (32, 3, 2))


def init_cnn_torso(key, obs_shape: Tuple[int, int, int],
                   conv_filters: Sequence[Tuple[int, int, int]],
                   out_dim: int = 256, dtype=jnp.float32) -> Dict:
    """Conv stack + flatten + dense projection to a feature vector."""
    h, w, cin = obs_shape
    keys = jax.random.split(key, len(conv_filters) + 1)
    convs = []
    for k, (cout, kern, stride) in zip(keys, conv_filters):
        convs.append(init_conv(k, kern, kern, cin, cout, dtype))
        h = -(-h // stride)  # ceil-div: SAME padding
        w = -(-w // stride)
        cin = cout
    flat = h * w * cin
    proj = jax.random.normal(keys[-1], (flat, out_dim), dtype=jnp.float32)
    return {
        "convs": convs,
        "proj_w": (proj * (2.0 / flat) ** 0.5).astype(dtype),
        "proj_b": jnp.zeros((out_dim,), dtype=dtype),
    }


def cnn_torso_forward(params: Dict, x: jax.Array,
                      conv_filters: Sequence[Tuple[int, int, int]]) -> jax.Array:
    """(B, H, W, C) float obs -> (B, out_dim) features."""
    for p, (_, _, stride) in zip(params["convs"], conv_filters):
        x = jax.nn.relu(conv_forward(p, x, stride=stride))
    x = x.reshape(x.shape[0], -1)
    return jax.nn.relu(x @ params["proj_w"] + params["proj_b"])


# ---------------------------------------------------------------------------
# ResNet (v2 pre-activation, GroupNorm) classifier.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ResNetConfig:
    """Stage layout. resnet18-style: stage_sizes=(2, 2, 2, 2); cifar
    tests shrink width/stages. num_groups is the GroupNorm group count.
    """

    num_classes: int = 10
    stage_sizes: Tuple[int, ...] = (2, 2, 2, 2)
    width: int = 64
    stem_kernel: int = 3  # 7 for ImageNet-scale inputs
    stem_stride: int = 1  # 2 for ImageNet-scale inputs
    num_groups: int = 8
    dtype: object = jnp.float32


# Named presets (the JaxTrainer ResNet north-star shapes). resnet50 here
# is the 2-conv-per-block (basic, not bottleneck) layout at resnet50's
# stage depths — same parameter regime, simpler block; documented
# divergence from torchvision's bottleneck blocks.
RESNET_CONFIGS = {
    "resnet18-cifar": ResNetConfig(stage_sizes=(2, 2, 2, 2), width=64),
    "resnet18": ResNetConfig(
        num_classes=1000, stage_sizes=(2, 2, 2, 2), width=64,
        stem_kernel=7, stem_stride=2, dtype=jnp.bfloat16,
    ),
    "resnet50": ResNetConfig(
        num_classes=1000, stage_sizes=(3, 4, 6, 3), width=64,
        stem_kernel=7, stem_stride=2, dtype=jnp.bfloat16,
    ),
}


def _init_block(key, cin: int, cout: int, cfg: ResNetConfig) -> Dict:
    k1, k2, k3 = jax.random.split(key, 3)
    block = {
        "norm1": init_group_norm(cin, cfg.dtype),
        "conv1": init_conv(k1, 3, 3, cin, cout, cfg.dtype),
        "norm2": init_group_norm(cout, cfg.dtype),
        "conv2": init_conv(k2, 3, 3, cout, cout, cfg.dtype),
    }
    if cin != cout:
        block["proj"] = init_conv(k3, 1, 1, cin, cout, cfg.dtype)
    return block


def init_resnet(key, cfg: ResNetConfig) -> Dict:
    n_stages = len(cfg.stage_sizes)
    keys = jax.random.split(key, n_stages + 2)
    cin = cfg.width
    params: Dict = {
        "stem": init_conv(keys[0], cfg.stem_kernel, cfg.stem_kernel, 3,
                          cfg.width, cfg.dtype),
        "stages": [],
    }
    for s, n_blocks in enumerate(cfg.stage_sizes):
        cout = cfg.width * (2 ** s)
        bkeys = jax.random.split(keys[s + 1], n_blocks)
        stage = []
        for b in range(n_blocks):
            stage.append(_init_block(bkeys[b], cin, cout, cfg))
            cin = cout
        params["stages"].append(stage)
    params["final_norm"] = init_group_norm(cin, cfg.dtype)
    head = jax.random.normal(keys[-1], (cin, cfg.num_classes),
                             dtype=jnp.float32)
    params["head_w"] = (head * cin ** -0.5).astype(cfg.dtype)
    params["head_b"] = jnp.zeros((cfg.num_classes,), dtype=cfg.dtype)
    return params


def _block_forward(p: Dict, x: jax.Array, stride: int,
                   cfg: ResNetConfig) -> jax.Array:
    """Pre-activation residual block (norm-relu-conv x2)."""
    h = jax.nn.relu(group_norm(p["norm1"], x, cfg.num_groups))
    shortcut = x
    if "proj" in p or stride != 1:
        # Project the identity path when shape changes (1x1 conv when
        # channels change; strided slice-free conv handles downsample).
        if "proj" in p:
            shortcut = conv_forward(p["proj"], h, stride=stride)
        else:
            shortcut = x[:, ::stride, ::stride, :]
    h = conv_forward(p["conv1"], h, stride=stride)
    h = jax.nn.relu(group_norm(p["norm2"], h, cfg.num_groups))
    h = conv_forward(p["conv2"], h, stride=1)
    return shortcut + h


def resnet_forward(params: Dict, x: jax.Array, cfg: ResNetConfig) -> jax.Array:
    """(B, H, W, 3) images -> (B, num_classes) logits."""
    x = x.astype(cfg.dtype)
    h = conv_forward(params["stem"], x, stride=cfg.stem_stride)
    for s, stage in enumerate(params["stages"]):
        for b, block in enumerate(stage):
            stride = 2 if (b == 0 and s > 0) else 1
            h = _block_forward(block, h, stride, cfg)
    h = jax.nn.relu(group_norm(params["final_norm"], h, cfg.num_groups))
    h = h.mean(axis=(1, 2))  # global average pool
    return h @ params["head_w"] + params["head_b"]


def resnet_loss(params: Dict, batch: Dict, cfg: ResNetConfig):
    """Cross-entropy + accuracy over {"x": images NHWC, "y": labels}."""
    logits = resnet_forward(params, batch["x"], cfg)
    labels = batch["y"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    loss = -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
    acc = (logits.argmax(-1) == labels).mean()
    return loss, {"loss": loss, "accuracy": acc}


def resnet_param_logical_axes(cfg: ResNetConfig) -> Dict:
    """Logical sharding axes mirroring init_resnet's tree exactly: conv
    kernels shard output channels on the tp axis ("heads") and input
    channels on "embed" (fsdp), the dense head shards classes on
    "vocab", and GroupNorm scales replicate — the same rule names
    DEFAULT_RULES maps for the transformer, so the trainer's sharding
    machinery needs no conv-specific cases."""

    def conv_axes():
        return {"w": (None, None, "embed", "heads"), "b": ("heads",)}

    def norm_axes():
        return {"scale": (None,), "bias": (None,)}

    stages = []
    cin = cfg.width
    for s, n_blocks in enumerate(cfg.stage_sizes):
        cout = cfg.width * (2 ** s)
        stage = []
        for _ in range(n_blocks):
            block = {
                "norm1": norm_axes(),
                "conv1": conv_axes(),
                "norm2": norm_axes(),
                "conv2": conv_axes(),
            }
            if cin != cout:
                block["proj"] = conv_axes()
            stage.append(block)
            cin = cout
        stages.append(stage)
    return {
        # RGB input channels (3) are unshardable: the stem shards only
        # its output channels.
        "stem": {"w": (None, None, None, "heads"), "b": ("heads",)},
        "stages": stages,
        "final_norm": norm_axes(),
        "head_w": ("embed", "vocab"),
        "head_b": ("vocab",),
    }
