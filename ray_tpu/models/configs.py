"""Named model configurations.

Llama-2 family dimensions follow the published architecture (Touvron et
al., arXiv:2307.09288); tiny/test configs keep the same structure at toy
scale for CPU tests.
"""

from __future__ import annotations

import jax.numpy as jnp

from ray_tpu.models.transformer import TransformerConfig

# -- test-scale ------------------------------------------------------------

tiny = TransformerConfig(
    vocab_size=256,
    d_model=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=4,
    d_ff=128,
    max_seq=128,
    dtype=jnp.float32,
    remat=False,
)

tiny_gqa = TransformerConfig(
    vocab_size=256,
    d_model=64,
    n_layers=2,
    n_heads=8,
    n_kv_heads=2,
    d_ff=128,
    max_seq=128,
    dtype=jnp.float32,
    remat=False,
)

tiny_moe = TransformerConfig(
    vocab_size=256,
    d_model=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=4,
    d_ff=128,
    max_seq=128,
    dtype=jnp.float32,
    num_experts=4,
    experts_per_token=2,
    remat=False,
)

# -- benchmark-scale (fits one v5e chip in bf16 for forward benches) -------

llama2_1b = TransformerConfig(
    vocab_size=32000,
    d_model=2048,
    n_layers=16,
    n_heads=16,
    n_kv_heads=16,
    d_ff=5504,
    max_seq=2048,
)

# -- production-scale ------------------------------------------------------

llama2_7b = TransformerConfig(
    vocab_size=32000,
    d_model=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=32,
    d_ff=11008,
    max_seq=4096,
)

llama2_13b = TransformerConfig(
    vocab_size=32000,
    d_model=5120,
    n_layers=40,
    n_heads=40,
    n_kv_heads=40,
    d_ff=13824,
    max_seq=4096,
)

llama2_70b = TransformerConfig(
    vocab_size=32000,
    d_model=8192,
    n_layers=80,
    n_heads=64,
    n_kv_heads=8,  # GQA
    d_ff=28672,
    max_seq=4096,
)

llama3_8b = TransformerConfig(
    vocab_size=128256,
    d_model=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=8,
    d_ff=14336,
    max_seq=8192,
    rope_theta=500000.0,
)

tiny_gemma = TransformerConfig(
    vocab_size=256,
    d_model=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=1,
    d_ff=256,
    max_seq=128,
    dtype=jnp.float32,
    remat=False,
    activation="gelu",
    final_logit_softcap=30.0,
    tie_embeddings=True,
    scale_embeddings=True,
)

# Gemma-2B architecture (arXiv:2403.08295: GeGLU MLP, MQA, tied
# embeddings, sqrt(d) embedding scaling, final logit softcap).
gemma_2b = TransformerConfig(
    vocab_size=256128,
    d_model=2048,
    n_layers=18,
    n_heads=8,
    n_kv_heads=1,
    d_ff=16384,
    max_seq=8192,
    activation="gelu",
    final_logit_softcap=30.0,
    tie_embeddings=True,
    scale_embeddings=True,
)

mixtral_8x7b = TransformerConfig(
    vocab_size=32000,
    d_model=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=8,
    d_ff=14336,
    max_seq=4096,
    num_experts=8,
    experts_per_token=2,
)

tiny_qwen = TransformerConfig(
    vocab_size=256,
    d_model=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    d_ff=256,
    max_seq=128,
    dtype=jnp.float32,
    remat=False,
    qk_norm=True,
    custom_head_dim=32,  # wider than d_model/n_heads, the Qwen3 shape
)

# Qwen3-4B architecture (arXiv:2505.09388): GQA with fixed 128-wide
# heads, per-head-dim QK-norm instead of QKV bias, SwiGLU, 1M rope theta.
qwen3_4b = TransformerConfig(
    vocab_size=151936,
    d_model=2560,
    n_layers=36,
    n_heads=32,
    n_kv_heads=8,
    d_ff=9728,
    max_seq=32768,
    rope_theta=1000000.0,
    qk_norm=True,
    custom_head_dim=128,
    tie_embeddings=True,
)

NAMED_CONFIGS = {
    "tiny": tiny,
    "tiny_gqa": tiny_gqa,
    "tiny_moe": tiny_moe,
    "llama2-1b": llama2_1b,
    "llama2-7b": llama2_7b,
    "llama2-13b": llama2_13b,
    "llama2-70b": llama2_70b,
    "llama3-8b": llama3_8b,
    "tiny_gemma": tiny_gemma,
    "gemma-2b": gemma_2b,
    "mixtral-8x7b": mixtral_8x7b,
    "tiny_qwen": tiny_qwen,
    "qwen3-4b": qwen3_4b,
}


def get_config(name: str) -> TransformerConfig:
    if name not in NAMED_CONFIGS:
        raise KeyError(f"unknown model config {name!r}; have {list(NAMED_CONFIGS)}")
    return NAMED_CONFIGS[name]
