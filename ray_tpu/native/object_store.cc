// Shared-memory object store — the TPU-native analog of the reference's
// plasma store (src/ray/object_manager/plasma/store.h,
// object_lifecycle_manager.h, eviction_policy.h, plasma_allocator.h).
//
// Design differences from plasma, chosen for the one-process-per-TPU-host
// world:
//   * No store server process and no unix-socket protocol (plasma.fbs,
//     fling.cc fd-passing). All control state — the object index, the
//     allocator, refcounts, the LRU clock — lives *inside* the shared
//     memory segment, guarded by a process-shared robust mutex. Any
//     attached process creates/seals/gets objects directly; a create+seal
//     round trip is two mutex acquisitions instead of two socket round
//     trips. This matters because a TPU host runs O(1) workers (JAX wants
//     one process owning all chips), not O(100), so a lock-per-op design
//     is uncontended in practice.
//   * Allocation uses a boundary-tag first-fit free list with coalescing
//     (plasma uses a dlmalloc arena, plasma/dlmalloc.cc).
//   * Eviction: LRU over sealed refcount-0 objects via a monotonic clock
//     tick per Get/Seal (plasma: eviction_policy.h LRUCache).
//
// Object lifecycle mirrors plasma: Create (allocates, writable by creator)
// -> Seal (immutable, visible to others) -> Get/Release (pin/unpin) ->
// Delete or Evict.  Abort frees an unsealed object.
//
// Build: g++ -O2 -fPIC -shared -o libray_tpu_store.so object_store.cc

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5254535452554354ull;  // "RTSTRUCT"
constexpr uint32_t kIdSize = 16;
constexpr uint64_t kAlign = 64;
// Allocator block header: size of this block's payload, size of previous
// block's payload (for coalescing), free flag.
struct BlockHeader {
  uint64_t size;       // payload bytes
  uint64_t prev_size;  // payload bytes of the block immediately before us
  uint32_t free_flag;  // 1 = free
  uint32_t pad;
};
static_assert(sizeof(BlockHeader) == 24, "block header layout");

constexpr uint64_t kBlockOverhead = ((sizeof(BlockHeader) + kAlign - 1) / kAlign) * kAlign;

enum ObjectState : uint32_t {
  kFree = 0,
  kCreating = 1,
  kSealed = 2,
  kTombstone = 3,  // deleted hash slot; probe chains continue through it
};

struct Entry {
  uint8_t id[kIdSize];
  uint32_t state;
  uint32_t refcount;
  uint64_t offset;     // payload offset from segment base
  uint64_t data_size;  // bytes of object data
  uint64_t lru_tick;
};

struct Header {
  uint64_t magic;
  uint64_t total_size;
  uint64_t table_offset;
  uint64_t table_capacity;  // power of two
  uint64_t heap_offset;
  uint64_t heap_size;
  uint64_t free_head;  // offset of first free block header, 0 = none
  uint64_t lru_clock;
  // stats
  uint64_t used_bytes;
  uint64_t num_objects;
  uint64_t num_evictions;
  uint64_t evicted_bytes;
  pthread_mutex_t mutex;
};

struct Handle {
  uint8_t* base;
  uint64_t size;
  Header* header;
  char name[256];
};

inline Entry* table(Handle* h) {
  return reinterpret_cast<Entry*>(h->base + h->header->table_offset);
}

inline BlockHeader* block_at(Handle* h, uint64_t payload_off) {
  return reinterpret_cast<BlockHeader*>(h->base + payload_off - kBlockOverhead);
}

inline uint64_t payload_off(Handle* h, BlockHeader* b) {
  return static_cast<uint64_t>(reinterpret_cast<uint8_t*>(b) - h->base) + kBlockOverhead;
}

// Free-list links are stored in the first 16 bytes of a free block's payload.
struct FreeLinks {
  uint64_t next;  // payload offset of next free block, 0 = end
  uint64_t prev;
};

inline FreeLinks* links(Handle* h, uint64_t off) {
  return reinterpret_cast<FreeLinks*>(h->base + off);
}

uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the 16-byte id.
  uint64_t hv = 1469598103934665603ull;
  for (uint32_t i = 0; i < kIdSize; i++) {
    hv ^= id[i];
    hv *= 1099511628211ull;
  }
  return hv;
}

void lock(Handle* h) {
  int rc = pthread_mutex_lock(&h->header->mutex);
  if (rc == EOWNERDEAD) {
    // A process died holding the lock; state is still consistent enough for
    // our ops (we never leave multi-step invariants broken across a lock).
    pthread_mutex_consistent(&h->header->mutex);
  }
}

void unlock(Handle* h) { pthread_mutex_unlock(&h->header->mutex); }

// ---- allocator ------------------------------------------------------------

void freelist_remove(Handle* h, uint64_t off) {
  FreeLinks* l = links(h, off);
  if (l->prev) {
    links(h, l->prev)->next = l->next;
  } else {
    h->header->free_head = l->next;
  }
  if (l->next) links(h, l->next)->prev = l->prev;
}

void freelist_push(Handle* h, uint64_t off) {
  FreeLinks* l = links(h, off);
  l->next = h->header->free_head;
  l->prev = 0;
  if (l->next) links(h, l->next)->prev = off;
  h->header->free_head = off;
}

inline uint64_t heap_end(Handle* h) {
  return h->header->heap_offset + h->header->heap_size;
}

// Blocks tile the heap contiguously. For a block with payload `off` and
// payload size `size`, the following block's payload offset is
// off + size + kBlockOverhead; it exists iff that is < heap_end.
inline uint64_t next_payload_off(uint64_t off, uint64_t size) {
  return off + size + kBlockOverhead;
}

// Returns payload offset or 0 on failure.
uint64_t alloc_block(Handle* h, uint64_t want) {
  want = (want + kAlign - 1) / kAlign * kAlign;
  if (want < sizeof(FreeLinks)) want = kAlign;
  uint64_t off = h->header->free_head;
  while (off) {
    BlockHeader* b = block_at(h, off);
    if (b->size >= want) {
      freelist_remove(h, off);
      uint64_t remainder = b->size - want;
      if (remainder >= kBlockOverhead + kAlign) {
        // Split: carve the tail into a new free block.
        b->size = want;
        uint64_t next_off = next_payload_off(off, want);
        BlockHeader* nb = block_at(h, next_off);
        nb->size = remainder - kBlockOverhead;
        nb->prev_size = want;
        nb->free_flag = 1;
        freelist_push(h, next_off);
        // fix prev_size of the block after the new free block
        uint64_t after = next_payload_off(next_off, nb->size);
        if (after < heap_end(h)) {
          block_at(h, after)->prev_size = nb->size;
        }
      }
      b->free_flag = 0;
      return off;
    }
    off = links(h, off)->next;
  }
  return 0;
}

void free_block(Handle* h, uint64_t off) {
  BlockHeader* b = block_at(h, off);
  b->free_flag = 1;
  // Coalesce with next block.
  uint64_t next_off = next_payload_off(off, b->size);
  if (next_off < heap_end(h)) {
    BlockHeader* nb = block_at(h, next_off);
    if (nb->free_flag) {
      freelist_remove(h, next_off);
      b->size += nb->size + kBlockOverhead;
    }
  }
  // Coalesce with previous block.
  if (b->prev_size) {
    uint64_t prev_payload = off - kBlockOverhead - b->prev_size;
    BlockHeader* pb = block_at(h, prev_payload);
    if (pb->free_flag) {
      freelist_remove(h, prev_payload);
      pb->size += b->size + kBlockOverhead;
      b = pb;
      off = prev_payload;
    }
  }
  // Fix the next block's prev_size after coalescing.
  uint64_t after = next_payload_off(off, b->size);
  if (after < heap_end(h)) {
    block_at(h, after)->prev_size = b->size;
  }
  freelist_push(h, off);
}

// ---- object index ---------------------------------------------------------

Entry* find_entry(Handle* h, const uint8_t* id) {
  Entry* t = table(h);
  uint64_t cap = h->header->table_capacity;
  uint64_t idx = hash_id(id) & (cap - 1);
  for (uint64_t probe = 0; probe < cap; probe++) {
    Entry* e = &t[(idx + probe) & (cap - 1)];
    if (e->state == kFree) return nullptr;
    if (e->state != kTombstone && memcmp(e->id, id, kIdSize) == 0) return e;
  }
  return nullptr;
}

Entry* insert_entry(Handle* h, const uint8_t* id) {
  Entry* t = table(h);
  uint64_t cap = h->header->table_capacity;
  uint64_t idx = hash_id(id) & (cap - 1);
  Entry* first_tomb = nullptr;
  for (uint64_t probe = 0; probe < cap; probe++) {
    Entry* e = &t[(idx + probe) & (cap - 1)];
    if (e->state == kTombstone) {
      if (!first_tomb) first_tomb = e;
      continue;
    }
    if (e->state == kFree) return first_tomb ? first_tomb : e;
    if (memcmp(e->id, id, kIdSize) == 0) return nullptr;  // exists
  }
  return first_tomb;  // table full unless a tombstone is reusable
}

void erase_entry(Handle* h, Entry* e) {
  memset(e->id, 0, kIdSize);
  e->state = kTombstone;
  e->refcount = 0;
  e->offset = 0;
  e->data_size = 0;
}

// Evict LRU sealed refcount-0 objects until at least `need` payload bytes
// could plausibly be freed. Returns bytes freed.
uint64_t evict_lru(Handle* h, uint64_t need) {
  uint64_t freed = 0;
  Entry* t = table(h);
  uint64_t cap = h->header->table_capacity;
  while (freed < need) {
    Entry* victim = nullptr;
    for (uint64_t i = 0; i < cap; i++) {
      Entry* e = &t[i];
      if (e->state == kSealed && e->refcount == 0) {
        if (!victim || e->lru_tick < victim->lru_tick) victim = e;
      }
    }
    if (!victim) break;
    uint64_t sz = victim->data_size;
    free_block(h, victim->offset);
    h->header->used_bytes -= sz;
    h->header->num_objects--;
    h->header->num_evictions++;
    h->header->evicted_bytes += sz;
    erase_entry(h, victim);
    freed += sz + kBlockOverhead;
  }
  return freed;
}

}  // namespace

extern "C" {

// Error codes
#define RT_OK 0
#define RT_ERR_EXISTS -1
#define RT_ERR_FULL -2
#define RT_ERR_NOT_FOUND -3
#define RT_ERR_NOT_SEALED -4
#define RT_ERR_IN_USE -5
#define RT_ERR_STATE -6
#define RT_ERR_SYS -7

void* rt_store_open(const char* name, uint64_t size, int create) {
  int flags = create ? (O_CREAT | O_EXCL | O_RDWR) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0) return nullptr;
  if (create) {
    if (ftruncate(fd, static_cast<off_t>(size)) != 0) {
      close(fd);
      shm_unlink(name);
      return nullptr;
    }
  } else {
    struct stat st;
    if (fstat(fd, &st) != 0) {
      close(fd);
      return nullptr;
    }
    size = static_cast<uint64_t>(st.st_size);
  }
  void* base = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;

  Handle* h = new Handle();
  h->base = static_cast<uint8_t*>(base);
  h->size = size;
  h->header = reinterpret_cast<Header*>(base);
  snprintf(h->name, sizeof(h->name), "%s", name);

  if (create) {
    Header* hd = h->header;
    memset(hd, 0, sizeof(Header));
    hd->magic = kMagic;
    hd->total_size = size;
    // Size the index at ~1 entry per 16 KiB of heap, clamped to [1024, 2^20].
    uint64_t cap = 1024;
    while (cap < size / 16384 && cap < (1ull << 20)) cap <<= 1;
    hd->table_capacity = cap;
    hd->table_offset = (sizeof(Header) + kAlign - 1) / kAlign * kAlign;
    uint64_t table_bytes = cap * sizeof(Entry);
    hd->heap_offset =
        (hd->table_offset + table_bytes + kAlign - 1) / kAlign * kAlign + kBlockOverhead;
    hd->heap_size = size - hd->heap_offset;
    memset(h->base + hd->table_offset, 0, table_bytes);
    // One giant free block spanning the heap.
    BlockHeader* b = block_at(h, hd->heap_offset);
    b->size = hd->heap_size - kBlockOverhead;
    // Leave room so payload + overhead fits: heap_size includes our header.
    b->size = (hd->heap_size >= 2 * kBlockOverhead) ? hd->heap_size - kBlockOverhead : 0;
    b->prev_size = 0;
    b->free_flag = 1;
    hd->free_head = hd->heap_offset;
    FreeLinks* l = links(h, hd->heap_offset);
    l->next = 0;
    l->prev = 0;

    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&hd->mutex, &attr);
    pthread_mutexattr_destroy(&attr);
  } else if (h->header->magic != kMagic) {
    munmap(base, size);
    delete h;
    return nullptr;
  }
  return h;
}

void rt_store_close(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  munmap(h->base, h->size);
  delete h;
}

int rt_store_unlink(const char* name) { return shm_unlink(name); }

uint8_t* rt_store_base(void* handle) { return static_cast<Handle*>(handle)->base; }

int64_t rt_store_create_object(void* handle, const uint8_t* id, uint64_t size) {
  Handle* h = static_cast<Handle*>(handle);
  lock(h);
  if (find_entry(h, id)) {
    unlock(h);
    return RT_ERR_EXISTS;
  }
  Entry* e = insert_entry(h, id);
  if (!e) {
    unlock(h);
    return RT_ERR_FULL;
  }
  uint64_t off = alloc_block(h, size ? size : 1);
  if (!off) {
    evict_lru(h, size + kBlockOverhead);
    off = alloc_block(h, size ? size : 1);
  }
  if (!off) {
    unlock(h);
    return RT_ERR_FULL;
  }
  memcpy(e->id, id, kIdSize);
  e->state = kCreating;
  e->refcount = 1;  // creator holds a ref until seal+release
  e->offset = off;
  e->data_size = size;
  e->lru_tick = ++h->header->lru_clock;
  h->header->used_bytes += size;
  h->header->num_objects++;
  unlock(h);
  return static_cast<int64_t>(off);
}

int rt_store_seal(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  lock(h);
  Entry* e = find_entry(h, id);
  if (!e) {
    unlock(h);
    return RT_ERR_NOT_FOUND;
  }
  if (e->state != kCreating) {
    unlock(h);
    return RT_ERR_STATE;
  }
  e->state = kSealed;
  e->lru_tick = ++h->header->lru_clock;
  unlock(h);
  return RT_OK;
}

// Get: pins the object (refcount++). Returns payload offset, fills size.
int64_t rt_store_get(void* handle, const uint8_t* id, uint64_t* size_out) {
  Handle* h = static_cast<Handle*>(handle);
  lock(h);
  Entry* e = find_entry(h, id);
  if (!e) {
    unlock(h);
    return RT_ERR_NOT_FOUND;
  }
  if (e->state != kSealed) {
    unlock(h);
    return RT_ERR_NOT_SEALED;
  }
  e->refcount++;
  e->lru_tick = ++h->header->lru_clock;
  if (size_out) *size_out = e->data_size;
  int64_t off = static_cast<int64_t>(e->offset);
  unlock(h);
  return off;
}

int rt_store_release(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  lock(h);
  Entry* e = find_entry(h, id);
  if (!e) {
    unlock(h);
    return RT_ERR_NOT_FOUND;
  }
  if (e->refcount > 0) e->refcount--;
  unlock(h);
  return RT_OK;
}

int rt_store_contains(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  lock(h);
  Entry* e = find_entry(h, id);
  int r = (e && e->state == kSealed) ? 1 : 0;
  unlock(h);
  return r;
}

int rt_store_delete(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  lock(h);
  Entry* e = find_entry(h, id);
  if (!e) {
    unlock(h);
    return RT_ERR_NOT_FOUND;
  }
  if (e->refcount > 0) {
    unlock(h);
    return RT_ERR_IN_USE;
  }
  free_block(h, e->offset);
  h->header->used_bytes -= e->data_size;
  h->header->num_objects--;
  erase_entry(h, e);
  unlock(h);
  return RT_OK;
}

int rt_store_abort(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  lock(h);
  Entry* e = find_entry(h, id);
  if (!e || e->state != kCreating) {
    unlock(h);
    return RT_ERR_STATE;
  }
  free_block(h, e->offset);
  h->header->used_bytes -= e->data_size;
  h->header->num_objects--;
  erase_entry(h, e);
  unlock(h);
  return RT_OK;
}

uint64_t rt_store_evict(void* handle, uint64_t nbytes) {
  Handle* h = static_cast<Handle*>(handle);
  lock(h);
  uint64_t freed = evict_lru(h, nbytes);
  unlock(h);
  return freed;
}

// stats: [0]=used_bytes [1]=num_objects [2]=num_evictions [3]=heap_size
void rt_store_stats(void* handle, uint64_t* out) {
  Handle* h = static_cast<Handle*>(handle);
  lock(h);
  out[0] = h->header->used_bytes;
  out[1] = h->header->num_objects;
  out[2] = h->header->num_evictions;
  out[3] = h->header->heap_size;
  unlock(h);
}

}  // extern "C"
