// Multithreaded store stress for the TSAN build (SURVEY §4: the
// reference's race-detection story is TSAN over the C++ test suite; this
// is the matching harness for the shm allocator — one process, many
// threads hammering create/seal/get/release/delete so TSAN can observe
// every lock interleaving the allocator permits).
//
// Build + run: make -C ray_tpu/native tsan_test
//
// Exit 0 + "STORE THREAD TESTS OK" when all operations stay coherent.

#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* rt_store_open(const char* name, uint64_t size, int create);
void rt_store_close(void* handle);
int rt_store_unlink(const char* name);
uint8_t* rt_store_base(void* handle);
int64_t rt_store_create_object(void* handle, const uint8_t* id, uint64_t size);
int rt_store_seal(void* handle, const uint8_t* id);
int64_t rt_store_get(void* handle, const uint8_t* id, uint64_t* size_out);
int rt_store_release(void* handle, const uint8_t* id);
int rt_store_contains(void* handle, const uint8_t* id);
int rt_store_delete(void* handle, const uint8_t* id);
}

namespace {

constexpr int kThreads = 8;
constexpr int kOpsPerThread = 2000;
constexpr uint64_t kStoreBytes = 16ull * 1024 * 1024;

std::atomic<long> g_errors{0};

void make_id(uint8_t* id, int thread, int n) {
  std::memset(id, 0, 16);
  std::memcpy(id, &thread, sizeof(thread));
  std::memcpy(id + 4, &n, sizeof(n));
}

void worker(void* store, int tid) {
  for (int i = 0; i < kOpsPerThread; ++i) {
    uint8_t id[16];
    make_id(id, tid, i);
    uint64_t size = 64 + (i % 512);
    int64_t off = rt_store_create_object(store, id, size);
    if (off < 0) continue;  // store full / evicted: fine under pressure
    uint8_t* base = rt_store_base(store);
    std::memset(base + off, tid + 1, size);
    if (rt_store_seal(store, id) != 0) {
      g_errors.fetch_add(1);
      continue;
    }
    rt_store_release(store, id);

    // Read back an object of a NEIGHBORING thread (cross-thread get).
    uint8_t other[16];
    make_id(other, (tid + 1) % kThreads, i / 2);
    uint64_t got_size = 0;
    int64_t goff = rt_store_get(store, other, &got_size);
    if (goff >= 0) {
      // Payload must be uniformly the creator's fill byte.
      uint8_t expect = static_cast<uint8_t>(((tid + 1) % kThreads) + 1);
      const uint8_t* p = rt_store_base(store) + goff;
      for (uint64_t b = 0; b < got_size; b += 37) {
        if (p[b] != expect) {
          g_errors.fetch_add(1);
          break;
        }
      }
      rt_store_release(store, other);
    }

    // Periodically delete own older objects to churn the free list.
    if (i % 7 == 0 && i > 16) {
      uint8_t old[16];
      make_id(old, tid, i - 16);
      rt_store_delete(store, old);
    }
  }
}

}  // namespace

int main() {
  std::string name = "/rt_tsan_test_" + std::to_string(getpid());
  void* store = rt_store_open(name.c_str(), kStoreBytes, 1);
  if (store == nullptr) {
    std::fprintf(stderr, "FAIL: store open\n");
    return 1;
  }
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back(worker, store, t);
  }
  for (auto& t : threads) t.join();
  rt_store_close(store);
  rt_store_unlink(name.c_str());
  if (g_errors.load() != 0) {
    std::fprintf(stderr, "FAIL: %ld coherence errors\n", g_errors.load());
    return 1;
  }
  std::printf("STORE THREAD TESTS OK\n");
  return 0;
}
