"""User-facing exceptions.

Mirrors the taxonomy in the reference's python/ray/exceptions.py: task
errors wrap the remote traceback, actor errors/unavailability, object loss,
and cancellation.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all ray_tpu errors."""


class TaskError(RayTpuError):
    """A task raised an exception remotely (reference: RayTaskError).

    Raised on `get()` of the task's return ref; carries the remote traceback.
    """

    def __init__(self, cause_cls_name: str, traceback_str: str, cause=None):
        self.cause_cls_name = cause_cls_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"Remote task failed with {cause_cls_name}:\n{traceback_str}")


class ActorError(RayTpuError):
    """Base class for actor-related failures (reference: RayActorError)."""


class ActorDiedError(ActorError):
    """The actor process is dead; calls on its handle will fail."""


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class CollectiveTimeoutError(RayTpuError, TimeoutError):
    """A collective op missed its deadline: a peer is dead or wedged.

    Raised by the eager DCN ring instead of hanging in ``recv`` forever,
    so one preempted rank converts into a restartable failure for the
    whole gang. Carries enough context to identify the bad link."""

    def __init__(self, message: str, *, group_name: str = "",
                 rank=None, peer_rank=None):
        self.group_name = group_name
        self.rank = rank
        self.peer_rank = peer_rank
        super().__init__(message)


class ServeOverloadedError(RayTpuError):
    """The serving tier shed this request instead of queueing it.

    Raised when a bounded admission queue (replica or engine) is full,
    or a draining/sick replica refuses new work and no healthy replica
    remains. Always retryable: the request was REJECTED before consuming
    a slot, so a later retry is safe regardless of deployment semantics.
    ``retry_after_s`` is the server's backlog-drain estimate — the proxy
    surfaces it as HTTP 429 + ``Retry-After``."""

    def __init__(self, message: str, *, app: str = "", tenant: str = "",
                 reason: str = "queue_full", retry_after_s: float = 1.0):
        self.app = app
        self.tenant = tenant
        self.reason = reason
        self.retry_after_s = retry_after_s
        super().__init__(message)


class PromptTooLongError(RayTpuError, ValueError):
    """The prompt cannot fit the serving engine's KV capacity.

    Raised by ``ContinuousBatchingEngine.submit`` BEFORE queueing: the
    bound is ``max_len - 2`` positions and, under the paged KV cache,
    the page pool's total capacity — whichever is smaller. Not
    retryable against the same engine (the limit is structural); the
    proxy maps it to HTTP 413. Subclasses ValueError so callers of the
    historical untyped rejection keep working."""

    def __init__(self, message: str, *, prompt_len: int = 0,
                 max_prompt_len: int = 0):
        self.prompt_len = prompt_len
        self.max_prompt_len = max_prompt_len
        super().__init__(message)


class RequestCancelledError(RayTpuError):
    """A serve request was cancelled instead of executed to completion.

    ``reason`` is one of ``"deadline"`` (the propagated absolute deadline
    expired — every hop checks it and expired work is evicted rather than
    run), ``"client"`` (the caller closed the stream / cancelled), or
    ``"shutdown"`` (the engine/replica is going away). Deadline
    cancellations are NOT retryable — the budget is gone by definition."""

    def __init__(self, message: str, *, reason: str = "deadline",
                 app: str = "", rid: str = ""):
        self.reason = reason
        self.app = app
        self.rid = rid
        super().__init__(message)


class ReplicaDrainingError(RayTpuError):
    """The chosen replica is draining (scale-down / migration) and no
    longer admits requests. Retryable by construction: the handle
    redispatches to a live replica exactly as for a dead one."""

    def __init__(self, message: str, *, app: str = ""):
        self.app = app
        super().__init__(message)


class ObjectLostError(RayTpuError):
    """All copies of the object are gone and it cannot be reconstructed."""


class ObjectStoreFullError(RayTpuError):
    """The shared-memory store could not allocate after eviction."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled before or during execution."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get()` exceeded its timeout."""


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly."""


class RuntimeEnvSetupError(RayTpuError):
    """Preparing the runtime environment for a task/actor failed."""


class PlacementGroupSchedulingError(RayTpuError):
    """The placement group could not be scheduled (infeasible bundles)."""
