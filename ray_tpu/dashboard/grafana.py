"""Grafana dashboard generation from the metrics surface.

Analog of the reference's grafana_dashboard_factory
(dashboard/modules/metrics/grafana_dashboard_factory.py): emit a Grafana
dashboard JSON whose panels query the Prometheus metrics this runtime
exposes at the dashboard's /metrics endpoint — the built-in system series
(rt_node_resource_*, rt_actors) plus one panel per registered user
metric (Counter -> rate graph, Gauge -> graph, Histogram -> p50/p95/p99
quantile graph over _bucket series).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional


def _panel(panel_id: int, title: str, targets: List[Dict], y: int,
           unit: str = "short") -> Dict:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "gridPos": {"h": 8, "w": 12, "x": (panel_id % 2) * 12, "y": y},
        "fieldConfig": {"defaults": {"unit": unit}, "overrides": []},
        "targets": [
            {"expr": t["expr"], "legendFormat": t.get("legend", ""),
             "refId": chr(ord("A") + i)}
            for i, t in enumerate(targets)
        ],
    }


_SYSTEM_PANELS = [
    ("Node resources available", [
        {"expr": "rt_node_resource_available",
         "legend": "{{node}} {{resource}}"},
    ]),
    ("Node resources total", [
        {"expr": "rt_node_resource_total", "legend": "{{node}} {{resource}}"},
    ]),
    ("Actors by state", [
        {"expr": "rt_actors", "legend": "{{state}}"},
    ]),
]

# Flight-recorder panel set: the curated training/serving/memory views
# the StepProfiler + memory accountant + serving engine publish. Emitted
# ahead of the generic per-registered-metric panels so a fresh cluster's
# dashboard has the observability story laid out even before any process
# registers the series locally. (name, targets, unit) triples.
_FLIGHT_RECORDER_PANELS = [
    ("Train step wall time p50/p95 by rank", [
        {"expr": "histogram_quantile(0.5, rate("
                 "train_step_wall_seconds_bucket[1m]))",
         "legend": "p50 rank {{rank}}"},
        {"expr": "histogram_quantile(0.95, rate("
                 "train_step_wall_seconds_bucket[1m]))",
         "legend": "p95 rank {{rank}}"},
    ], "s"),
    ("Train step phase breakdown", [
        {"expr": "rate(train_step_phase_seconds_total[1m])",
         "legend": "rank {{rank}} {{phase}}"},
    ], "s"),
    ("Cross-rank step skew (straggler gap)", [
        {"expr": "histogram_quantile(0.95, rate("
                 "train_step_skew_seconds_bucket[1m]))",
         "legend": "p95 skew"},
        {"expr": "train_straggler_rank", "legend": "straggler rank"},
    ], "s"),
    ("Elastic gang size vs reclaimed chips", [
        {"expr": "train_gang_size", "legend": "gang world size"},
        {"expr": "sum(rate(preempt_total[5m])) by (reason)",
         "legend": "preemptions/s {{reason}}"},
        {"expr": "rate(train_resize_total[5m])",
         "legend": "resizes/s {{direction}}"},
    ], "short"),
    ("Training throughput / MFU", [
        {"expr": "train_tokens_per_s", "legend": "rank {{rank}} tok/s"},
        {"expr": "train_step_mfu", "legend": "rank {{rank}} MFU"},
    ], "short"),
    ("Step compiles (retraces)", [
        {"expr": "rate(train_step_compiles_total[5m])",
         "legend": "rank {{rank}}"},
    ], "short"),
    ("Device HBM (live arrays vs allocator)", [
        {"expr": "device_hbm_live_bytes",
         "legend": "{{node}} {{device}} live"},
        {"expr": "device_hbm_in_use_bytes",
         "legend": "{{node}} {{device}} in use"},
        {"expr": "device_hbm_limit_bytes",
         "legend": "{{node}} {{device}} limit"},
    ], "bytes"),
    ("Object store usage by node", [
        {"expr": "rt_raylet_store_used_bytes", "legend": "{{node}}"},
    ], "bytes"),
    ("Data feed stalls", [
        {"expr": "rate(data_feed_stall_seconds_count[1m])",
         "legend": "stalls/s"},
        {"expr": "rate(data_feed_stall_seconds_sum[1m])",
         "legend": "stall seconds/s"},
    ], "short"),
    ("Serving TTFT p50/p95", [
        {"expr": "histogram_quantile(0.5, rate("
                 "serve_llm_ttft_seconds_bucket[1m]))", "legend": "p50"},
        {"expr": "histogram_quantile(0.95, rate("
                 "serve_llm_ttft_seconds_bucket[1m]))", "legend": "p95"},
    ], "s"),
    ("Serving TPOT p50/p95", [
        {"expr": "histogram_quantile(0.5, rate("
                 "serve_llm_tpot_seconds_bucket[1m]))", "legend": "p50"},
        {"expr": "histogram_quantile(0.95, rate("
                 "serve_llm_tpot_seconds_bucket[1m]))", "legend": "p95"},
    ], "s"),
    ("Serving batch occupancy", [
        {"expr": "serve_llm_batch_occupancy", "legend": "occupancy"},
    ], "percentunit"),
    # -- request observatory --------------------------------------------
    ("Serve request e2e p50/p99", [
        {"expr": "histogram_quantile(0.5, rate("
                 "serve_request_e2e_seconds_bucket[1m]))",
         "legend": "{{app}} p50"},
        {"expr": "histogram_quantile(0.99, rate("
                 "serve_request_e2e_seconds_bucket[1m]))",
         "legend": "{{app}} p99"},
    ], "s"),
    ("Serve request phase breakdown", [
        {"expr": "rate(serve_request_phase_seconds_total[1m])",
         "legend": "{{app}} {{phase}}"},
    ], "s"),
    ("Serve per-tenant request rate", [
        {"expr": "rate(serve_requests_total[1m])",
         "legend": "{{app}} {{tenant}}"},
    ], "short"),
    ("Serve SLO burn rate by tenant", [
        {"expr": "serve_slo_burn_rate",
         "legend": "{{app}} {{tenant}} {{slo}}"},
    ], "short"),
    ("Serve head-of-line blocking", [
        {"expr": "rate(serve_hol_blocked_seconds_total[1m])",
         "legend": "blocked slot-seconds/s"},
    ], "s"),
    ("Serve engine admission queue", [
        {"expr": "serve_llm_waiting_requests", "legend": "waiting"},
        {"expr": "histogram_quantile(0.99, rate("
                 "serve_llm_admission_wait_seconds_bucket[1m]))",
         "legend": "admission wait p99"},
    ], "short"),
    # -- serve survival plane -------------------------------------------
    ("Serve shed rate (admission control)", [
        {"expr": "rate(serve_requests_shed_total[1m])",
         "legend": "{{app}} {{tenant}} {{reason}}"},
    ], "short"),
    ("Serve circuit-breaker state (0 closed / 2 open)", [
        {"expr": "serve_circuit_breaker_state",
         "legend": "{{app}} {{replica}}"},
    ], "short"),
    ("Serve drain duration p50/p99", [
        {"expr": "histogram_quantile(0.5, rate("
                 "serve_drain_seconds_bucket[5m]))",
         "legend": "{{app}} p50"},
        {"expr": "histogram_quantile(0.99, rate("
                 "serve_drain_seconds_bucket[5m]))",
         "legend": "{{app}} p99"},
    ], "s"),
    ("Serve deadline expirations by hop", [
        {"expr": "rate(serve_deadline_expired_total[1m])",
         "legend": "{{app}} {{hop}}"},
    ], "short"),
    ("Serve HTTP responses by code", [
        {"expr": "rate(serve_http_responses_total[1m])",
         "legend": "{{app}} {{code}}"},
    ], "short"),
    # -- paged KV cache ---------------------------------------------------
    ("Serve KV page-pool occupancy", [
        {"expr": "serve_kv_pages_in_use", "legend": "pages in use"},
    ], "short"),
    ("Serve prefix-cache hit ratio", [
        {"expr": "rate(serve_prefix_cache_hits_total[1m]) / "
                 "(rate(serve_prefix_cache_hits_total[1m]) + "
                 "rate(serve_prefix_cache_misses_total[1m]))",
         "legend": "hit ratio"},
        {"expr": "rate(serve_prefill_tokens_skipped_total[1m])",
         "legend": "prefill tokens skipped/s"},
    ], "short"),
    ("Serve autoscaler target vs actual replicas", [
        {"expr": "serve_autoscaler_target_replicas",
         "legend": "{{app}} target"},
        {"expr": "serve_autoscaler_actual_replicas",
         "legend": "{{app}} actual"},
    ], "short"),
    # -- control-plane profiler -----------------------------------------
    ("GCS RPC rate by method", [
        {"expr": "rate(gcs_rpc_calls_total[1m])", "legend": "{{method}}"},
    ], "short"),
    ("GCS RPC handler latency p50/p99", [
        {"expr": "histogram_quantile(0.5, rate("
                 "gcs_rpc_server_seconds_bucket[1m]))", "legend": "p50"},
        {"expr": "histogram_quantile(0.99, rate("
                 "gcs_rpc_server_seconds_bucket[1m]))", "legend": "p99"},
    ], "s"),
    ("GCS RPC client-observed latency p50/p99", [
        {"expr": "histogram_quantile(0.5, rate("
                 "gcs_rpc_client_seconds_bucket[1m]))", "legend": "p50"},
        {"expr": "histogram_quantile(0.99, rate("
                 "gcs_rpc_client_seconds_bucket[1m]))", "legend": "p99"},
    ], "s"),
    ("Scheduler queue depth by node", [
        {"expr": "rt_raylet_tasks_queued", "legend": "{{node}}"},
    ], "short"),
    ("Scheduler dispatch scans / passes", [
        {"expr": "rate(rt_raylet_dispatch_scans_total[1m])",
         "legend": "{{node}} scans/s"},
        {"expr": "rate(rt_raylet_dispatch_passes_total[1m])",
         "legend": "{{node}} passes/s"},
    ], "short"),
    ("Scheduler last dispatch batch / scan length", [
        {"expr": "rt_raylet_dispatch_batch_last",
         "legend": "{{node}} batch"},
        {"expr": "rt_raylet_dispatch_scan_last",
         "legend": "{{node}} scan"},
    ], "short"),
    # -- topology-native collectives -------------------------------------
    ("Collective wire bytes by tier/algo", [
        {"expr": "rate(collective_bytes_total[1m])",
         "legend": "{{tier}} {{algo}} {{dtype}}"},
    ], "Bps"),
    ("Collective op latency p50/p95", [
        {"expr": "histogram_quantile(0.5, rate("
                 "collective_op_seconds_bucket[1m]))", "legend": "p50"},
        {"expr": "histogram_quantile(0.95, rate("
                 "collective_op_seconds_bucket[1m]))", "legend": "p95"},
    ], "s"),
    # -- multi-tenancy / preemption ---------------------------------------
    ("Preemptions by tenant/reason", [
        {"expr": "rate(preempt_total[5m])",
         "legend": "{{tenant}} {{reason}}"},
        {"expr": "preempt_active", "legend": "active drains"},
    ], "short"),
    ("Preemption grace (drain-to-release) p50/p99", [
        {"expr": "histogram_quantile(0.5, rate("
                 "preempt_grace_seconds_bucket[5m]))", "legend": "p50"},
        {"expr": "histogram_quantile(0.99, rate("
                 "preempt_grace_seconds_bucket[5m]))", "legend": "p99"},
    ], "s"),
    ("Chip occupancy by tenant", [
        {"expr": "tenant_chip_occupancy", "legend": "{{tenant}}"},
    ], "short"),
    # -- loadgen witness (macro harness) -----------------------------------
    ("Loadgen offered vs achieved QPS", [
        {"expr": "loadgen_offered_qps", "legend": "offered"},
        {"expr": "rate(loadgen_requests_total[1m])",
         "legend": "{{tenant}} {{outcome}}"},
    ], "short"),
    ("Client-observed latency p50/p99 (witness)", [
        {"expr": "histogram_quantile(0.5, rate("
                 "loadgen_client_e2e_seconds_bucket[1m]))", "legend": "p50"},
        {"expr": "histogram_quantile(0.99, rate("
                 "loadgen_client_e2e_seconds_bucket[1m]))", "legend": "p99"},
        {"expr": "histogram_quantile(0.99, rate("
                 "loadgen_client_ttfb_seconds_bucket[1m]))",
         "legend": "ttfb p99"},
    ], "s"),
    ("Unattributed client<->server gap", [
        {"expr": "loadgen_gap_fraction", "legend": "gap fraction {{q}}"},
        {"expr": "loadgen_unattributed_gap_seconds",
         "legend": "gap seconds {{q}}"},
    ], "short"),
    # -- cluster black box (event journal) ---------------------------------
    ("Journal events by kind", [
        {"expr": "rate(journal_events_total[1m])", "legend": "{{kind}}"},
    ], "short"),
    ("Journal ring overwrites (events lost to any future dump)", [
        {"expr": "rate(journal_dropped_total[1m])", "legend": "dropped/s"},
    ], "short"),
]


def generate_dashboard(
    user_metrics: Optional[List[Dict]] = None,
    title: str = "ray_tpu cluster",
) -> Dict:
    """Build the dashboard dict.

    user_metrics: list of Metric.info dicts ({"name", "description",
    "type"}); defaults to every metric registered in this process
    (util/metrics._registry).
    """
    if user_metrics is None:
        from ray_tpu.util import metrics as m

        with m._registry_lock:
            user_metrics = [
                {**metric.info, "type": type(metric).__name__.lower()}
                for metric in m._registry
            ]

    panels: List[Dict] = []
    pid = 1
    y = 0
    for name, targets in _SYSTEM_PANELS:
        panels.append(_panel(pid, name, targets, y))
        pid += 1
        y += 8 * (pid % 2 == 1)

    covered = set()
    for name, targets, unit in _FLIGHT_RECORDER_PANELS:
        panels.append(_panel(pid, name, targets, y, unit=unit))
        pid += 1
        y += 8 * (pid % 2 == 1)
        for t in targets:
            # Track the base series each curated panel queries so the
            # generic per-metric pass below doesn't duplicate it.
            expr = t["expr"]
            for suffix in ("_bucket", "_sum", "_count"):
                expr = expr.replace(suffix, "")
            for token in expr.replace("(", " ").replace(")", " ").replace(
                    "[1m]", " ").replace("[5m]", " ").split():
                if token.startswith(("train_", "serve_", "device_", "data_",
                                     "rt_raylet_", "gcs_rpc_",
                                     "collective_", "preempt_",
                                     "tenant_", "loadgen_", "journal_")):
                    covered.add(token)

    for info in user_metrics:
        name, mtype = info["name"], info["type"]
        if name in covered:
            continue
        if mtype == "counter":
            targets = [{"expr": f"rate({name}[1m])", "legend": name}]
        elif mtype == "gauge":
            targets = [{"expr": name, "legend": name}]
        else:  # histogram
            targets = [
                {"expr": f"histogram_quantile({q}, "
                         f"rate({name}_bucket[1m]))",
                 "legend": f"p{int(q * 100)}"}
                for q in (0.5, 0.95, 0.99)
            ]
        panels.append(
            _panel(pid, info.get("description") or name, targets, y)
        )
        pid += 1
        y += 8 * (pid % 2 == 1)

    return {
        "title": title,
        "uid": "rt-tpu-cluster",
        "schemaVersion": 39,
        "refresh": "10s",
        "time": {"from": "now-1h", "to": "now"},
        "templating": {
            "list": [{
                "name": "datasource",
                "type": "datasource",
                "query": "prometheus",
            }]
        },
        "panels": panels,
    }


def write_dashboard(path: str, **kwargs) -> str:
    """Write the dashboard JSON to `path`; returns the path."""
    with open(path, "w") as f:
        json.dump(generate_dashboard(**kwargs), f, indent=2)
    return path
