"""Grafana dashboard generation from the metrics surface.

Analog of the reference's grafana_dashboard_factory
(dashboard/modules/metrics/grafana_dashboard_factory.py): emit a Grafana
dashboard JSON whose panels query the Prometheus metrics this runtime
exposes at the dashboard's /metrics endpoint — the built-in system series
(rt_node_resource_*, rt_actors) plus one panel per registered user
metric (Counter -> rate graph, Gauge -> graph, Histogram -> p50/p95/p99
quantile graph over _bucket series).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional


def _panel(panel_id: int, title: str, targets: List[Dict], y: int,
           unit: str = "short") -> Dict:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "gridPos": {"h": 8, "w": 12, "x": (panel_id % 2) * 12, "y": y},
        "fieldConfig": {"defaults": {"unit": unit}, "overrides": []},
        "targets": [
            {"expr": t["expr"], "legendFormat": t.get("legend", ""),
             "refId": chr(ord("A") + i)}
            for i, t in enumerate(targets)
        ],
    }


_SYSTEM_PANELS = [
    ("Node resources available", [
        {"expr": "rt_node_resource_available",
         "legend": "{{node}} {{resource}}"},
    ]),
    ("Node resources total", [
        {"expr": "rt_node_resource_total", "legend": "{{node}} {{resource}}"},
    ]),
    ("Actors by state", [
        {"expr": "rt_actors", "legend": "{{state}}"},
    ]),
]


def generate_dashboard(
    user_metrics: Optional[List[Dict]] = None,
    title: str = "ray_tpu cluster",
) -> Dict:
    """Build the dashboard dict.

    user_metrics: list of Metric.info dicts ({"name", "description",
    "type"}); defaults to every metric registered in this process
    (util/metrics._registry).
    """
    if user_metrics is None:
        from ray_tpu.util import metrics as m

        with m._registry_lock:
            user_metrics = [
                {**metric.info, "type": type(metric).__name__.lower()}
                for metric in m._registry
            ]

    panels: List[Dict] = []
    pid = 1
    y = 0
    for name, targets in _SYSTEM_PANELS:
        panels.append(_panel(pid, name, targets, y))
        pid += 1
        y += 8 * (pid % 2 == 1)

    for info in user_metrics:
        name, mtype = info["name"], info["type"]
        if mtype == "counter":
            targets = [{"expr": f"rate({name}[1m])", "legend": name}]
        elif mtype == "gauge":
            targets = [{"expr": name, "legend": name}]
        else:  # histogram
            targets = [
                {"expr": f"histogram_quantile({q}, "
                         f"rate({name}_bucket[1m]))",
                 "legend": f"p{int(q * 100)}"}
                for q in (0.5, 0.95, 0.99)
            ]
        panels.append(
            _panel(pid, info.get("description") or name, targets, y)
        )
        pid += 1
        y += 8 * (pid % 2 == 1)

    return {
        "title": title,
        "uid": "rt-tpu-cluster",
        "schemaVersion": 39,
        "refresh": "10s",
        "time": {"from": "now-1h", "to": "now"},
        "templating": {
            "list": [{
                "name": "datasource",
                "type": "datasource",
                "query": "prometheus",
            }]
        },
        "panels": panels,
    }


def write_dashboard(path: str, **kwargs) -> str:
    """Write the dashboard JSON to `path`; returns the path."""
    with open(path, "w") as f:
        json.dump(generate_dashboard(**kwargs), f, indent=2)
    return path
