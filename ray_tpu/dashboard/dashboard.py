"""Cluster dashboard: HTTP JSON API + Prometheus metrics + minimal UI.

Analog of the reference dashboard head process (dashboard/dashboard.py,
head.py with its pluggable modules: node, actor, job, state, metrics,
healthz — dashboard/modules/) collapsed into one aiohttp app fed directly
from the GCS. The reference's React frontend is replaced by a single
self-contained HTML page; the REST surface mirrors the module routes the
CLI/SDK consume (jobs REST = dashboard/modules/job/job_head.py).

Run standalone:  python -m ray_tpu.dashboard --address HOST:PORT [--port 8265]
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Optional

from aiohttp import web

from ray_tpu._private.protocol import Connection, connect

_HTML = """<!DOCTYPE html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 2rem; color: #222; }
 h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.6rem; }
 table { border-collapse: collapse; margin-top: .4rem; min-width: 40rem; }
 th, td { border: 1px solid #ccc; padding: .25rem .6rem; font-size: .85rem;
          text-align: left; }
 th { background: #f3f3f3; }
 code { background: #f6f6f6; padding: 0 .2rem; }
</style></head>
<body>
<h1>ray_tpu cluster</h1>
<div id="root">loading…</div>
<script>
const fmt = (o) => typeof o === "object" ? JSON.stringify(o) : o;
async function refresh() {
  const [status, nodes, actors, jobs] = await Promise.all([
    fetch("api/cluster_status").then(r => r.json()),
    fetch("api/nodes").then(r => r.json()),
    fetch("api/actors").then(r => r.json()),
    fetch("api/jobs").then(r => r.json()),
  ]);
  const rows = (items, cols) =>
    "<table><tr>" + cols.map(c => `<th>${c}</th>`).join("") + "</tr>" +
    items.map(it => "<tr>" + cols.map(c => `<td>${fmt(it[c] ?? "")}</td>`)
      .join("") + "</tr>").join("") + "</table>";
  document.getElementById("root").innerHTML =
    `<p>${status.alive_nodes}/${status.total_nodes} nodes alive · ` +
    Object.entries(status.resources_total).map(([k, v]) =>
      `${k}: ${status.resources_available[k] ?? 0}/${v}`).join(" · ") + "</p>" +
    "<h2>Nodes</h2>" + rows(nodes, ["node_id", "state", "address",
                                    "resources_total", "resources_available"]) +
    "<h2>Actors</h2>" + rows(actors, ["actor_id", "class_name", "state",
                                      "name", "node_id"]) +
    "<h2>Jobs</h2>" + rows(jobs, ["submission_id", "state", "entrypoint"]);
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""


def _hex(b):
    return b.hex() if isinstance(b, (bytes, bytearray)) else b


def _prom_escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_labels(tags) -> str:
    if not tags:
        return ""
    inner = ",".join(f'{k}="{_prom_escape(v)}"' for k, v in tags)
    return "{" + inner + "}"


class Dashboard:
    def __init__(self, gcs_address: str, host: str = "127.0.0.1", port: int = 8265):
        self.gcs_address = gcs_address
        self.host, self.port = host, port
        self.gcs: Optional[Connection] = None
        self.app = web.Application()
        self.app.add_routes(
            [
                web.get("/", self.page),
                web.get("/healthz", self.healthz),
                web.get("/metrics", self.metrics),
                web.get("/api/cluster_status", self.cluster_status),
                web.get("/api/nodes", self.nodes),
                web.get("/api/actors", self.actors),
                web.get("/api/tasks", self.tasks),
                web.get("/api/objects", self.objects),
                web.get("/api/placement_groups", self.placement_groups),
                web.get("/api/jobs", self.jobs),
                web.post("/api/jobs", self.submit_job),
                web.get("/api/jobs/{submission_id}", self.job_info),
                web.get("/api/jobs/{submission_id}/logs", self.job_logs),
                web.post("/api/jobs/{submission_id}/stop", self.stop_job),
            ]
        )
        self._runner: Optional[web.AppRunner] = None

    async def start(self) -> int:
        host, port = self.gcs_address.rsplit(":", 1)
        self.gcs = await connect(host, int(port))
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self):
        if self._runner:
            await self._runner.cleanup()
        if self.gcs:
            await self.gcs.close()

    # -- pages -----------------------------------------------------------
    async def page(self, request):
        return web.Response(text=_HTML, content_type="text/html")

    async def healthz(self, request):
        try:
            await self.gcs.call("ping", {}, timeout=2)
        except Exception:
            return web.Response(status=503, text="gcs unreachable")
        return web.Response(text="ok")

    # -- json api --------------------------------------------------------
    def _json(self, data):
        return web.Response(
            text=json.dumps(data, default=_hex), content_type="application/json"
        )

    async def cluster_status(self, request):
        nodes = (await self.gcs.call("get_nodes", {}))["nodes"]
        alive = [n for n in nodes if n["state"] == "ALIVE"]
        totals, avail = {}, {}
        for n in alive:
            for k, v in n.get("resources_total", {}).items():
                totals[k] = totals.get(k, 0) + v
            for k, v in n.get("resources_available", {}).items():
                avail[k] = avail.get(k, 0) + v
        return self._json(
            {
                "alive_nodes": len(alive),
                "total_nodes": len(nodes),
                "resources_total": totals,
                "resources_available": avail,
                "timestamp": time.time(),
            }
        )

    async def nodes(self, request):
        nodes = (await self.gcs.call("get_nodes", {}))["nodes"]
        return self._json(
            [
                {
                    "node_id": _hex(n["node_id"]),
                    "state": n["state"],
                    "address": f"{n['address']}:{n['port']}",
                    "is_head": n.get("is_head", False),
                    "resources_total": n.get("resources_total", {}),
                    "resources_available": n.get("resources_available", {}),
                }
                for n in nodes
            ]
        )

    async def actors(self, request):
        actors = (await self.gcs.call("list_actors", {}))["actors"]
        return self._json(
            [
                {
                    "actor_id": _hex(a["actor_id"]),
                    "class_name": a.get("class_name", ""),
                    "state": a.get("state", ""),
                    "name": a.get("name") or "",
                    "node_id": _hex(a.get("node_id") or b""),
                }
                for a in actors
            ]
        )

    async def tasks(self, request):
        events = (await self.gcs.call("list_task_events", {"limit": 100_000}))[
            "events"
        ]
        tasks = {}
        for ev in events:
            t = tasks.setdefault(
                ev["task_id"],
                {"task_id": _hex(ev["task_id"]), "name": ev.get("name", ""),
                 "type": ev.get("type")},
            )
            t["state"] = ev["state"]
        return self._json(list(tasks.values()))

    async def objects(self, request):
        objs = (await self.gcs.call("list_objects", {}))["objects"]
        return self._json(
            [
                {"object_id": _hex(o["object_id"]), "size": o["size"],
                 "locations": [_hex(n) for n in o["nodes"]]}
                for o in objs
            ]
        )

    async def placement_groups(self, request):
        pgs = (await self.gcs.call("list_placement_groups", {}))["pgs"]
        return self._json(
            [
                {"pg_id": _hex(p["pg_id"]), "state": p["state"],
                 "strategy": p["strategy"], "bundles": p["bundles"]}
                for p in pgs
            ]
        )

    async def jobs(self, request):
        jobs = (await self.gcs.call("list_jobs", {}))["jobs"]
        return self._json(
            [{**j, "job_id": _hex(j.get("job_id", b"")),
              "node_id": _hex(j.get("node_id") or b"")} for j in jobs]
        )

    async def submit_job(self, request):
        body = await request.json()
        r = await self.gcs.call(
            "submit_job",
            {
                "entrypoint": body["entrypoint"],
                "submission_id": body.get("submission_id"),
                "runtime_env": body.get("runtime_env"),
                "metadata": body.get("metadata"),
            },
        )
        status = 200 if r.get("ok") else 400
        return web.Response(
            status=status, text=json.dumps(r), content_type="application/json"
        )

    async def job_info(self, request):
        sid = request.match_info["submission_id"]
        r = await self.gcs.call("get_job", {"submission_id": sid})
        if r["job"] is None:
            return web.Response(status=404, text="no such job")
        return self._json(
            {**r["job"], "job_id": _hex(r["job"].get("job_id", b"")),
             "node_id": _hex(r["job"].get("node_id") or b"")}
        )

    async def job_logs(self, request):
        sid = request.match_info["submission_id"]
        r = await self.gcs.call("job_logs", {"submission_id": sid})
        if r["logs"] is None:
            return web.Response(status=404, text="no such job")
        return web.Response(text=r["logs"])

    async def stop_job(self, request):
        sid = request.match_info["submission_id"]
        r = await self.gcs.call("stop_job", {"submission_id": sid})
        return self._json(r)

    # -- prometheus ------------------------------------------------------
    async def metrics(self, request):
        lines = []
        # System metrics derived from GCS tables (stats/metric_defs.h
        # analog: node resources, actor/task/job states).
        nodes = (await self.gcs.call("get_nodes", {}))["nodes"]
        lines.append("# TYPE rt_node_resource_total gauge")
        lines.append("# TYPE rt_node_resource_available gauge")
        for n in nodes:
            if n["state"] != "ALIVE":
                continue
            nid = _hex(n["node_id"])[:12]
            for k, v in n.get("resources_total", {}).items():
                lines.append(
                    f'rt_node_resource_total{{node="{nid}",resource="{_prom_escape(k)}"}} {v}'
                )
            for k, v in n.get("resources_available", {}).items():
                lines.append(
                    f'rt_node_resource_available{{node="{nid}",resource="{_prom_escape(k)}"}} {v}'
                )
        actors = (await self.gcs.call("list_actors", {}))["actors"]
        states: dict = {}
        for a in actors:
            states[a.get("state", "?")] = states.get(a.get("state", "?"), 0) + 1
        lines.append("# TYPE rt_actors gauge")
        for s, c in states.items():
            lines.append(f'rt_actors{{state="{s}"}} {c}')

        # GCS-internal runtime metrics (per-component stats).
        stats = await self.gcs.call("gcs_stats", {})
        lines.append("# TYPE rt_gcs_rpc_total counter")
        for method, count in sorted(stats["rpc_counts"].items()):
            lines.append(
                f'rt_gcs_rpc_total{{method="{_prom_escape(method)}"}} {count}'
            )
        for gauge in ("kv_entries", "task_events", "subscriber_conns",
                      "object_dir_entries", "placement_groups"):
            lines.append(f"# TYPE rt_gcs_{gauge} gauge")
            lines.append(f"rt_gcs_{gauge} {stats[gauge]}")

        # User metrics (util/metrics.py) from the GCS aggregate.
        snapshot = (await self.gcs.call("metrics_snapshot", {}))["metrics"]
        for m in snapshot:
            name = m["name"]
            if m["description"]:
                lines.append(f"# HELP {name} {_prom_escape(m['description'])}")
            ptype = {"counter": "counter", "gauge": "gauge",
                     "histogram": "histogram"}[m["type"]]
            lines.append(f"# TYPE {name} {ptype}")
            for tags, val in m["series"]:
                if m["type"] in ("counter", "gauge"):
                    lines.append(f"{name}{_prom_labels(tags)} {val}")
                else:
                    bounds = m["boundaries"]
                    cum = 0
                    for i, b in enumerate(bounds):
                        cum += val["buckets"][i]
                        lab = list(tags) + [["le", str(b)]]
                        lines.append(f"{name}_bucket{_prom_labels(lab)} {cum}")
                    cum += val["buckets"][-1]
                    lab = list(tags) + [["le", "+Inf"]]
                    lines.append(f"{name}_bucket{_prom_labels(lab)} {cum}")
                    lines.append(f"{name}_sum{_prom_labels(tags)} {val['sum']}")
                    lines.append(f"{name}_count{_prom_labels(tags)} {val['count']}")
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")


def run_dashboard(gcs_address: str, host: str = "127.0.0.1", port: int = 8265):
    """Blocking entry point (standalone dashboard process)."""

    async def main():
        dash = Dashboard(gcs_address, host, port)
        actual = await dash.start()
        print(f"DASHBOARD_PORT={actual}", flush=True)
        await asyncio.Event().wait()

    asyncio.run(main())


def main():  # pragma: no cover - subprocess entry
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--address", required=True, help="GCS host:port")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8265)
    args = p.parse_args()
    run_dashboard(args.address, args.host, args.port)


if __name__ == "__main__":
    main()
