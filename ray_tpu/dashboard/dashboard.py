"""Cluster dashboard: HTTP JSON API + Prometheus metrics + minimal UI.

Analog of the reference dashboard head process (dashboard/dashboard.py,
head.py with its pluggable modules: node, actor, job, state, metrics,
healthz — dashboard/modules/) collapsed into one aiohttp app fed directly
from the GCS. The reference's React frontend is replaced by a single
self-contained HTML page; the REST surface mirrors the module routes the
CLI/SDK consume (jobs REST = dashboard/modules/job/job_head.py).

Run standalone:  python -m ray_tpu.dashboard --address HOST:PORT [--port 8265]
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Optional

from aiohttp import web

from ray_tpu._private.protocol import Connection, connect

_HTML = """<!DOCTYPE html>
<html><head><title>ray_tpu dashboard</title>
<style>
 :root { --bg:#fff; --fg:#1a1a1a; --muted:#667; --line:#d8dbe0;
         --accent:#2563eb; --ok:#16a34a; --warn:#d97706; --bad:#dc2626; }
 body { font-family: system-ui, sans-serif; margin:0; color:var(--fg);
        background:var(--bg); }
 header { display:flex; align-items:center; gap:1rem; padding:.7rem 1.2rem;
          border-bottom:1px solid var(--line); }
 header h1 { font-size:1.05rem; margin:0; }
 nav button { border:0; background:none; padding:.45rem .8rem;
              font-size:.9rem; cursor:pointer; color:var(--muted);
              border-bottom:2px solid transparent; }
 nav button.active { color:var(--accent);
                     border-bottom-color:var(--accent); }
 #status { margin-left:auto; font-size:.8rem; color:var(--muted); }
 main { padding:1rem 1.2rem; }
 table { border-collapse:collapse; width:100%; margin-top:.5rem; }
 th, td { border-bottom:1px solid var(--line); padding:.3rem .6rem;
          font-size:.82rem; text-align:left; vertical-align:top; }
 th { color:var(--muted); font-weight:600; cursor:pointer;
      white-space:nowrap; user-select:none; }
 tr:hover td { background:#f6f8fa; }
 .bar { background:#eef1f5; border-radius:4px; height:.9rem; width:14rem;
        display:inline-block; vertical-align:middle; overflow:hidden; }
 .bar i { display:block; height:100%; background:var(--accent); }
 .cards { display:flex; gap:1rem; flex-wrap:wrap; margin:.4rem 0 1rem; }
 .card { border:1px solid var(--line); border-radius:8px;
         padding:.7rem 1rem; min-width:9rem; }
 .card b { font-size:1.4rem; display:block; }
 .card span { font-size:.78rem; color:var(--muted); }
 .state-ALIVE, .state-RUNNING, .state-CREATED, .state-SUCCEEDED
   { color:var(--ok); font-weight:600; }
 .state-PENDING, .state-RESTARTING, .state-PENDING_SCHEDULING
   { color:var(--warn); font-weight:600; }
 .state-DEAD, .state-FAILED, .state-REMOVED { color:var(--bad);
   font-weight:600; }
 input[type=search] { padding:.3rem .5rem; border:1px solid var(--line);
   border-radius:6px; font-size:.85rem; width:16rem; }
 pre { background:#0f172a; color:#e2e8f0; padding: .8rem; border-radius:8px;
       font-size:.78rem; overflow:auto; max-height:24rem; }
 canvas { border:1px solid var(--line); border-radius:6px; }
 code { background:#f2f4f7; padding:0 .25rem; border-radius:3px; }
</style></head>
<body>
<header>
 <h1>ray_tpu</h1>
 <nav id="tabs"></nav>
 <span id="status">connecting…</span>
</header>
<main>
 <div id="controls"></div>
 <div id="main">loading…</div>
</main>
<script>
"use strict";
const TABS = ["overview","nodes","actors","tasks","objects",
              "placement groups","serve","jobs","events","metrics","stacks"];
let tab = location.hash.slice(1) || "overview";
let filter = "", sortKey = null, sortDir = 1, openJob = null;
const hist = {};  // metric sparkline history

const el = (id) => document.getElementById(id);
const fmt = (o) => o === null || o === undefined ? "" :
  typeof o === "object" ? JSON.stringify(o) : String(o);
const esc = (s) => String(s).replace(/&/g,"&amp;").replace(/</g,"&lt;")
  .replace(/"/g,"&quot;").replace(/'/g,"&#39;");
const api = (p) => fetch("api/" + p).then(r => r.json());

function nav() {
  el("tabs").innerHTML = TABS.map(t =>
    `<button class="${t===tab?"active":""}"
      onclick="setTab('${t}')">${t}</button>`).join("");
}
function setTab(t) { tab = t; location.hash = t; sortKey = null;
  openJob = null; filter = ""; nav(); controls(); refresh(); }
function controls() {
  // The filter box lives OUTSIDE the refreshed content so typing never
  // loses focus to a re-render; refreshes also pause while it has focus.
  el("controls").innerHTML = tab === "overview" ? "" :
    `<input type=search id=filterbox placeholder="filter…"
       value="${esc(filter)}"
       oninput="filter=this.value;render()">`;
}

function stateCell(v) {
  return `<span class="state-${esc(v)}">${esc(v)}</span>`;
}
function cmpVals(a, b) {
  if (typeof a === "number" && typeof b === "number") return a - b;
  const fa = fmt(a), fb = fmt(b);
  return fa < fb ? -1 : fa > fb ? 1 : 0;
}
function rows(items, cols, stateCol) {
  if (filter) {
    const f = filter.toLowerCase();
    items = items.filter(it =>
      cols.some(c => fmt(it[c]).toLowerCase().includes(f)));
  }
  if (sortKey) {
    items = [...items].sort((a, b) =>
      sortDir * cmpVals(a[sortKey], b[sortKey]));
  }
  return `<table><tr>${cols.map(c => `<th onclick="sortBy('${c}')">${c}
     ${sortKey===c ? (sortDir>0?"▲":"▼") : ""}</th>`).join("")}</tr>` +
   items.map(it => "<tr>" + cols.map(c =>
     `<td>${c===stateCol ? stateCell(it[c]) : esc(fmt(it[c] ?? ""))}</td>`
   ).join("") + "</tr>").join("") + "</table>" +
   `<p style="color:var(--muted);font-size:.78rem">${items.length} rows</p>`;
}
function sortBy(c) {
  sortDir = sortKey === c ? -sortDir : 1; sortKey = c; refresh();
}

function resourceBars(status) {
  return Object.entries(status.resources_total).map(([k, total]) => {
    const avail = status.resources_available[k] ?? 0;
    const used = total - avail;
    const pct = total ? Math.round(100 * used / total) : 0;
    return `<div style="margin:.2rem 0">
      <code>${esc(k)}</code> ${used.toFixed(2)} / ${total} used
      <span class="bar"><i style="width:${pct}%"></i></span> ${pct}%
      </div>`;
  }).join("");
}

function spark(id, values, w=260, h=48) {
  const c = el(id); if (!c) return;
  const ctx = c.getContext("2d");
  ctx.clearRect(0, 0, w, h);
  if (values.length < 2) return;
  const max = Math.max(...values, 1e-9), min = Math.min(...values, 0);
  ctx.beginPath(); ctx.strokeStyle = "#2563eb"; ctx.lineWidth = 1.5;
  values.forEach((v, i) => {
    const x = i * (w - 4) / (values.length - 1) + 2;
    const y = h - 3 - (v - min) * (h - 8) / (max - min || 1);
    i ? ctx.lineTo(x, y) : ctx.moveTo(x, y);
  });
  ctx.stroke();
}

async function render() {
  if (tab === "overview") {
    const [status, actors, tasks, jobs] = await Promise.all([
      api("cluster_status"), api("actors"), api("tasks"), api("jobs")]);
    const cpuT = status.resources_total.CPU || 0;
    const cpuA = status.resources_available.CPU ?? cpuT;
    (hist.cpu = hist.cpu || []).push(cpuT - cpuA);
    if (hist.cpu.length > 120) hist.cpu.shift();
    el("main").innerHTML = `
      <div class="cards">
       <div class="card"><b>${status.alive_nodes}/${status.total_nodes}</b>
         <span>nodes alive</span></div>
       <div class="card"><b>${actors.filter(a=>a.state==="ALIVE").length}</b>
         <span>live actors</span></div>
       <div class="card"><b>${tasks.length}</b><span>tasks seen</span></div>
       <div class="card"><b>${jobs.length}</b><span>jobs</span></div>
       <div class="card"><canvas id=cpuspark width=260 height=48></canvas>
         <span>CPU in use (last ${hist.cpu.length} ticks)</span></div>
      </div>
      <h3>Cluster resources</h3>${resourceBars(status)}`;
    spark("cpuspark", hist.cpu);
  } else if (tab === "nodes") {
    el("main").innerHTML = rows(await api("nodes"),
      ["node_id","state","draining","address","is_head","resources_total",
       "resources_available","proc_stats"], "state");
  } else if (tab === "actors") {
    el("main").innerHTML = rows(await api("actors"),
      ["actor_id","class_name","name","state","node_id"], "state");
  } else if (tab === "tasks") {
    el("main").innerHTML = rows(await api("tasks"),
      ["task_id","name","type","state"], "state");
  } else if (tab === "objects") {
    el("main").innerHTML = rows(await api("objects"),
      ["object_id","size","locations"]);
  } else if (tab === "placement groups") {
    el("main").innerHTML = rows(await api("placement_groups"),
      ["pg_id","state","strategy","bundles"], "state");
  } else if (tab === "serve") {
    const apps = await api("serve");
    el("main").innerHTML = apps.length
      ? rows(apps, ["app","deployment","target_replicas",
                    "running_replicas","version"])
      : `<p style="color:var(--muted)">no serve applications</p>`;
  } else if (tab === "jobs") {
    const jobs = await api("jobs");
    let html = `<table><tr><th>submission_id</th><th>state</th>
        <th>entrypoint</th><th>logs</th></tr>` +
      jobs.map(j => `<tr><td>${esc(j.submission_id ?? "")}</td>` +
        `<td>${stateCell(j.state ?? "")}</td>` +
        `<td>${esc(j.entrypoint ?? "")}</td>` +
        `<td><a href="#jobs" data-sid="${esc(j.submission_id ?? "")}"
           onclick="openJob=this.dataset.sid;refresh();return false"
           >view</a></td></tr>`).join("") +
      `</table>`;
    if (openJob) {
      const logs = await fetch(`api/jobs/${openJob}/logs`)
        .then(r => r.text());
      html += `<h3>logs: ${esc(openJob)}</h3><pre>${esc(logs)}</pre>`;
    }
    el("main").innerHTML = html;
  } else if (tab === "events") {
    const evts = (await api("events")).reverse().map(e => ({
      time: new Date(e.timestamp * 1000).toLocaleTimeString(),
      source: e.source, severity: e.severity, message: e.message,
      detail: Object.fromEntries(Object.entries(e).filter(([k]) =>
        !["timestamp","source","severity","message","pid"].includes(k))),
    }));
    el("main").innerHTML = rows(evts,
      ["time","source","severity","message","detail"]);
  } else if (tab === "stacks") {
    // On-demand per-worker thread stacks (the `rt stack` profiling
    // drill-down; reference: dashboard reporter py-spy integration).
    el("main").innerHTML = `<p style="color:var(--muted)">collecting live
      thread stacks from every worker…</p>`;
    const nodes = await api("stacks");
    el("main").innerHTML = nodes.map(n => `
      <h3>node ${esc(n.node_id)}</h3>` +
      (n.error ? `<pre>error: ${esc(n.error)}</pre>` :
       n.workers.map(w => `
        <details><summary>pid ${esc(fmt(w.pid))}
          ${w.actor ? "(actor)" : "(worker)"}
          — ${(w.threads||[]).length} threads
          ${w.error ? " — " + esc(w.error) : ""}</summary>
          <pre>${esc((w.threads||[]).map(t =>
            "-- " + t.thread + " --\n" + t.stack).join("\n"))}</pre>
        </details>`).join(""))).join("");
  } else if (tab === "metrics") {
    const text = await fetch("metrics").then(r => r.text());
    const rowsOut = [];
    for (const line of text.split("\n")) {
      if (!line || line.startsWith("#")) continue;
      const i = line.lastIndexOf(" ");
      const name = line.slice(0, i), val = parseFloat(line.slice(i + 1));
      rowsOut.push({metric: name, value: val});
      (hist[name] = hist[name] || []).push(val);
      if (hist[name].length > 120) hist[name].shift();
    }
    el("main").innerHTML = rows(rowsOut, ["metric","value"]);
  }
}

let lastStacks = 0;
async function refresh() {
  if (document.activeElement && document.activeElement.id === "filterbox")
    return;  // don't repaint under the user's caret
  if (tab === "stacks") {
    // Expensive probe: refresh at most every 15s.
    if (Date.now() - lastStacks < 15000) return;
    lastStacks = Date.now();
  }
  try {
    await render();
    el("status").textContent =
      "live · " + new Date().toLocaleTimeString();
  } catch (e) {
    el("status").textContent = "api error: " + e;
  }
}
nav(); controls(); refresh(); setInterval(refresh, 2000);
</script></body></html>
"""


def _hex(b):
    return b.hex() if isinstance(b, (bytes, bytearray)) else b


def _prom_escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_labels(tags) -> str:
    if not tags:
        return ""
    inner = ",".join(f'{k}="{_prom_escape(v)}"' for k, v in tags)
    return "{" + inner + "}"


class Dashboard:
    def __init__(self, gcs_address: str, host: str = "127.0.0.1", port: int = 8265):
        self.gcs_address = gcs_address
        self.host, self.port = host, port
        self.gcs: Optional[Connection] = None
        self.app = web.Application()
        self.app.add_routes(
            [
                web.get("/", self.page),
                web.get("/healthz", self.healthz),
                web.get("/metrics", self.metrics),
                web.get("/api/cluster_status", self.cluster_status),
                web.get("/api/nodes", self.nodes),
                web.get("/api/actors", self.actors),
                web.get("/api/tasks", self.tasks),
                web.get("/api/objects", self.objects),
                web.get("/api/placement_groups", self.placement_groups),
                web.get("/api/jobs", self.jobs),
                web.get("/api/events", self.events),
                web.get("/api/stacks", self.stacks),
                web.get("/api/serve", self.serve_apps),
                web.post("/api/jobs", self.submit_job),
                web.get("/api/jobs/{submission_id}", self.job_info),
                web.get("/api/jobs/{submission_id}/logs", self.job_logs),
                web.post("/api/jobs/{submission_id}/stop", self.stop_job),
            ]
        )
        self._runner: Optional[web.AppRunner] = None

    async def start(self) -> int:
        host, port = self.gcs_address.rsplit(":", 1)
        self.gcs = await connect(host, int(port))
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self):
        if self._runner:
            await self._runner.cleanup()
        if self.gcs:
            await self.gcs.close()

    # -- pages -----------------------------------------------------------
    async def page(self, request):
        return web.Response(text=_HTML, content_type="text/html")

    async def healthz(self, request):
        try:
            await self.gcs.call("ping", {}, timeout=2)
        except Exception:
            return web.Response(status=503, text="gcs unreachable")
        return web.Response(text="ok")

    # -- json api --------------------------------------------------------
    def _json(self, data):
        return web.Response(
            text=json.dumps(data, default=_hex), content_type="application/json"
        )

    async def cluster_status(self, request):
        nodes = (await self.gcs.call("get_nodes", {}))["nodes"]
        alive = [n for n in nodes if n["state"] == "ALIVE"]
        totals, avail = {}, {}
        for n in alive:
            for k, v in n.get("resources_total", {}).items():
                totals[k] = totals.get(k, 0) + v
            for k, v in n.get("resources_available", {}).items():
                avail[k] = avail.get(k, 0) + v
        return self._json(
            {
                "alive_nodes": len(alive),
                "total_nodes": len(nodes),
                "resources_total": totals,
                "resources_available": avail,
                "timestamp": time.time(),
            }
        )

    async def nodes(self, request):
        nodes = (await self.gcs.call("get_nodes", {}))["nodes"]
        return self._json(
            [
                {
                    "node_id": _hex(n["node_id"]),
                    "state": n["state"],
                    "draining": bool(n.get("draining", False)),
                    "address": f"{n['address']}:{n['port']}",
                    "is_head": n.get("is_head", False),
                    "resources_total": n.get("resources_total", {}),
                    "resources_available": n.get("resources_available", {}),
                    "proc_stats": n.get("proc_stats", {}),
                }
                for n in nodes
            ]
        )

    async def actors(self, request):
        actors = (await self.gcs.call("list_actors", {}))["actors"]
        return self._json(
            [
                {
                    "actor_id": _hex(a["actor_id"]),
                    "class_name": a.get("class_name", ""),
                    "state": a.get("state", ""),
                    "name": a.get("name") or "",
                    "node_id": _hex(a.get("node_id") or b""),
                }
                for a in actors
            ]
        )

    async def tasks(self, request):
        events = (await self.gcs.call("list_task_events", {"limit": 100_000}))[
            "events"
        ]
        tasks = {}
        for ev in events:
            t = tasks.setdefault(
                ev["task_id"],
                {"task_id": _hex(ev["task_id"]), "name": ev.get("name", ""),
                 "type": ev.get("type")},
            )
            t["state"] = ev["state"]
        return self._json(list(tasks.values()))

    async def objects(self, request):
        objs = (await self.gcs.call("list_objects", {}))["objects"]
        return self._json(
            [
                {"object_id": _hex(o["object_id"]), "size": o["size"],
                 "locations": [_hex(n) for n in o["nodes"]]}
                for o in objs
            ]
        )

    async def placement_groups(self, request):
        pgs = (await self.gcs.call("list_placement_groups", {}))["pgs"]
        return self._json(
            [
                {"pg_id": _hex(p["pg_id"]), "state": p["state"],
                 "strategy": p["strategy"], "bundles": p["bundles"]}
                for p in pgs
            ]
        )

    async def serve_apps(self, request):
        """Serve application/deployment view, read from the controller's
        GCS-KV checkpoint (written on every mutation — the dashboard
        needs no actor-call machinery; reference: the dashboard serve
        module reading controller state)."""
        import cloudpickle

        try:
            r = await self.gcs.call(
                "kv_get", {"ns": "serve", "key": b"serve_controller_ckpt"}
            )
            raw = r.get("value")
            if not raw:
                return self._json([])
            state = cloudpickle.loads(raw)
        except Exception:  # noqa: BLE001 — no serve running
            return self._json([])
        out = []
        for name, app in (state.get("apps") or {}).items():
            dep = app.get("deployment")
            out.append({
                "app": name,
                "deployment": getattr(dep, "name", str(dep)),
                "target_replicas": app.get("target"),
                "running_replicas": len(app.get("replicas") or []),
                "version": app.get("version"),
            })
        return self._json(out)

    async def stacks(self, request):
        """Live per-worker thread stacks from every (or one) node — the
        `rt stack` drill-down surfaced in the UI (reference: the
        dashboard reporter's on-demand py-spy profiling,
        dashboard/modules/reporter/profile_manager.py)."""
        from ray_tpu._private.protocol import connect as _connect

        node_filter = request.query.get("node_id")
        out = []
        for n in (await self.gcs.call("get_nodes", {}))["nodes"]:
            if n["state"] != "ALIVE":
                continue
            nid = _hex(n["node_id"])
            if node_filter and nid != node_filter:
                continue
            try:
                conn = await _connect(n["address"], n["port"], timeout=5)
                try:
                    r = await asyncio.wait_for(
                        conn.call("worker_stacks", {}), 30
                    )
                finally:
                    await conn.close()
                workers = []
                for w in r.get("workers", []):
                    w = dict(w)
                    wid = w.get("worker_id")
                    if isinstance(wid, (bytes, bytearray)):
                        w["worker_id"] = wid.hex()
                    workers.append(w)
                out.append({"node_id": nid, "workers": workers})
            except Exception as e:  # noqa: BLE001 — node mid-death
                out.append({"node_id": nid, "error": f"{type(e).__name__}: {e}"})
        return self._json(out)

    async def events(self, request):
        """Merged structured event tail (reference: dashboard event
        module over RAY_EVENT JSON files)."""
        from ray_tpu.util.event import read_events

        limit = int(request.query.get("limit", 200))
        return self._json(read_events(limit=limit))

    async def jobs(self, request):
        jobs = (await self.gcs.call("list_jobs", {}))["jobs"]
        return self._json(
            [{**j, "job_id": _hex(j.get("job_id", b"")),
              "node_id": _hex(j.get("node_id") or b"")} for j in jobs]
        )

    async def submit_job(self, request):
        body = await request.json()
        r = await self.gcs.call(
            "submit_job",
            {
                "entrypoint": body["entrypoint"],
                "submission_id": body.get("submission_id"),
                "runtime_env": body.get("runtime_env"),
                "metadata": body.get("metadata"),
            },
        )
        status = 200 if r.get("ok") else 400
        return web.Response(
            status=status, text=json.dumps(r), content_type="application/json"
        )

    async def job_info(self, request):
        sid = request.match_info["submission_id"]
        r = await self.gcs.call("get_job", {"submission_id": sid})
        if r["job"] is None:
            return web.Response(status=404, text="no such job")
        return self._json(
            {**r["job"], "job_id": _hex(r["job"].get("job_id", b"")),
             "node_id": _hex(r["job"].get("node_id") or b"")}
        )

    async def job_logs(self, request):
        sid = request.match_info["submission_id"]
        r = await self.gcs.call("job_logs", {"submission_id": sid})
        if r["logs"] is None:
            return web.Response(status=404, text="no such job")
        return web.Response(text=r["logs"])

    async def stop_job(self, request):
        sid = request.match_info["submission_id"]
        r = await self.gcs.call("stop_job", {"submission_id": sid})
        return self._json(r)

    # -- prometheus ------------------------------------------------------
    async def metrics(self, request):
        lines = []
        # System metrics derived from GCS tables (stats/metric_defs.h
        # analog: node resources, actor/task/job states).
        nodes = (await self.gcs.call("get_nodes", {}))["nodes"]
        lines.append("# TYPE rt_node_resource_total gauge")
        lines.append("# TYPE rt_node_resource_available gauge")
        for n in nodes:
            if n["state"] != "ALIVE":
                continue
            nid = _hex(n["node_id"])[:12]
            for k, v in n.get("resources_total", {}).items():
                lines.append(
                    f'rt_node_resource_total{{node="{nid}",resource="{_prom_escape(k)}"}} {v}'
                )
            for k, v in n.get("resources_available", {}).items():
                lines.append(
                    f'rt_node_resource_available{{node="{nid}",resource="{_prom_escape(k)}"}} {v}'
                )
        actors = (await self.gcs.call("list_actors", {}))["actors"]
        states: dict = {}
        for a in actors:
            states[a.get("state", "?")] = states.get(a.get("state", "?"), 0) + 1
        lines.append("# TYPE rt_actors gauge")
        for s, c in states.items():
            lines.append(f'rt_actors{{state="{s}"}} {c}')

        # GCS-internal runtime metrics (per-component stats).
        stats = await self.gcs.call("gcs_stats", {})
        lines.append("# TYPE rt_gcs_rpc_total counter")
        for method, count in sorted(stats["rpc_counts"].items()):
            lines.append(
                f'rt_gcs_rpc_total{{method="{_prom_escape(method)}"}} {count}'
            )
        for gauge in ("kv_entries", "task_events", "subscriber_conns",
                      "object_dir_entries", "placement_groups"):
            lines.append(f"# TYPE rt_gcs_{gauge} gauge")
            lines.append(f"rt_gcs_{gauge} {stats[gauge]}")

        # User metrics (util/metrics.py) from the GCS aggregate.
        snapshot = (await self.gcs.call("metrics_snapshot", {}))["metrics"]
        for m in snapshot:
            name = m["name"]
            if m["description"]:
                lines.append(f"# HELP {name} {_prom_escape(m['description'])}")
            ptype = {"counter": "counter", "gauge": "gauge",
                     "histogram": "histogram"}[m["type"]]
            lines.append(f"# TYPE {name} {ptype}")
            for tags, val in m["series"]:
                if m["type"] in ("counter", "gauge"):
                    lines.append(f"{name}{_prom_labels(tags)} {val}")
                else:
                    bounds = m["boundaries"]
                    cum = 0
                    for i, b in enumerate(bounds):
                        cum += val["buckets"][i]
                        lab = list(tags) + [["le", str(b)]]
                        lines.append(f"{name}_bucket{_prom_labels(lab)} {cum}")
                    cum += val["buckets"][-1]
                    lab = list(tags) + [["le", "+Inf"]]
                    lines.append(f"{name}_bucket{_prom_labels(lab)} {cum}")
                    lines.append(f"{name}_sum{_prom_labels(tags)} {val['sum']}")
                    lines.append(f"{name}_count{_prom_labels(tags)} {val['count']}")
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")


def run_dashboard(gcs_address: str, host: str = "127.0.0.1", port: int = 8265):
    """Blocking entry point (standalone dashboard process)."""

    async def main():
        dash = Dashboard(gcs_address, host, port)
        actual = await dash.start()
        print(f"DASHBOARD_PORT={actual}", flush=True)
        await asyncio.Event().wait()

    asyncio.run(main())


def main():  # pragma: no cover - subprocess entry
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--address", required=True, help="GCS host:port")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8265)
    args = p.parse_args()
    run_dashboard(args.address, args.host, args.port)


if __name__ == "__main__":
    main()
