from ray_tpu.dashboard.dashboard import main

main()
