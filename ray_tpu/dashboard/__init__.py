from ray_tpu.dashboard.dashboard import Dashboard, run_dashboard  # noqa: F401
