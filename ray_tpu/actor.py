"""@remote actor classes.

Analog of python/ray/actor.py (ActorClass, _remote at :830 which calls
core_worker.create_actor; max_restarts/max_task_retries options at :75/:147).
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu._private import worker as worker_mod
from ray_tpu.remote_function import _resources_from_options, _scheduling_from_options


class ActorClass:
    def __init__(self, cls, **options):
        self._cls = cls
        self._options = options
        self.__name__ = getattr(cls, "__name__", "ActorClass")

    def remote(self, *args, **kwargs):
        client = worker_mod.get_client()
        opts = self._options
        return client.create_actor(
            self._cls,
            args,
            kwargs,
            name=opts.get("name"),
            namespace=opts.get("namespace", ""),
            resources=_resources_from_options(opts),
            max_restarts=opts.get("max_restarts", 0),
            max_task_retries=opts.get("max_task_retries", 0),
            max_concurrency=opts.get("max_concurrency", 1),
            scheduling=_scheduling_from_options(opts),
            detached=opts.get("lifetime") == "detached",
            runtime_env=opts.get("runtime_env"),
            priority=int(opts.get("priority") or 0),
        )

    def options(self, **new_options):
        merged = {**self._options, **new_options}
        return ActorClass(self._cls, **merged)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self.__name__!r} cannot be instantiated directly; "
            f"use .remote(...)"
        )


def method(**options):
    """Decorator for per-method options (reference: ray.method)."""

    def decorator(fn):
        fn.__rt_method_options__ = options
        return fn

    return decorator
