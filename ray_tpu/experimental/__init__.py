from ray_tpu.experimental.channel import Channel, ChannelClosed  # noqa: F401
