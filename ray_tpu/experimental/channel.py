"""Mutable shared-memory channels for compiled DAGs.

Analog of the reference's ``ray.experimental.channel.Channel``
(experimental/channel.py:49) which backs compiled-DAG edges with *mutable*
plasma objects (experimental_mutable_object_put_serialized :129,
read-release :159). Here a channel is one single-producer single-consumer
slot in POSIX shared memory with sequence-number handoff: a write blocks
until the previous value is consumed; a read blocks until a value arrives.
Same-host only — exactly the compiled-DAG fast path (TPU pipeline stages
co-located on one host); cross-host edges fall back to RPC.

Layout: [wseq u64][rseq u64][length u64][flags u64][payload ...]
x86/ARM store ordering + the seq handoff makes the payload visible before
the reader observes the incremented wseq.
"""

from __future__ import annotations

import pickle
import struct
import time
from multiprocessing import shared_memory
from typing import Any, Optional

_HDR = struct.Struct("<QQQQ")
_CLOSED_FLAG = 1


class ChannelClosed(Exception):
    pass


class Channel:
    """One SPSC slot. Create once (driver), attach by name elsewhere."""

    def __init__(self, name: Optional[str] = None, max_size: int = 10_000_000,
                 create: bool = False):
        if create:
            import uuid

            name = name or f"rtchan_{uuid.uuid4().hex[:16]}"
            self._shm = shared_memory.SharedMemory(
                name=name, create=True, size=_HDR.size + max_size
            )
            self._shm.buf[: _HDR.size] = _HDR.pack(0, 0, 0, 0)
        else:
            assert name is not None
            self._shm = shared_memory.SharedMemory(name=name)
            # CPython's resource tracker would unlink the segment when THIS
            # process exits, yanking it from under the creator — standard
            # workaround: attachers unregister (bpo-38119).
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(self._shm._name, "shared_memory")
            except Exception:
                pass
        self.name = name
        self.max_size = self._shm.size - _HDR.size
        self._owner = create

    # -- header access ---------------------------------------------------
    # Each field is written only by its owner (writer: wseq+length, reader:
    # rseq, closer: flags) at its own offset — never a full-header rewrite,
    # which would clobber a concurrent close() with a stale snapshot.
    _WSEQ, _RSEQ, _LEN, _FLAGS = 0, 8, 16, 24

    def _hdr(self):
        return _HDR.unpack_from(self._shm.buf, 0)

    def _put_u64(self, offset: int, value: int):
        struct.pack_into("<Q", self._shm.buf, offset, value)

    # -- ops --------------------------------------------------------------
    def write(self, value: Any, timeout: float = 30.0):
        data = pickle.dumps(value, protocol=5)
        if len(data) > self.max_size:
            raise ValueError(
                f"value of {len(data)} bytes exceeds channel capacity "
                f"{self.max_size}; size the channel's max_size accordingly"
            )
        deadline = time.monotonic() + timeout
        while True:
            wseq, rseq, _, flags = self._hdr()
            if flags & _CLOSED_FLAG:
                raise ChannelClosed(self.name)
            if wseq == rseq:  # previous value consumed
                break
            if time.monotonic() > deadline:
                raise TimeoutError(f"channel {self.name} write timed out")
            time.sleep(0.0001)
        self._shm.buf[_HDR.size : _HDR.size + len(data)] = data
        self._put_u64(self._LEN, len(data))
        self._put_u64(self._WSEQ, wseq + 1)  # publish last

    def read(self, timeout: float = 30.0) -> Any:
        deadline = time.monotonic() + timeout
        while True:
            wseq, rseq, length, flags = self._hdr()
            if wseq != rseq:
                break
            if flags & _CLOSED_FLAG:
                raise ChannelClosed(self.name)
            if time.monotonic() > deadline:
                raise TimeoutError(f"channel {self.name} read timed out")
            time.sleep(0.0001)
        data = bytes(self._shm.buf[_HDR.size : _HDR.size + length])
        value = pickle.loads(data)
        self._put_u64(self._RSEQ, rseq + 1)
        return value

    def close(self):
        try:
            (flags,) = struct.unpack_from("<Q", self._shm.buf, self._FLAGS)
            self._put_u64(self._FLAGS, flags | _CLOSED_FLAG)
        except Exception:
            pass

    def destroy(self):
        self.close()
        try:
            self._shm.close()
        except Exception:
            pass
        if self._owner:
            try:
                self._shm.unlink()
            except Exception:
                pass

    def detach(self):
        try:
            self._shm.close()
        except Exception:
            pass

    def __reduce__(self):
        # Attach-by-name on the receiving side.
        return (_attach, (self.name,))


def _attach(name: str) -> Channel:
    return Channel(name=name)
