"""Compiled DAG execution over shared-memory channels.

Analog of the reference's ``CompiledDAG`` (dag/compiled_dag_node.py:141):
an actor-only DAG is lowered once — every edge gets a pre-allocated
mutable shared-memory channel (experimental/channel.py) and every
participating actor starts a resident exec loop (do_exec_compiled_task
:34) that reads its input channels, runs the bound method, and writes its
output channels. ``execute()`` then costs one channel write + one channel
read on the driver: no scheduler, no GCS, no per-call RPC.

Restrictions (as in the reference's aDAG): all compute nodes must be actor
method calls; actors must be co-located with the driver's host (channels
are same-host shared memory).
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, List

from ray_tpu._private import worker as worker_mod
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
)
from ray_tpu.experimental.channel import Channel


class CompiledDAG:
    def __init__(self, root: DAGNode, max_buf_size: int = 10_000_000):
        self._root = root
        self._max_buf_size = max_buf_size
        self._channels: List[Channel] = []
        self._input_channels: List[Channel] = []
        self._output_channels: List[Channel] = []
        self._actor_loops: List[tuple] = []  # (actor_id, loop_id)
        self._torn_down = False
        self._desynced = False
        self._compile()

    # -- lowering ---------------------------------------------------------
    def _new_channel(self) -> Channel:
        ch = Channel(create=True, max_size=self._max_buf_size)
        self._channels.append(ch)
        return ch

    def _compile(self):
        topo = self._root._topo()
        outputs = (
            list(self._root._bound_args)
            if isinstance(self._root, MultiOutputNode)
            else [self._root]
        )
        compute_nodes = [
            n for n in topo if not isinstance(n, (InputNode, MultiOutputNode))
        ]
        for n in compute_nodes:
            if not isinstance(n, ClassMethodNode):
                raise ValueError(
                    "experimental_compile supports actor-method DAGs only "
                    "(plain task nodes execute eagerly via .execute())"
                )

        # Count consumers per producing node: k consumers => k channels
        # (channels are strictly SPSC).
        consumers: Dict[int, int] = {}
        for n in compute_nodes:
            for up in n._upstream():
                consumers[up._id] = consumers.get(up._id, 0) + 1
        for out in outputs:
            consumers[out._id] = consumers.get(out._id, 0) + 1

        produced: Dict[int, List[Channel]] = {}  # node id -> its channels
        taken: Dict[int, int] = {}  # node id -> channels handed out

        def channels_for(node: DAGNode) -> List[Channel]:
            if node._id not in produced:
                produced[node._id] = [
                    self._new_channel() for _ in range(consumers.get(node._id, 0))
                ]
            return produced[node._id]

        def take_channel(node: DAGNode) -> Channel:
            chans = channels_for(node)
            idx = taken.get(node._id, 0)
            taken[node._id] = idx + 1
            return chans[idx]

        # Per-actor stage lists in topo order.
        stages_by_actor: Dict[bytes, List[dict]] = {}
        for n in compute_nodes:
            arg_spec = []
            for a in n._bound_args:
                if isinstance(a, DAGNode):
                    arg_spec.append({"kind": "chan", "name": take_channel(a).name})
                else:
                    arg_spec.append({"kind": "const", "value": pickle.dumps(a)})
            kwarg_spec = {}
            for k, v in n._bound_kwargs.items():
                if isinstance(v, DAGNode):
                    kwarg_spec[k] = {"kind": "chan", "name": take_channel(v).name}
                else:
                    kwarg_spec[k] = {"kind": "const", "value": pickle.dumps(v)}
            out_chans = [c.name for c in channels_for(n)]
            actor_id = n._actor_handle._actor_id
            stages_by_actor.setdefault(actor_id, []).append(
                {
                    "method": n._method_name,
                    "args": arg_spec,
                    "kwargs": kwarg_spec,
                    "out_channels": out_chans,
                }
            )

        # Driver endpoints.
        for n in topo:
            if isinstance(n, InputNode):
                self._input_channels = channels_for(n)
        self._output_channels = [take_channel(o) for o in outputs]
        self._multi_output = isinstance(self._root, MultiOutputNode)

        # Start resident loops.
        client = worker_mod.get_client()
        for actor_id, stages in stages_by_actor.items():
            aid = actor_id.binary() if hasattr(actor_id, "binary") else actor_id
            r = client.actor_raw_call(
                actor_id, "dag_start",
                {"actor_id": aid, "stages": stages},
            )
            if not r.get("ok"):
                self.teardown()
                raise RuntimeError(
                    f"compiled-DAG loop failed to start: {r.get('error')}"
                )
            self._actor_loops.append((actor_id, r.get("loop_id")))

    # -- execution --------------------------------------------------------
    def execute(self, *input_values, timeout: float = 30.0):
        """One pipelined pass: returns the output value(s) directly."""
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        if self._desynced:
            raise RuntimeError(
                "compiled DAG is desynchronized after a timed-out execute "
                "(an input is still in flight); teardown() and recompile"
            )
        if self._input_channels:
            if not input_values:
                raise ValueError("DAG has an InputNode; pass execute(value)")
            for ch in self._input_channels:
                ch.write(input_values[0], timeout=timeout)
        try:
            outs = [ch.read(timeout=timeout) for ch in self._output_channels]
        except TimeoutError:
            # The input was already written: a late result would pair with
            # the NEXT execute's read, silently skewing every later call.
            self._desynced = True
            raise
        for o in outs:
            if isinstance(o, _StageError):
                raise o.rebuild()
        return outs if self._multi_output else outs[0]

    def teardown(self):
        if self._torn_down:
            return
        self._torn_down = True
        client = worker_mod.get_client_or_none()
        for ch in self._channels:
            ch.close()
        if client is not None:
            for actor_id, loop_id in self._actor_loops:
                try:
                    client.actor_raw_call(
                        actor_id, "dag_stop", {"loop_id": loop_id}
                    )
                except Exception:
                    pass
        for ch in self._channels:
            ch.destroy()

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass


class _StageError:
    """Error marker shipped through channels by a failing stage."""

    def __init__(self, exc: BaseException):
        import traceback

        self.type_name = type(exc).__name__
        self.message = str(exc)
        self.traceback_str = traceback.format_exc()

    def rebuild(self) -> Exception:
        from ray_tpu.exceptions import TaskError

        return TaskError(self.type_name, self.traceback_str or self.message)
