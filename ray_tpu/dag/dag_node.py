"""DAG node types + lazy `.bind()` composition.

Analog of the reference's python/ray/dag/ (dag_node.py, input_node.py,
class_node.py, output_node.py): ``fn.bind(...)`` / ``actor.method.bind(...)``
build a lazy graph; ``dag.execute(input)`` runs it eagerly through normal
task/actor submission, and ``dag.experimental_compile()`` lowers an
actor-only DAG onto pre-allocated shared-memory channels for repeat
low-latency execution (compiled_dag_node.py:141).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional

_node_ids = itertools.count()


class DAGNode:
    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs
        self._id = next(_node_ids)

    # -- graph helpers ----------------------------------------------------
    def _upstream(self) -> List["DAGNode"]:
        ups = [a for a in self._bound_args if isinstance(a, DAGNode)]
        ups += [v for v in self._bound_kwargs.values() if isinstance(v, DAGNode)]
        return ups

    def _topo(self) -> List["DAGNode"]:
        seen: Dict[int, DAGNode] = {}
        order: List[DAGNode] = []

        def visit(node: DAGNode):
            if node._id in seen:
                return
            seen[node._id] = node
            for up in node._upstream():
                visit(up)
            order.append(node)

        visit(self)
        return order

    # -- eager execution --------------------------------------------------
    def execute(self, *input_values, timeout: Optional[float] = None):
        """Run the DAG once through normal task/actor submission."""
        import ray_tpu as rt

        topo = self._topo()
        input_nodes = [n for n in topo if isinstance(n, InputNode)]
        if len(input_nodes) > 1:
            raise ValueError(
                "a DAG may use a single InputNode (reuse the same `inp` "
                "placeholder for every consumer)"
            )
        resolved: Dict[int, Any] = {}
        for node in topo:
            if isinstance(node, InputNode):
                if not input_values:
                    raise ValueError("DAG has an InputNode; pass execute(value)")
                resolved[node._id] = input_values[0]
            else:
                resolved[node._id] = node._execute_node(resolved)
        out = resolved[self._id]
        if isinstance(self, MultiOutputNode):
            return rt.get(list(out), timeout=timeout)
        return rt.get(out, timeout=timeout)

    def _execute_node(self, resolved: Dict[int, Any]):
        raise NotImplementedError

    def _resolve_args(self, resolved):
        args = [
            resolved[a._id] if isinstance(a, DAGNode) else a
            for a in self._bound_args
        ]
        kwargs = {
            k: resolved[v._id] if isinstance(v, DAGNode) else v
            for k, v in self._bound_kwargs.items()
        }
        return args, kwargs

    # -- compilation -------------------------------------------------------
    def experimental_compile(self, max_buf_size: int = 10_000_000):
        from ray_tpu.dag.compiled_dag import CompiledDAG

        return CompiledDAG(self, max_buf_size=max_buf_size)


class InputNode(DAGNode):
    """`with InputNode() as inp:` — the DAG's runtime input placeholder."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_node(self, resolved):
        args, kwargs = self._resolve_args(resolved)
        return self._remote_fn.remote(*args, **kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, actor_method, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_method = actor_method

    @property
    def _actor_handle(self):
        return self._actor_method._handle

    @property
    def _method_name(self) -> str:
        return self._actor_method._name

    def _execute_node(self, resolved):
        args, kwargs = self._resolve_args(resolved)
        return self._actor_method.remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _execute_node(self, resolved):
        return [resolved[o._id] for o in self._bound_args]
