from ray_tpu.dag.dag_node import (  # noqa: F401
    ClassMethodNode,
    DAGNode,
    FunctionNode,
    InputNode,
    MultiOutputNode,
)
from ray_tpu.dag.compiled_dag import CompiledDAG  # noqa: F401
