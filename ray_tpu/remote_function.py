"""@remote functions.

Analog of python/ray/remote_function.py (RemoteFunction at :40, _remote at
:262 which feeds worker.core_worker.submit_task) and the option plumbing in
python/ray/_private/ray_option_utils.py.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private import worker as worker_mod


def _resources_from_options(opts: Dict[str, Any]) -> Dict[str, float]:
    resources = dict(opts.get("resources") or {})
    num_cpus = opts.get("num_cpus")
    num_tpus = opts.get("num_tpus")
    if num_cpus is not None:
        resources["CPU"] = float(num_cpus)
    elif "CPU" not in resources:
        resources["CPU"] = 1.0
    if num_tpus is not None:
        resources["TPU"] = float(num_tpus)
    accelerator_type = opts.get("accelerator_type")
    if accelerator_type:
        resources[accelerator_type] = 0.001
    return resources


def _scheduling_from_options(opts: Dict[str, Any]):
    strategy = opts.get("scheduling_strategy")
    if strategy is None:
        return None
    if isinstance(strategy, str):
        if strategy == "SPREAD":
            return {"type": "spread"}
        if strategy == "DEFAULT":
            return None
        raise ValueError(f"unknown scheduling strategy {strategy!r}")
    return strategy.to_dict()


class RemoteFunction:
    def __init__(self, fn, **options):
        self._function = fn
        self._options = options
        functools.update_wrapper(self, fn)

    def remote(self, *args, **kwargs):
        client = worker_mod.get_client()
        opts = self._options
        num_returns = opts.get("num_returns", 1)
        refs = client.submit_task(
            self._function,
            args,
            kwargs,
            name=opts.get("name") or self._function.__qualname__,
            num_returns=num_returns,
            resources=_resources_from_options(opts),
            scheduling=_scheduling_from_options(opts),
            max_retries=opts.get("max_retries"),
            runtime_env=opts.get("runtime_env"),
            max_calls=opts.get("max_calls"),
            priority=int(opts.get("priority") or 0),
        )
        return refs[0] if num_returns in (1, "dynamic") else refs

    def options(self, **new_options):
        merged = {**self._options, **new_options}
        return RemoteFunction(self._function, **merged)

    def bind(self, *args, **kwargs):
        """Lazy DAG composition (reference: dag/function_node.py)."""
        from ray_tpu.dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._function.__name__!r} cannot be called "
            f"directly; use .remote(...)"
        )
