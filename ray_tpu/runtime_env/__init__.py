from ray_tpu.runtime_env.runtime_env import (  # noqa: F401
    RuntimeEnv,
    apply_runtime_env,
    prepare_runtime_env,
)
