"""Per-job/actor/task runtime environments.

Analog of the reference's runtime-env stack (python/ray/_private/runtime_env/:
``RuntimeEnv`` validation, URI packaging to the GCS KV in packaging.py, and
the per-node agent that materializes envs for workers). Supported fields:

  * ``env_vars``     — exported into the worker process.
  * ``working_dir``  — a local directory, zipped + content-addressed into
                       the GCS KV at submit time; workers download, extract,
                       chdir into it, and prepend it to sys.path.
  * ``py_modules``   — list of local module directories shipped the same
                       way and prepended to sys.path.

``pip``/``conda`` envs are rejected: this build targets TPU pod images
where dependencies are baked in (installing per-task would stall whole
slices); the reference's plugin seam (runtime_env/plugin.py) is kept so a
deployment can add its own handler.

Worker matching: each resolved env has a stable hash; the raylet's worker
pool dispatches a task only to workers started with the same hash
(reference: WorkerPool caches workers by runtime-env hash, worker_pool.h).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import sys
import zipfile
from typing import Any, Dict, List, Optional

_MAX_PACKAGE_BYTES = 512 * 1024 * 1024
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}

# Pluggable field handlers (reference: runtime_env/plugin.py RuntimeEnvPlugin).
# A plugin sees the raw field value at prepare time and the resolved value at
# apply time.
_plugins: Dict[str, "RuntimeEnvPlugin"] = {}


class RuntimeEnvPlugin:
    name: str = ""

    def prepare(self, value: Any, client) -> Any:
        """Driver-side: turn the raw field into something shippable."""
        return value

    def apply(self, value: Any, client) -> None:
        """Worker-side: materialize the field before user code runs."""


def register_plugin(plugin: RuntimeEnvPlugin):
    _plugins[plugin.name] = plugin


class RuntimeEnv(dict):
    """Validated runtime environment spec (dict-compatible)."""

    KNOWN = ("env_vars", "working_dir", "py_modules", "pip", "conda")

    def __init__(
        self,
        *,
        env_vars: Optional[Dict[str, str]] = None,
        working_dir: Optional[str] = None,
        py_modules: Optional[List[str]] = None,
        pip: Optional[Any] = None,
        **kwargs,
    ):
        super().__init__()
        if kwargs.pop("conda", None) is not None:
            raise ValueError(
                "runtime_env['conda'] is not supported on this TPU build: "
                "use 'pip' (per-env-hash venvs) or bake dependencies into "
                "the host image"
            )
        unknown = set(kwargs) - set(_plugins)
        if unknown:
            raise ValueError(f"unknown runtime_env fields: {sorted(unknown)}")
        if env_vars:
            if not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in env_vars.items()
            ):
                raise TypeError("env_vars must be Dict[str, str]")
            self["env_vars"] = dict(env_vars)
        if working_dir:
            self["working_dir"] = working_dir
        if py_modules:
            self["py_modules"] = list(py_modules)
        if pip:
            # Reference shapes (runtime_env/pip.py): list of requirement
            # strings / pip args, or {"packages": [...]}.
            if isinstance(pip, dict):
                pip = list(pip.get("packages") or ())
            if not isinstance(pip, (list, tuple)) or not all(
                isinstance(p, str) for p in pip
            ):
                raise TypeError("pip must be a list of requirement strings")
            self["pip"] = list(pip)
        for k, v in kwargs.items():
            self[k] = v


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
            for fname in sorted(files):
                full = os.path.join(root, fname)
                rel = os.path.relpath(full, path)
                try:
                    total += os.path.getsize(full)
                except OSError:
                    continue
                if total > _MAX_PACKAGE_BYTES:
                    raise ValueError(
                        f"runtime_env package {path!r} exceeds "
                        f"{_MAX_PACKAGE_BYTES >> 20} MiB"
                    )
                zi = zipfile.ZipInfo(rel)  # fixed date => stable hash
                with open(full, "rb") as f:
                    zf.writestr(zi, f.read())
    return buf.getvalue()


class GcsKvAdapter:
    """Sync kv_get/kv_put facade over a raw GCS Connection, for callers
    (job client, raylet job supervisor) that don't hold a CoreClient.
    Must be used from a thread other than the connection's event loop."""

    def __init__(self, conn, loop):
        self._conn = conn
        self._loop = loop

    def _call(self, method, payload):
        import asyncio

        return asyncio.run_coroutine_threadsafe(
            self._conn.call(method, payload), self._loop
        ).result(120)

    def kv_get(self, key: bytes, ns: str = ""):
        return self._call("kv_get", {"ns": ns, "key": key})["value"]

    def kv_put(self, key: bytes, value: bytes, ns: str = "", overwrite=True):
        return self._call(
            "kv_put", {"ns": ns, "key": key, "value": value,
                       "overwrite": overwrite}
        )["added"]


def compute_env_hash(resolved: Dict[str, Any]) -> str:
    return hashlib.sha256(
        json.dumps(resolved, sort_keys=True).encode()
    ).hexdigest()[:16]


def package_dir(path: str):
    """Zip + content-address a directory: returns (blob, uri) using
    packaging.py's gcs://_ray_pkg_<hash>.zip scheme."""
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env directory not found: {path}")
    blob = _zip_dir(path)
    digest = hashlib.sha256(blob).hexdigest()[:32]
    return blob, f"gcs://_rt_pkg_{digest}.zip"


def _upload_dir(client, path: str) -> str:
    blob, uri = package_dir(path)
    key = uri.encode()
    if client.kv_get(key, ns="pkg") is None:
        client.kv_put(key, blob, ns="pkg")
    return uri


def extract_package(blob: bytes, uri: str) -> str:
    """Extract a package blob to its content-addressed dir; idempotent."""
    digest = uri.removeprefix("gcs://").removesuffix(".zip")
    dest = os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "ray_tpu", "pkg", digest
    )
    if os.path.exists(os.path.join(dest, ".rt_complete")):
        return dest
    tmp = dest + f".tmp{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    open(os.path.join(tmp, ".rt_complete"), "w").close()
    try:
        os.rename(tmp, dest)
    except OSError:  # concurrent extraction won
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def prepare_runtime_env(renv: Optional[dict], client) -> Optional[dict]:
    """Driver side: resolve local paths to KV URIs; returns a plain dict
    with a stable content hash under "hash"."""
    if not renv:
        return None
    if not isinstance(renv, RuntimeEnv):
        renv = RuntimeEnv(**renv)
    resolved: Dict[str, Any] = {}
    if renv.get("env_vars"):
        resolved["env_vars"] = dict(renv["env_vars"])
    if renv.get("working_dir"):
        wd = renv["working_dir"]
        resolved["working_dir_uri"] = (
            wd if wd.startswith("gcs://") else _upload_dir(client, wd)
        )
    if renv.get("py_modules"):
        resolved["py_module_uris"] = [
            m if m.startswith("gcs://") else _upload_dir(client, m)
            for m in renv["py_modules"]
        ]
    if renv.get("pip"):
        resolved["pip"] = sorted(renv["pip"])
    for name, plugin in _plugins.items():
        if renv.get(name) is not None:
            resolved[name] = plugin.prepare(renv[name], client)
            # Workers are separate processes: ship the plugin's import
            # path so apply_runtime_env can load it there (reference:
            # RAY_RUNTIME_ENV_PLUGINS class-path loading, plugin.py).
            resolved.setdefault("_plugin_paths", {})[name] = (
                f"{type(plugin).__module__}:{type(plugin).__qualname__}"
            )
    if not resolved:
        return None
    resolved["hash"] = compute_env_hash(resolved)
    return resolved


def _materialize(client, uri: str) -> str:
    """Download + extract a package URI; idempotent per host."""
    digest = uri.removeprefix("gcs://").removesuffix(".zip")
    dest = os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "ray_tpu", "pkg", digest
    )
    if os.path.exists(os.path.join(dest, ".rt_complete")):
        return dest
    blob = client.kv_get(uri.encode(), ns="pkg")
    if blob is None:
        raise RuntimeError(f"runtime_env package {uri} missing from GCS")
    return extract_package(blob, uri)


def _venv_root() -> str:
    return os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "ray_tpu", "venvs"
    )


def _pip_site_packages(venv_dir: str) -> str:
    py = f"python{sys.version_info.major}.{sys.version_info.minor}"
    return os.path.join(venv_dir, "lib", py, "site-packages")


def _materialize_pip_env(pip_args: List[str], env_hash: str) -> str:
    """Per-env-hash venv with the requested pip installs; idempotent per
    host, concurrency-safe via atomic mkdir + ready marker.

    Reference analog: _private/runtime_env/pip.py (per-env virtualenv with
    --system-site-packages so the base image's jax/numpy stay visible).
    Returns the venv's site-packages path to prepend to sys.path.
    """
    import subprocess
    import time as _time

    venv_dir = os.path.join(_venv_root(), env_hash)
    ready = os.path.join(venv_dir, ".rt_ready")
    site = _pip_site_packages(venv_dir)
    if os.path.exists(ready):
        return site
    claim = venv_dir + ".building"
    try:
        os.makedirs(claim)  # atomic claim
        building = True
    except FileExistsError:
        building = False
    if not building:
        # Another worker is installing: wait for the marker.
        deadline = _time.monotonic() + 600
        while _time.monotonic() < deadline:
            if os.path.exists(ready):
                return site
            _time.sleep(0.5)
        raise RuntimeError(
            f"timed out waiting for pip env {env_hash} to build"
        )
    try:
        import venv as _venv

        os.makedirs(os.path.dirname(venv_dir), exist_ok=True)
        _venv.EnvBuilder(
            system_site_packages=True, with_pip=True, clear=True
        ).create(venv_dir)
        pip_bin = os.path.join(venv_dir, "bin", "pip")
        r = subprocess.run(
            [pip_bin, "install", "--disable-pip-version-check", *pip_args],
            capture_output=True, text=True, timeout=600,
        )
        if r.returncode != 0:
            raise RuntimeError(
                f"pip install {' '.join(pip_args)} failed:\n{r.stderr[-2000:]}"
            )
        with open(ready, "w") as f:
            f.write("ok")
        return site
    except BaseException:
        import shutil as _shutil

        _shutil.rmtree(venv_dir, ignore_errors=True)
        raise
    finally:
        import shutil as _shutil

        _shutil.rmtree(claim, ignore_errors=True)


def apply_runtime_env(resolved: Optional[dict], client) -> None:
    """Worker side: materialize the env before running user code."""
    if not resolved:
        return
    for k, v in (resolved.get("env_vars") or {}).items():
        os.environ[k] = v
    if resolved.get("pip"):
        site = _materialize_pip_env(resolved["pip"], resolved["hash"])
        if site not in sys.path:
            sys.path.insert(0, site)
    for uri in resolved.get("py_module_uris") or ():
        path = _materialize(client, uri)
        if path not in sys.path:
            sys.path.insert(0, path)
    wd_uri = resolved.get("working_dir_uri")
    if wd_uri:
        path = _materialize(client, wd_uri)
        if path not in sys.path:
            sys.path.insert(0, path)
        os.chdir(path)
    # Load any plugins this env used that aren't registered in this
    # process (py_modules above may have just made them importable).
    import importlib

    for name, path in (resolved.get("_plugin_paths") or {}).items():
        if name not in _plugins:
            mod_name, _, cls_name = path.partition(":")
            cls = getattr(importlib.import_module(mod_name), cls_name)
            register_plugin(cls())
    for name, plugin in _plugins.items():
        if resolved.get(name) is not None:
            plugin.apply(resolved[name], client)
