"""The `rt` command-line interface.

Analog of the reference CLI (python/ray/scripts/scripts.py: `ray start`
:566, `ray stop` :1042, `ray status`, `ray list/summary` via state_cli.py,
`ray timeline`, `ray memory`). Cluster services start as real subprocesses
(the standalone GCS and raylet mains), tracked through a session file so
`rt stop` can tear them down.

Usage:
    rt start --head [--port 6379] [--num-cpus N] [--resources '{...}']
    rt start --address HOST:PORT [--num-cpus N]
    rt stop
    rt status [--address HOST:PORT]
    rt list {nodes,tasks,actors,objects,jobs,placement-groups,workers}
    rt summary tasks
    rt timeline [--output FILE]
    rt memory
    rt job submit|status|logs|list|stop ...
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Optional

SESSION_FILE = os.path.join(
    os.environ.get("TMPDIR", "/tmp"), "ray_tpu", "session.json"
)


def _read_session() -> Optional[dict]:
    try:
        with open(SESSION_FILE) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _write_session(data: dict):
    os.makedirs(os.path.dirname(SESSION_FILE), exist_ok=True)
    with open(SESSION_FILE, "w") as f:
        json.dump(data, f)


def _log_dir() -> str:
    from ray_tpu._private.config import session_log_dir

    d = session_log_dir()
    os.makedirs(d, exist_ok=True)
    return d


def _spawn_service(name: str, cmd: list) -> subprocess.Popen:
    """Start a daemon with stdout/stderr to a session log file, NOT
    inherited — inherited pipes keep `rt start | ...` pipelines open
    forever and break user prints once the CLI exits."""
    log = open(os.path.join(_log_dir(), f"{name}-{os.getpid()}.log"), "ab")
    return subprocess.Popen(
        cmd, stdout=log, stderr=subprocess.STDOUT, start_new_session=True
    )


def _wait_for_key(proc: subprocess.Popen, log_path: str, prefix: str,
                  timeout: float = 60.0) -> str:
    """Poll the service's log until its `KEY=value` bootstrap line appears."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            tail = ""
            try:
                with open(log_path) as f:
                    tail = f.read()[-2000:]
            except OSError:
                pass
            raise RuntimeError(
                f"process exited while waiting for {prefix}\n{tail}"
            )
        try:
            with open(log_path) as f:
                for line in f:
                    if line.startswith(prefix):
                        return line.strip().split("=", 1)[1]
        except OSError:
            pass
        time.sleep(0.1)
    raise TimeoutError(f"timed out waiting for {prefix}")


def _resolve_address(args) -> str:
    addr = getattr(args, "address", None)
    if addr:
        return addr
    addr = os.environ.get("RT_GCS_ADDR")
    if addr:
        return addr
    sess = _read_session()
    if sess:
        return sess["gcs_address"]
    sys.exit("error: no running session found; pass --address host:port")


def cmd_start(args):
    logdir = _log_dir()
    if args.head:
        gcs = _spawn_service(
            "gcs",
            [sys.executable, "-m", "ray_tpu._private.gcs", "--port", str(args.port)],
        )
        gcs_log = os.path.join(logdir, f"gcs-{os.getpid()}.log")
        gcs_port = int(_wait_for_key(gcs, gcs_log, "GCS_PORT="))
        raylet_cmd = [
            sys.executable, "-m", "ray_tpu._private.raylet",
            "--gcs-port", str(gcs_port), "--head",
        ]
    else:
        address = _resolve_address(args)
        host, port = address.rsplit(":", 1)
        gcs = None
        gcs_port = int(port)
        raylet_cmd = [
            sys.executable, "-m", "ray_tpu._private.raylet",
            "--gcs-host", host, "--gcs-port", str(gcs_port),
        ]
    if args.num_cpus is not None:
        raylet_cmd += ["--num-cpus", str(args.num_cpus)]
    if args.resources:
        raylet_cmd += ["--resources", args.resources]
    if args.object_store_memory:
        raylet_cmd += ["--object-store-memory", str(args.object_store_memory)]
    raylet = _spawn_service("raylet", raylet_cmd)
    raylet_log = os.path.join(logdir, f"raylet-{os.getpid()}.log")
    raylet_port = int(_wait_for_key(raylet, raylet_log, "RAYLET_PORT="))
    node_id = _wait_for_key(raylet, raylet_log, "RAYLET_NODE_ID=")

    gcs_address = f"127.0.0.1:{gcs_port}" if args.head else _resolve_address(args)
    sess = _read_session() if not args.head else None
    pids = (sess or {}).get("pids", [])
    if gcs is not None:
        pids.append(gcs.pid)
    pids.append(raylet.pid)
    dashboard_port = None
    if args.head and not args.no_dashboard:
        dash = _spawn_service(
            "dashboard",
            [sys.executable, "-m", "ray_tpu.dashboard",
             "--address", gcs_address, "--port", str(args.dashboard_port)],
        )
        dash_log = os.path.join(_log_dir(), f"dashboard-{os.getpid()}.log")
        try:
            dashboard_port = int(
                _wait_for_key(dash, dash_log, "DASHBOARD_PORT=", timeout=60)
            )
            pids.append(dash.pid)
        except (RuntimeError, TimeoutError) as e:
            print(f"warning: dashboard failed to start: {e}")
            try:
                dash.kill()
            except OSError:
                pass
    if sess and dashboard_port is None:
        dashboard_port = sess.get("dashboard_port")
    _write_session(
        {"gcs_address": gcs_address, "pids": pids, "raylet_port": raylet_port,
         "dashboard_port": dashboard_port}
    )
    print(f"started node {node_id[:12]} (raylet port {raylet_port})")
    print(f"GCS address: {gcs_address}")
    if dashboard_port:
        print(f"dashboard: http://127.0.0.1:{dashboard_port}")
    print(f'connect with:  ray_tpu.init(address="{gcs_address}")')
    if args.block:
        try:
            raylet.wait()
        except KeyboardInterrupt:
            pass


def cmd_stop(args):
    sess = _read_session()
    if not sess:
        print("no running session")
        return
    for pid in reversed(sess.get("pids", [])):
        try:
            os.kill(pid, signal.SIGTERM)
            print(f"stopped pid {pid}")
        except ProcessLookupError:
            pass
    deadline = time.monotonic() + 5
    for pid in sess.get("pids", []):
        while time.monotonic() < deadline:
            try:
                os.kill(pid, 0)
                time.sleep(0.1)
            except ProcessLookupError:
                break
        else:
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
    try:
        os.unlink(SESSION_FILE)
    except OSError:
        pass


def cmd_status(args):
    from ray_tpu.util.state import list_nodes

    nodes = list_nodes(address=_resolve_address(args))
    alive = [n for n in nodes if n["state"] == "ALIVE"]
    print(f"{len(alive)}/{len(nodes)} nodes alive")
    totals: dict = {}
    avail: dict = {}
    for n in alive:
        for k, v in n["resources_total"].items():
            totals[k] = totals.get(k, 0) + v
        for k, v in n["resources_available"].items():
            avail[k] = avail.get(k, 0) + v
    print("resources:")
    for k in sorted(totals):
        print(f"  {avail.get(k, 0):g}/{totals[k]:g} {k}")
    for n in nodes:
        head = " (head)" if n.get("is_head") else ""
        print(f"  node {n['node_id'][:12]} {n['state']}{head} @ {n['address']}")


def cmd_list(args):
    from ray_tpu.util import state as state_api

    fns = {
        "nodes": state_api.list_nodes,
        "tasks": state_api.list_tasks,
        "actors": state_api.list_actors,
        "objects": state_api.list_objects,
        "jobs": state_api.list_jobs,
        "placement-groups": state_api.list_placement_groups,
        "workers": state_api.list_workers,
    }
    rows = fns[args.entity](address=_resolve_address(args))
    print(json.dumps(rows, indent=2, default=str))


def cmd_summary(args):
    from ray_tpu.util.state import summarize_tasks

    print(json.dumps(summarize_tasks(address=_resolve_address(args)), indent=2))


def cmd_timeline(args):
    if getattr(args, "cluster", False):
        return _timeline_cluster(args)
    from ray_tpu.util.state import get_timeline

    trace = get_timeline(
        address=_resolve_address(args),
        lifecycle=getattr(args, "lifecycle", False),
    )
    out = args.output or f"timeline-{int(time.time())}.json"
    with open(out, "w") as f:
        json.dump(trace, f)
    print(f"wrote {len(trace)} events to {out} (open in chrome://tracing)")


def _wait_bundle(bundle: str, settle_s: float = 0.4,
                 timeout_s: float = 5.0) -> None:
    """Wait for a postmortem bundle to stop growing: processes dump on
    the pubsub push asynchronously, so the CLI polls until the file
    count holds still for `settle_s` (or gives up at `timeout_s`)."""
    deadline = time.monotonic() + timeout_s
    last_n, last_change = -1, time.monotonic()
    while time.monotonic() < deadline:
        try:
            n = len([f for f in os.listdir(bundle) if f.endswith(".jsonl")])
        except OSError:
            n = 0
        if n != last_n:
            last_n, last_change = n, time.monotonic()
        elif n > 0 and time.monotonic() - last_change >= settle_s:
            return
        time.sleep(0.1)


def _timeline_cluster(args):
    """Live merged spine: force a cluster-wide journal dump and render
    the assembled HLC-ordered timeline."""
    from ray_tpu.util import journal
    from ray_tpu.util.state.api import StateApiClient

    client = StateApiClient(_resolve_address(args))
    try:
        resp = client.call("journal_trigger", {
            "reason": "manual", "source": "rt timeline", "force": True,
        })
    finally:
        client.close()
    bundle = resp.get("bundle")
    if not bundle:
        print("journal trigger suppressed (journal disabled?)",
              file=sys.stderr)
        sys.exit(1)
    _wait_bundle(bundle)
    events, metas = journal.load_bundle(bundle)
    print(f"cluster spine: {len(events)} events from {len(metas)} "
          f"process(es) — bundle {bundle}")
    print(journal.render_timeline(events, limit=args.limit))


def cmd_postmortem(args):
    """Assemble a postmortem bundle into one causally-ordered timeline
    and name the culprit chain."""
    from ray_tpu.util import journal

    bundle = args.bundle
    if bundle in (None, "latest"):
        bundle = _latest_bundle(args)
        if bundle is None:
            print("no postmortem bundles found (none triggered yet, or "
                  f"look under {journal.dump_dir()})", file=sys.stderr)
            sys.exit(1)
    if not os.path.isdir(bundle):
        print(f"not a bundle directory: {bundle}", file=sys.stderr)
        sys.exit(1)
    events, metas = journal.load_bundle(bundle)
    if not events:
        print(f"bundle {bundle} holds no events", file=sys.stderr)
        sys.exit(1)
    procs = sorted({f"{m.get('proc', '?')}({m.get('pid', '?')})"
                    for m in metas})
    trigger = next((m.get("trigger") for m in metas
                    if m.get("trigger")), None) or {}
    print(f"postmortem {os.path.basename(bundle)} — {len(events)} events "
          f"from {len(metas)} process(es): {', '.join(procs)}")
    if trigger:
        print(f"trigger: {trigger.get('reason', '?')} "
              f"(source: {trigger.get('source') or 'auto'})")
    chain = journal.causal_chain(events)
    if chain:
        print("\nculprit chain:")
        t0 = chain[0].get("ts", 0.0)
        for i, e in enumerate(chain):
            arrow = "   " if i == 0 else " → "
            print(f" {arrow}{journal._fmt_event(e, t0)}")
    else:
        print("\nno causal chain found (no seed fault in the window)")
    if not args.chain_only:
        print("\nmerged timeline:")
        print(journal.render_timeline(events, limit=args.limit))


def _latest_bundle(args) -> Optional[str]:
    """Newest bundle: ask the GCS first (it minted them), fall back to
    scanning the dump directory (offline postmortems)."""
    from ray_tpu.util import journal

    try:
        from ray_tpu.util.state.api import StateApiClient

        client = StateApiClient(_resolve_address(args))
        try:
            pms = client.call("get_postmortems", {}).get("postmortems", [])
        finally:
            client.close()
        if pms:
            return pms[-1]["bundle"]
    except Exception:  # noqa: BLE001 — no live cluster; scan the dir
        pass
    root = journal.dump_dir()
    try:
        cands = [os.path.join(root, d) for d in os.listdir(root)
                 if os.path.isdir(os.path.join(root, d))]
    except OSError:
        return None
    return max(cands, key=os.path.getmtime) if cands else None


def cmd_profile(args):
    """Flip cluster-wide lifecycle sampling / show the phase breakdown."""
    from ray_tpu.util import lifecycle
    from ray_tpu.util.state.api import StateApiClient

    client = StateApiClient(_resolve_address(args))
    try:
        if args.on or args.off:
            rate = 0.0 if args.off else (
                args.rate if args.rate is not None else 1.0
            )
            client.call("set_profile_config", {"task_trace_sample": rate})
            state = "off" if rate == 0.0 else f"on (rate {rate:g})"
            print(f"task lifecycle sampling: {state} — applies to every "
                  "connected driver and worker")
            return
        if args.profile_command != "tasks":
            print("usage: rt profile [--on [--rate R] | --off | tasks]",
                  file=sys.stderr)
            sys.exit(2)
        records = lifecycle.stitch(client.task_events())
        if not records:
            print("no sampled lifecycle spans; enable with `rt profile --on"
                  " [--rate R]` or RT_TASK_TRACE_SAMPLE=R")
            return
        agg = lifecycle.aggregate(records)
        cov = agg.pop("coverage", None)
        e2e = agg.pop("e2e", None)
        print(f"{len(records)} sampled tasks — per-phase latency (µs)")
        print(f"  {'phase':<14}{'count':>8}{'mean':>12}{'p50':>12}{'p99':>12}")
        for phase, row in agg.items():
            print(f"  {phase:<14}{row['count']:>8}{row['mean_us']:>12.1f}"
                  f"{row['p50_us']:>12.1f}{row['p99_us']:>12.1f}")
        if e2e:
            print(f"  {'e2e':<14}{e2e['count']:>8}{e2e['mean_us']:>12.1f}"
                  f"{e2e['p50_us']:>12.1f}{e2e['p99_us']:>12.1f}")
        if cov:
            print(f"  phase coverage of e2e wall: mean "
                  f"{100 * cov['mean_us']:.1f}%  p50 {100 * cov['p50_us']:.1f}%")
    finally:
        client.close()


def _hist_percentile(buckets, bounds, q):
    """Upper-bound percentile estimate from cumulative histogram buckets."""
    total = sum(buckets)
    if not total:
        return 0.0
    target = q * total
    cum = 0
    for i, n in enumerate(buckets):
        cum += n
        if cum >= target:
            return bounds[i] if i < len(bounds) else bounds[-1]
    return bounds[-1]


def cmd_rpc(args):
    """Per-method GCS RPC accounting (server-side handler latency)."""
    from ray_tpu.util.state.api import StateApiClient

    client = StateApiClient(_resolve_address(args))
    try:
        stats = client.call("gcs_stats")
    finally:
        client.close()
    lat = stats.get("rpc_latency") or {}
    bounds = stats.get("rpc_latency_boundaries") or []
    if not lat:
        print("no GCS RPCs recorded yet")
        return
    rows = sorted(lat.items(), key=lambda kv: -kv[1].get("sum_s", 0.0))
    total_calls = sum(st.get("count", 0) for _, st in rows)
    total_s = sum(st.get("sum_s", 0.0) for _, st in rows)
    print(f"GCS RPCs: {total_calls} calls, {total_s * 1e3:.1f} ms handler "
          "time — by method, busiest first")
    print(f"  {'method':<24}{'calls':>9}{'total_ms':>11}{'mean_us':>10}"
          f"{'p50_us':>9}{'p99_us':>9}{'max_ms':>9}")
    for method, st in rows:
        n = st.get("count", 0) or 1
        bkts = st.get("buckets") or []
        print(f"  {method:<24}{st.get('count', 0):>9}"
              f"{st.get('sum_s', 0.0) * 1e3:>11.1f}"
              f"{st.get('sum_s', 0.0) / n * 1e6:>10.1f}"
              f"{_hist_percentile(bkts, bounds, 0.5) * 1e6:>9.0f}"
              f"{_hist_percentile(bkts, bounds, 0.99) * 1e6:>9.0f}"
              f"{st.get('max_s', 0.0) * 1e3:>9.2f}")


def cmd_trace(args):
    """Print one trace's span tree (TRACE_SPAN events, parent-linked)."""
    from ray_tpu.util import tracing

    spans = tracing.get_trace(args.trace_id, address=_resolve_address(args))
    if not spans:
        print(f"no finished spans for trace {args.trace_id}")
        return
    by_id = {s["span_id"]: s for s in spans}
    children: dict = {}
    roots = []
    for s in spans:
        pid = s.get("parent_id") or ""
        if pid and pid in by_id:
            children.setdefault(pid, []).append(s)
        else:
            roots.append(s)

    def emit(s, depth):
        print(f"  {'  ' * depth}{s['name'] or '<unnamed>'}  "
              f"[{s['kind']}]  {s['dur_s'] * 1e3:.2f} ms")
        for c in children.get(s["span_id"], []):
            emit(c, depth + 1)

    print(f"trace {args.trace_id}: {len(spans)} spans")
    for r in roots:
        emit(r, 0)


def cmd_memory(args):
    from ray_tpu.util.state import list_objects

    if getattr(args, "devices", False):
        # Unified HBM + object-store view from the memory accountant.
        from ray_tpu.util.memory import memory_summary

        s = memory_summary(address=_resolve_address(args))
        print(f"HBM (live jax arrays): {s['hbm_live_bytes'] / 1e6:.1f} MB "
              f"across {len(s['devices'])} sampled device(s)")
        for d in s["devices"]:
            extra = ""
            if "bytes_in_use" in d:
                extra = f"  in_use {d['bytes_in_use'] / 1e6:.1f} MB"
                if "bytes_limit" in d and d["bytes_limit"]:
                    extra += (f" / limit {d['bytes_limit'] / 1e6:.1f} MB"
                              f" ({100 * d['bytes_in_use'] / d['bytes_limit']:.0f}%)")
            print(f"  node {d['node']} {d['device']}: "
                  f"{d.get('live_bytes', 0) / 1e6:>9.1f} MB live "
                  f"({d.get('live_arrays', 0)} arrays){extra}")
        st = s["object_store"]
        print(f"object store: {st['used_bytes'] / 1e6:.1f} MB, "
              f"{st['num_objects']} objects "
              f"({s['objects']['count']} in object table, "
              f"{s['objects']['bytes'] / 1e6:.1f} MB primary copies)")
        for node, v in sorted(st["per_node"].items()):
            print(f"  node {node}: {v.get('used_bytes', 0) / 1e6:>9.1f} MB, "
                  f"{v.get('num_objects', 0)} objects")
        return

    objs = list_objects(address=_resolve_address(args))
    total = sum(o["size"] for o in objs)
    print(f"{len(objs)} objects, {total / 1e6:.1f} MB total")
    for o in sorted(objs, key=lambda o: -o["size"])[:50]:
        locs = ",".join(loc[:8] for loc in o["locations"])
        print(f"  {o['object_id'][:16]}  {o['size']:>12} B  on [{locs}]")


def _series_by_tags(snapshot, name):
    """[(tags_dict, value)] for one metric from a metrics_snapshot reply."""
    for m in snapshot:
        if m["name"] == name:
            return [(dict(tuple(t) for t in tags), val)
                    for tags, val in m["series"]]
    return []


def _hist_total(snapshot, name):
    """(count, sum) over every tag set of a histogram metric."""
    count, total = 0, 0.0
    for _, st in _series_by_tags(snapshot, name):
        if isinstance(st, dict):
            count += st.get("count", 0)
            total += st.get("sum", 0.0)
    return count, total


def _render_top(snapshot, nodes) -> str:
    """One frame of the `rt top` live cluster view, assembled purely from
    the GCS metrics snapshot + node table (no per-node dials)."""
    lines = []
    alive = [n for n in nodes if n["state"] == "ALIVE"]
    lines.append(f"rt top — {len(alive)}/{len(nodes)} nodes alive")

    # -- training: per-rank step wall + skew/straggler -------------------
    per_rank = {}
    for tags, st in _series_by_tags(snapshot, "train_step_wall_seconds"):
        if isinstance(st, dict) and st.get("count"):
            per_rank[tags.get("rank", "-")] = st
    if per_rank:
        lines.append("train:")
        phases = {}
        for tags, v in _series_by_tags(snapshot,
                                       "train_step_phase_seconds_total"):
            phases.setdefault(tags.get("rank", "-"), {})[
                tags.get("phase", "?")] = v
        compiles = {t.get("rank", "-"): v for t, v in
                    _series_by_tags(snapshot, "train_step_compiles_total")}
        tput = {t.get("rank", "-"): v for t, v in
                _series_by_tags(snapshot, "train_tokens_per_s")}
        means = {}
        for rank in sorted(per_rank):
            st = per_rank[rank]
            mean_ms = st["sum"] / st["count"] * 1e3
            means[rank] = mean_ms
            ph = phases.get(rank, {})
            ph_total = sum(ph.values()) or 1.0
            ph_str = " ".join(
                f"{k} {100 * v / ph_total:.0f}%"
                for k, v in sorted(ph.items(), key=lambda kv: -kv[1])
            )
            extra = ""
            if rank in tput:
                extra += f"  {tput[rank]:,.0f} tok/s"
            if compiles.get(rank):
                extra += f"  compiles={compiles[rank]:.0f}"
            lines.append(f"  rank {rank}: {st['count']} steps, "
                         f"{mean_ms:.1f} ms/step  [{ph_str}]{extra}")
        if len(means) >= 2:
            slowest = max(means, key=means.get)
            skew_ms = means[slowest] - min(means.values())
            lines.append(f"  skew: {skew_ms:.1f} ms/step — slowest rank "
                         f"{slowest} (straggler)")
    sk_count, sk_sum = _hist_total(snapshot, "train_step_skew_seconds")
    if sk_count:
        lines.append(f"  skew metric: {sk_sum / sk_count * 1e3:.1f} ms avg "
                     f"over {sk_count} polls")

    # -- memory: HBM gauges + per-node object store ----------------------
    hbm = _series_by_tags(snapshot, "device_hbm_live_bytes")
    store = _series_by_tags(snapshot, "rt_raylet_store_used_bytes")
    if hbm or store:
        lines.append("memory:")
        for tags, v in sorted(hbm, key=lambda x: (x[0].get("node", ""),
                                                  x[0].get("device", ""))):
            lines.append(f"  hbm {tags.get('node', '-')} "
                         f"{tags.get('device', '?')}: {v / 1e6:.1f} MB live")
        for tags, v in sorted(store, key=lambda x: x[0].get("node", "")):
            lines.append(f"  store {tags.get('node', '-')}: "
                         f"{v / 1e6:.1f} MB")

    # -- data feed -------------------------------------------------------
    st_count, st_sum = _hist_total(snapshot, "data_feed_stall_seconds")
    batches = sum(v for _, v in
                  _series_by_tags(snapshot, "data_feed_batches_total"))
    if batches or st_count:
        lines.append(f"data feed: {batches:.0f} batches, {st_count} stalls "
                     f"({st_sum * 1e3:.1f} ms waiting)")

    # -- serving ---------------------------------------------------------
    occ = _series_by_tags(snapshot, "serve_llm_batch_occupancy")
    ttft_c, ttft_s = _hist_total(snapshot, "serve_llm_ttft_seconds")
    tpot_c, tpot_s = _hist_total(snapshot, "serve_llm_tpot_seconds")
    req = _series_by_tags(snapshot, "serve_requests_total")
    if occ or ttft_c or req:
        lines.append("serve:")
        if occ:
            lines.append(f"  batch occupancy: "
                         f"{100 * sum(v for _, v in occ) / len(occ):.0f}%")
        waiting = _series_by_tags(snapshot, "serve_llm_waiting_requests")
        if waiting:
            lines.append(f"  waiting: "
                         f"{sum(v for _, v in waiting):.0f} queued")
        if ttft_c:
            lines.append(f"  ttft: {ttft_s / ttft_c * 1e3:.1f} ms avg "
                         f"({ttft_c} requests)")
        if tpot_c:
            lines.append(f"  tpot: {tpot_s / tpot_c * 1e3:.2f} ms/token avg")
        # Observatory phase attribution: where request wall-time goes.
        phases = _series_by_tags(snapshot,
                                 "serve_request_phase_seconds_total")
        if phases:
            total = sum(v for _, v in phases) or 1.0
            top = sorted(phases, key=lambda x: -x[1])[:4]
            lines.append("  phases: " + " ".join(
                f"{t.get('phase', '?')}={100 * v / total:.0f}%"
                for t, v in top
            ))
        hol = sum(v for _, v in _series_by_tags(
            snapshot, "serve_hol_blocked_seconds_total"))
        if hol:
            lines.append(f"  hol blocked: {hol:.3f} slot-seconds")
        if req:
            by_tenant: dict = {}
            for t, v in req:
                key = t.get("tenant", "-")
                by_tenant[key] = by_tenant.get(key, 0) + v
            lines.append("  tenants: " + " ".join(
                f"{k}={v:.0f}" for k, v in sorted(by_tenant.items())
            ))

    # -- preemption / multi-tenancy --------------------------------------
    pre = _series_by_tags(snapshot, "preempt_total")
    active = sum(v for _, v in _series_by_tags(snapshot, "preempt_active"))
    chips = _series_by_tags(snapshot, "tenant_chip_occupancy")
    if pre or active or chips:
        lines.append("preemptions:")
        if active:
            lines.append(f"  active: {active:.0f} draining")
        by_victim: dict = {}
        for t, v in pre:
            key = (t.get("tenant", "-"), t.get("reason", "-"))
            by_victim[key] = by_victim.get(key, 0) + v
        for (tenant, reason), v in sorted(by_victim.items()):
            lines.append(f"  evicted {tenant}: {v:.0f} ({reason})")
        g_count, g_sum = _hist_total(snapshot, "preempt_grace_seconds")
        if g_count:
            lines.append(f"  grace: {g_sum / g_count:.2f} s avg to release "
                         f"({g_count} evictions)")
        if chips:
            lines.append("  chips: " + " ".join(
                f"{t.get('tenant', '-')}={v:.0f}"
                for t, v in sorted(chips, key=lambda x: -x[1])
            ))
    return "\n".join(lines)


def cmd_top(args):
    """Live cluster view: per-rank step times + skew, HBM/object-store
    memory, feed stalls, serving occupancy/latency — everything the
    flight recorder publishes, one screen."""
    from ray_tpu.util.state.api import StateApiClient

    address = _resolve_address(args)
    while True:
        client = StateApiClient(address)
        try:
            snapshot = client.call("metrics_snapshot")["metrics"]
            nodes = client.nodes()
        finally:
            client.close()
        print(_render_top(snapshot, nodes))
        if not args.watch:
            return
        time.sleep(args.interval)
        print()


def cmd_drain(args):
    """Graceful drain (reference: `ray drain-node`): cordon -> wait for
    running work to finish -> remove from the cluster."""
    from ray_tpu.util.state import drain_node

    r = drain_node(args.node_id, timeout=args.timeout, undo=args.undo,
                   address=_resolve_address(args))
    if args.undo:
        if r.get("ok"):
            print("cordon lifted")
            return
        print(f"failed: {r.get('error')}")
        raise SystemExit(1)
    if r.get("ok"):
        print(f"node {args.node_id[:12]} drained and removed")
    else:
        print(f"drain failed: {r.get('error')}")
        raise SystemExit(1)


def cmd_logs(args):
    """List session log files, or tail one (reference: `ray logs`)."""
    from ray_tpu.util.state import get_log, list_logs

    addr = _resolve_address(args)
    if not args.filename:
        for e in list_logs(node_id=args.node, address=addr):
            if "error" in e:
                print(f"{e['node_id'][:12]}  <error: {e['error']}>")
            else:
                print(f"{e['node_id'][:12]}  {e['size']:>10}  {e['name']}")
        return
    print(get_log(args.filename, node_id=args.node,
                  tail_bytes=args.tail, address=addr), end="")


def cmd_stack(args):
    """Live thread stacks of every worker (reference: dashboard py-spy
    on-demand dumps)."""
    from ray_tpu.util.state import get_worker_stacks

    for w in get_worker_stacks(address=_resolve_address(args)):
        if "error" in w:
            print(f"== worker {w.get('worker_id', '?')}: {w['error']}")
            continue
        kind = "actor" if w.get("actor") else "worker"
        print(f"== {kind} pid={w['pid']} node={w['node_id'][:8]}")
        for t in w["threads"]:
            print(f"-- thread {t['thread']}")
            print(t["stack"], end="")


def cmd_job(args):
    from ray_tpu.job import job_cli

    job_cli(args, _resolve_address(args))


def _fetch_serve_signals(address=None):
    """Read the controller-published ServeSignals doc off the GCS KV.

    No actors are dialed — one kv_get against the GCS (the controller
    republishes each serve_signals_interval_s), so this works from any
    machine that can reach the head. None when nothing is published."""
    import json as _json

    from ray_tpu.serve.observatory import SIGNALS_KEY
    from ray_tpu.util.state.api import StateApiClient

    client = StateApiClient(address)
    try:
        raw = client.call(
            "kv_get", {"key": SIGNALS_KEY, "ns": "serve"}
        ).get("value")
    finally:
        client.close()
    if not raw:
        return None
    return _json.loads(raw)


def _render_serve(doc) -> str:
    """ServeSignals -> the `rt serve` table (deployments, replicas,
    latency, phase breakdown, HOL, per-tenant SLO burn)."""
    if not doc or not doc.get("apps"):
        return "no serve signals published (is a serve app running?)"
    age = time.time() - doc.get("ts", 0.0)
    lines = [f"serve signals  seq={doc.get('seq')}  age={age:.1f}s"]
    for name, app in sorted(doc["apps"].items()):
        occ = app.get("occupancy")
        drain = app.get("backlog_drain_s")
        frac = app.get("phase_sum_fraction")
        # Schema v2 fields — absent in docs from older controllers.
        tgt = app.get("target_replicas")
        run = app.get("running_replicas")
        lines.append(
            f"app {name}: qps={app.get('qps', 0.0):.2f} "
            f"waiting={app.get('waiting', 0)}"
            + (f" replicas={run}/{tgt}" if tgt is not None else "")
            + (f" occupancy={100 * occ:.0f}%" if occ is not None else "")
            + (f" backlog_drain={drain:.2f}s" if drain is not None else "")
            + (f" phase_sum={100 * frac:.1f}%" if frac is not None else "")
        )
        kv = app.get("kv")
        if kv:
            hr = kv.get("prefix_hit_rate")
            lines.append(
                f"  kv: pages {kv.get('pages_in_use', 0)}"
                f"/{kv.get('pages_total', 0)}"
                + (f" ({100 * kv['util']:.0f}%)"
                   if kv.get("util") is not None else "")
                + (f" prefix_hit={100 * hr:.0f}%" if hr is not None else "")
                + (f" prefill_skipped={kv['prefill_tokens_skipped']}"
                   if kv.get("prefill_tokens_skipped") else "")
            )
        for r in app.get("replicas") or []:
            status = ("UNREACHABLE" if r.get("unreachable")
                      else f"ongoing={r.get('ongoing')} "
                           f"served={r.get('total_served')}")
            hf = r.get("health_fails", 0)
            ku = r.get("kv_util")
            lines.append(
                f"  replica {r.get('actor_id', '?')[:8]}: {status}"
                + (f" kv={100 * ku:.0f}%" if ku is not None else "")
                + (f" health_fails={hf}" if hf else "")
            )
        ttft, tpot = app.get("ttft_s") or {}, app.get("tpot_s") or {}
        if ttft.get("n"):
            lines.append(
                f"  ttft p50={ttft['p50'] * 1e3:.1f}ms "
                f"p99={ttft['p99'] * 1e3:.1f}ms (n={ttft['n']})  "
                f"tpot p50={tpot.get('p50', 0) * 1e3:.2f}ms "
                f"p99={tpot.get('p99', 0) * 1e3:.2f}ms"
            )
        phases = app.get("phases") or {}
        if phases:
            total = sum(p["sum_s"] for p in phases.values()) or 1.0
            parts = [
                f"{ph}={100 * p['sum_s'] / total:.0f}%"
                for ph, p in sorted(
                    phases.items(), key=lambda kv: -kv[1]["sum_s"]
                )
            ]
            lines.append("  phases: " + " ".join(parts))
        hol = app.get("hol") or {}
        if hol.get("blocked_slot_seconds"):
            lines.append(
                f"  hol: {hol['blocked_slot_seconds']:.3f} "
                f"slot-seconds blocked"
            )
            for ev in (hol.get("events") or [])[-3:]:
                culprits = ", ".join(
                    f"req {c['request_id']} ({c['prompt_tokens']} tok)"
                    for c in ev.get("culprits") or []
                ) or "unknown"
                lines.append(
                    f"    {ev['prefill_s'] * 1e3:.0f}ms prefill stalled "
                    f"{ev['victims']} slot(s) — {culprits}"
                )
        for tname, t in sorted((app.get("tenants") or {}).items()):
            burns = []
            for w, kinds in sorted(t.get("slo_windows", {}).items(),
                                   key=lambda kv: int(kv[0])):
                for kind, row in sorted(kinds.items()):
                    burns.append(
                        f"{kind}@{w}s={row['burn']:.2f}"
                        f"({row['total'] - row['good']}/{row['total']})"
                    )
            lines.append(
                f"  tenant {tname}: req={t.get('requests', 0)} "
                f"tokens={t.get('tokens_in', 0)}/{t.get('tokens_out', 0)}"
                + ("  burn " + " ".join(burns) if burns else "")
            )
    return "\n".join(lines)


def cmd_serve(args):
    """`rt serve [signals]`: live ServeSignals table straight off the
    GCS KV (per-deployment QPS/occupancy/latency, per-tenant SLO burn,
    HOL events). `rt serve deploy <config>`: declarative deploys
    (reference: `serve deploy`, serve/scripts.py:256)."""
    cmdname = args.serve_command or "signals"
    if cmdname == "signals":
        # Read-only path: one GCS kv_get, no rt.init / actor dials.
        address = _resolve_address(args)
        if not getattr(args, "watch", False):
            print(_render_serve(_fetch_serve_signals(address)))
            return
        try:
            while True:
                out = _render_serve(_fetch_serve_signals(address))
                print("\x1b[2J\x1b[H" + out, flush=True)
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return
    import ray_tpu as rt
    from ray_tpu import serve

    rt.init(address=_resolve_address(args), num_cpus=0,
            ignore_reinit_error=True)
    if cmdname == "deploy":
        if not args.config:
            raise SystemExit("rt serve deploy requires a config file path")
        handles = serve.run_from_config(args.config)
        print(f"deployed: {', '.join(handles) or '(nothing)'}")
    elif cmdname == "status":
        import json as _json

        print(_json.dumps(serve.status(), indent=2, default=str))
    elif cmdname == "shutdown":
        serve.shutdown()
        print("serve shut down")


def cmd_loadgen(args):
    """`rt loadgen gen <trace>`: write a seeded-deterministic traffic
    trace (offline — no cluster). `rt loadgen run <trace> --app X`:
    replay it against a deployed serve app and print the
    client<->server latency reconciliation report."""
    from ray_tpu.loadgen import trace as trace_mod
    from ray_tpu.loadgen import workload

    if args.loadgen_command == "gen":
        flash = []
        for f in args.flash:
            parts = f.split(":")
            if len(parts) != 3:
                raise SystemExit(
                    f"--flash wants START:DUR:MULT, got {f!r}")
            flash.append(tuple(float(x) for x in parts))
        curve = workload.RateCurve(
            base_qps=args.qps, ramp_to_qps=args.ramp_to,
            ramp_s=args.ramp_s,
            diurnal_amplitude=args.diurnal_amplitude,
            diurnal_period_s=args.diurnal_period, flash=flash,
        )
        spec = trace_mod.TraceSpec(
            seed=args.seed, duration_s=args.duration, curve=curve,
            kind="closed" if args.closed else "open",
            process=args.process, pareto_alpha=args.pareto_alpha,
            concurrency=args.concurrency, num_requests=args.requests,
            mean_think_s=args.think,
        )
        header, records = trace_mod.generate(spec)
        trace_mod.write(args.trace, header, records)
        print(f"wrote {len(records)} requests to {args.trace} "
              f"({header['kind']} loop, seed {header['seed']})")
        return
    # run
    if not args.app:
        raise SystemExit("rt loadgen run requires --app")
    import ray_tpu as rt
    from ray_tpu import loadgen

    header, records = trace_mod.read(args.trace)
    rt.init(address=_resolve_address(args), num_cpus=0,
            ignore_reinit_error=True)
    call = loadgen.serve_call_fn(args.app, stream=not args.unary)
    result = loadgen.run_trace(header, records, call,
                               workers=args.workers)
    server = loadgen.collect_server_records(args.app)
    report = loadgen.reconcile(result.cards, server)
    summary = result.summary()
    print(json.dumps(summary, indent=2))
    print(loadgen.render_report(report))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump({"run": summary, "reconciliation": report}, f,
                      indent=2)
        print(f"report written to {args.out}")
    if not report["summary"]["gate_pass"]:
        raise SystemExit(1)


def cmd_config(args):
    """List the runtime config registry (the ray_config_def.h analog):
    every knob, its current value, and the RT_* env var that tunes it."""
    from dataclasses import fields

    from ray_tpu._private.config import Config, get_config

    cfg = get_config()
    rows = []
    for f in fields(Config):
        cur = getattr(cfg, f.name)
        default = f.default
        rows.append((f.name, cur, default))
    width = max(len(r[0]) for r in rows)
    for name, cur, default in sorted(rows):
        marker = " *" if cur != default else ""
        print(f"RT_{name.upper():<{width}}  {cur!r}{marker}")
    print(f"\n{len(rows)} knobs; * = overridden from default")


def cmd_up(args):
    """`rt up cluster.yaml` (reference: scripts.py:566 up)."""
    from ray_tpu.autoscaler.launcher import ClusterLauncher

    ClusterLauncher.from_yaml(args.config).up()


def cmd_down(args):
    from ray_tpu.autoscaler.launcher import ClusterLauncher

    ClusterLauncher.from_yaml(args.config).down()


def cmd_exec(args):
    from ray_tpu.autoscaler.launcher import ClusterLauncher

    launcher = ClusterLauncher.from_yaml(args.config)
    for out in launcher.exec(" ".join(args.cmd), all_nodes=args.all_nodes):
        print(out, end="")


def cmd_attach(args):
    """Exec into an interactive shell on the head node."""
    from ray_tpu.autoscaler.launcher import ClusterLauncher

    cmd = ClusterLauncher.from_yaml(args.config).attach_command()
    os.execvp("/bin/sh", ["/bin/sh", "-c", cmd])


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="rt", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("start", help="start cluster services on this host")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", help="existing GCS address (worker nodes)")
    sp.add_argument("--port", type=int, default=0, help="GCS port (head)")
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--resources", help="JSON resource map")
    sp.add_argument("--object-store-memory", type=int)
    sp.add_argument("--block", action="store_true")
    sp.add_argument("--no-dashboard", action="store_true")
    sp.add_argument("--dashboard-port", type=int, default=8265)
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop services started by `rt start`")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("config", help="list runtime config knobs")
    sp.set_defaults(fn=cmd_config)

    sp = sub.add_parser("up", help="launch a cluster from a YAML config")
    sp.add_argument("config")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down a YAML-launched cluster")
    sp.add_argument("config")
    sp.set_defaults(fn=cmd_down)

    sp = sub.add_parser("exec", help="run a command on the cluster head")
    sp.add_argument("config")
    sp.add_argument("--all-nodes", action="store_true")
    sp.add_argument("cmd", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_exec)

    sp = sub.add_parser("attach", help="open a shell on the cluster head")
    sp.add_argument("config")
    sp.set_defaults(fn=cmd_attach)

    sp = sub.add_parser("status", help="cluster resource overview")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser(
        "drain", help="gracefully drain a node (cordon, wait idle, remove)"
    )
    sp.add_argument("node_id", help="node id (hex, from `rt list nodes`)")
    sp.add_argument("--timeout", type=float, default=300.0)
    sp.add_argument("--undo", action="store_true",
                    help="lift the cordon instead of draining")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_drain)

    sp = sub.add_parser("list", help="list cluster entities")
    sp.add_argument(
        "entity",
        choices=["nodes", "tasks", "actors", "objects", "jobs",
                 "placement-groups", "workers"],
    )
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("summary", help="summarize tasks by name/state")
    sp.add_argument("entity", choices=["tasks"])
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser("timeline", help="dump chrome-trace task timeline")
    sp.add_argument("--output", "-o")
    sp.add_argument("--lifecycle", action="store_true",
                    help="include sampled per-phase lifecycle rows")
    sp.add_argument("--cluster", action="store_true",
                    help="render the live merged cluster event spine "
                         "(forces a journal dump) instead of a trace file")
    sp.add_argument("--limit", type=int, default=200,
                    help="max events to render with --cluster")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser(
        "postmortem",
        help="assemble a black-box bundle into a causal timeline",
    )
    sp.add_argument("bundle", nargs="?", default="latest",
                    help="bundle directory (default: newest)")
    sp.add_argument("--chain-only", action="store_true",
                    help="print only the culprit chain")
    sp.add_argument("--limit", type=int, default=0,
                    help="max timeline events to render (0 = all)")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_postmortem)

    sp = sub.add_parser(
        "profile", help="sampled task-lifecycle profiler (control plane)"
    )
    sp.add_argument("profile_command", nargs="?", choices=["tasks"],
                    help="tasks: per-phase latency breakdown")
    sp.add_argument("--on", action="store_true",
                    help="enable sampling cluster-wide")
    sp.add_argument("--off", action="store_true", help="disable sampling")
    sp.add_argument("--rate", type=float,
                    help="sample probability 0..1 (with --on; default 1)")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_profile)

    sp = sub.add_parser("rpc", help="per-method GCS RPC latency accounting")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_rpc)

    sp = sub.add_parser("trace", help="print one trace's span tree")
    sp.add_argument("trace_id")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser("logs", help="list or tail session log files")
    sp.add_argument("filename", nargs="?", help="log file to tail")
    sp.add_argument("--node", help="node id (hex) to query")
    sp.add_argument("--tail", type=int, default=64 * 1024,
                    help="bytes from the end of the file")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_logs)

    sp = sub.add_parser("stack", help="dump live worker thread stacks")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_stack)

    sp = sub.add_parser("memory", help="object store usage by object")
    sp.add_argument("--devices", action="store_true",
                    help="unified HBM + object-store view per device/node")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser(
        "top", help="live cluster view: step times, skew, memory, serving"
    )
    sp.add_argument("--watch", action="store_true",
                    help="refresh continuously instead of one shot")
    sp.add_argument("--interval", type=float, default=2.0,
                    help="refresh period with --watch (seconds)")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_top)

    sp = sub.add_parser(
        "serve",
        help="serve observability (signals) and declarative deploys",
    )
    sp.add_argument(
        "serve_command", nargs="?",
        choices=["signals", "deploy", "status", "shutdown"],
        help="default: signals (live ServeSignals table off the GCS)",
    )
    sp.add_argument("config", nargs="?", help="JSON/YAML app config")
    sp.add_argument("--watch", action="store_true",
                    help="refresh the signals table continuously")
    sp.add_argument("--interval", type=float, default=2.0,
                    help="refresh period with --watch (seconds)")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("job", help="submit and manage jobs")
    sp.add_argument("job_command",
                    choices=["submit", "status", "logs", "list", "stop"])
    sp.add_argument("args", nargs=argparse.REMAINDER)
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_job)

    sp = sub.add_parser(
        "loadgen",
        help="macro traffic harness: generate/replay traces, reconcile "
             "client vs server latency",
    )
    sp.add_argument("loadgen_command", choices=["gen", "run"])
    sp.add_argument("trace", help="trace file (JSONL) to write or replay")
    # gen knobs (offline; no cluster needed)
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--duration", type=float, default=30.0,
                    help="open-loop trace length in seconds")
    sp.add_argument("--qps", type=float, default=20.0,
                    help="base offered rate")
    sp.add_argument("--ramp-to", type=float, default=None,
                    help="ramp linearly from --qps to this rate")
    sp.add_argument("--ramp-s", type=float, default=0.0,
                    help="ramp duration (seconds)")
    sp.add_argument("--diurnal-amplitude", type=float, default=0.0)
    sp.add_argument("--diurnal-period", type=float, default=86400.0)
    sp.add_argument("--flash", action="append", default=[],
                    metavar="START:DUR:MULT",
                    help="flash-crowd window (repeatable)")
    sp.add_argument("--process", choices=["poisson", "pareto"],
                    default="poisson")
    sp.add_argument("--pareto-alpha", type=float, default=1.5)
    sp.add_argument("--closed", action="store_true",
                    help="closed-loop trace (bounded concurrency)")
    sp.add_argument("--concurrency", type=int, default=8)
    sp.add_argument("--requests", type=int, default=0,
                    help="closed-loop request count")
    sp.add_argument("--think", type=float, default=0.0,
                    help="closed-loop mean think time (seconds)")
    # run knobs
    sp.add_argument("--app", help="deployed serve app to drive")
    sp.add_argument("--workers", type=int, default=64,
                    help="open-loop dispatch pool size")
    sp.add_argument("--unary", action="store_true",
                    help="unary calls instead of streaming")
    sp.add_argument("--out", help="write the reconciliation report "
                                  "(JSON) here")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_loadgen)

    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
