"""Job submission SDK + CLI glue.

Analog of the reference's job submission stack (dashboard/modules/job/:
``JobSubmissionClient.submit_job`` sdk.py:39,129, ``JobManager``
job_manager.py:525, per-job ``JobSupervisor`` actor :140, CLI
``ray job submit``). Here the GCS keeps the job table and the head raylet
acts as supervisor: it spawns the detached driver subprocess, streams its
stdout/stderr back to the GCS, and reports terminal state — so a submitted
job outlives the submitting client.
"""

from __future__ import annotations

import sys
import time
from typing import Dict, List, Optional

from ray_tpu._private.node import EventLoopThread
from ray_tpu._private.protocol import connect

TERMINAL_STATES = ("SUCCEEDED", "FAILED", "STOPPED")


class JobSubmissionClient:
    """Lightweight GCS dialer — no raylet or object store needed."""

    def __init__(self, address: Optional[str] = None):
        import os

        if address is None:
            address = os.environ.get("RT_GCS_ADDR")
        if address is None:
            raise RuntimeError("pass address='host:port' or set RT_GCS_ADDR")
        address = address.removeprefix("rt://").removeprefix("http://")
        host, port = address.rsplit(":", 1)
        self._io = EventLoopThread("rt-job")
        self._conn = self._run(connect(host, int(port)))

    def _run(self, coro, timeout=30.0):
        import asyncio

        return asyncio.run_coroutine_threadsafe(coro, self._io.loop).result(timeout)

    def close(self):
        try:
            self._run(self._conn.close(), timeout=5)
        except Exception:
            pass
        self._io.stop()

    def _prepare_job_runtime_env(self, renv: Optional[Dict]) -> Optional[Dict]:
        """Resolve the job env exactly like task envs (upload working_dir /
        py_modules to the GCS KV) so the supervisor and the job's own
        workers can materialize it."""
        if not renv:
            return None
        from ray_tpu.runtime_env.runtime_env import (
            GcsKvAdapter,
            prepare_runtime_env,
        )

        kv = GcsKvAdapter(self._conn, self._io.loop)
        return prepare_runtime_env(renv, kv)

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[Dict] = None,
        metadata: Optional[Dict[str, str]] = None,
    ) -> str:
        runtime_env = self._prepare_job_runtime_env(runtime_env)
        r = self._run(
            self._conn.call(
                "submit_job",
                {
                    "entrypoint": entrypoint,
                    "submission_id": submission_id,
                    "runtime_env": runtime_env,
                    "metadata": metadata,
                },
            )
        )
        if not r.get("ok"):
            raise RuntimeError(r.get("error", "job submission failed"))
        return r["submission_id"]

    def get_job_status(self, submission_id: str) -> str:
        job = self.get_job_info(submission_id)
        return job["state"]

    def get_job_info(self, submission_id: str) -> dict:
        r = self._run(self._conn.call("get_job", {"submission_id": submission_id}))
        if r["job"] is None:
            raise RuntimeError(f"no such job: {submission_id}")
        job = dict(r["job"])
        for k in ("job_id", "node_id"):
            if isinstance(job.get(k), (bytes, bytearray)):
                job[k] = job[k].hex()
        return job

    def get_job_logs(self, submission_id: str) -> str:
        r = self._run(self._conn.call("job_logs", {"submission_id": submission_id}))
        if r["logs"] is None:
            raise RuntimeError(f"no such job: {submission_id}")
        return r["logs"]

    def list_jobs(self) -> List[dict]:
        jobs = self._run(self._conn.call("list_jobs", {}))["jobs"]
        out = []
        for j in jobs:
            j = dict(j)
            for k in ("job_id", "node_id"):
                if isinstance(j.get(k), (bytes, bytearray)):
                    j[k] = j[k].hex()
            out.append(j)
        return out

    def stop_job(self, submission_id: str) -> bool:
        r = self._run(self._conn.call("stop_job", {"submission_id": submission_id}))
        return bool(r.get("ok"))

    def wait_until_finished(
        self, submission_id: str, timeout: float = 300.0, poll: float = 0.25
    ) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            state = self.get_job_status(submission_id)
            if state in TERMINAL_STATES:
                return state
            time.sleep(poll)
        raise TimeoutError(f"job {submission_id} still {state} after {timeout}s")


def job_cli(args, address: str):
    """Back end of `rt job ...` (scripts/scripts.py)."""
    client = JobSubmissionClient(address)
    try:
        rest = [a for a in args.args if a != "--"]
        cmd = args.job_command
        if cmd == "submit":
            if not rest:
                sys.exit("usage: rt job submit -- <entrypoint command>")
            sid = client.submit_job(entrypoint=" ".join(rest))
            print(f"submitted {sid}")
        elif cmd == "status":
            print(client.get_job_status(rest[0]))
        elif cmd == "logs":
            print(client.get_job_logs(rest[0]), end="")
        elif cmd == "list":
            for j in client.list_jobs():
                sid = j.get("submission_id") or j["job_id"][:12]
                print(f"{sid}\t{j['state']}\t{j.get('entrypoint', '')}")
        elif cmd == "stop":
            ok = client.stop_job(rest[0])
            print("stopped" if ok else "stop failed")
    finally:
        client.close()
