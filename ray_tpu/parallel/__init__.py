"""Parallelism primitives: mesh management, sharding rules, ring attention,
sequence parallelism, pipeline parallelism, expert parallelism.

This package supplies natively what the reference delegates to user
libraries (SURVEY.md §2.4: TP "not implemented in Ray itself", PP "not
implemented", SP/CP "absent", EP "absent") — the idiomatic TPU route: one
jax.Mesh over the pod slice, GSPMD sharding annotations for DP/FSDP/TP,
shard_map + ppermute ring attention for context parallelism, all-to-all
resharding (Ulysses) as the alternative SP mode, lax.scan pipelining for
PP, and capacity-based top-k routing for EP.
"""

from ray_tpu.parallel.mesh import (
    MeshConfig,
    build_mesh,
    logical_to_physical,
    shard_params,
    with_sharding_constraint,
)
from ray_tpu.parallel.ring_attention import ring_attention
from ray_tpu.parallel.ulysses import ulysses_attention
from ray_tpu.parallel.pipeline import pipeline_stages
from ray_tpu.parallel.moe import moe_layer, top_k_routing

__all__ = [
    "MeshConfig",
    "build_mesh",
    "logical_to_physical",
    "shard_params",
    "with_sharding_constraint",
    "ring_attention",
    "ulysses_attention",
    "pipeline_stages",
    "moe_layer",
    "top_k_routing",
]
