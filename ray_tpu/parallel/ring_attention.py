"""Ring attention: context parallelism over a mesh axis.

Long-context attention where the sequence is sharded over the "sp" mesh
axis. Each device holds a query block; key/value blocks rotate around the
ring via `jax.lax.ppermute` (XLA lowers this to ICI neighbor transfers that
overlap with the attention compute), and softmax is accumulated online
(flash-attention style running max/denominator) so the result is exact.

The reference has no analog (SURVEY.md §2.4: SP/CP/ring attention
"Absent"); this is new TPU-native capability. Technique: Liu et al., "Ring
Attention with Blockwise Transformers" (arXiv:2310.01889), re-implemented
from the paper for shard_map.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu._private.jax_compat import shard_map

NEG_INF = -1e30


def _block_attn(q, k, v, bias):
    """Scores for one (q-block, kv-block) pair. q:[B,Lq,H,D] k,v:[B,Lk,H,D]"""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    return s


def _online_update(carry, s, v):
    """Online-softmax accumulate one kv block (flash attention recurrence)."""
    o, m, l = carry  # o:[B,H,Lq,D] m,l:[B,H,Lq]
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])  # [B,H,Lq,Lk]
    correction = jnp.exp(m - m_new)
    l_new = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bhqd", p, v)
    o_new = o * correction[..., None] + pv
    return o_new, m_new, l_new


def _causal_bias(q_idx, k_idx, block_q, block_k, dtype):
    """Bias for a q-block at ring position q_idx vs kv-block at k_idx.

    Global positions: q in [q_idx*block_q, ...), k in [k_idx*block_k, ...).
    """
    q_pos = q_idx * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_pos = k_idx * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return jnp.where(q_pos >= k_pos, 0.0, NEG_INF).astype(dtype)[None, None]


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = True,
    query_spec: P = None,
):
    """Exact attention with the sequence sharded over `axis_name`.

    Args:
      q, k, v: [batch, seq, heads, head_dim], seq sharded over `axis_name`.
      mesh: the device mesh containing `axis_name`.
      causal: apply causal masking using *global* positions.

    Returns [batch, seq, heads, head_dim] with the same sharding as q.
    """
    axis_size = mesh.shape[axis_name]
    if query_spec is None:
        query_spec = P(None, axis_name, None, None)

    def local_fn(q_blk, k_blk, v_blk):
        # q_blk: [B, Lq_local, H, D] — this device's query block.
        my_idx = jax.lax.axis_index(axis_name)
        block_q = q_blk.shape[1]
        block_k = k_blk.shape[1]
        b, _, h, d = q_blk.shape

        o = jnp.zeros((b, h, block_q, d), dtype=jnp.float32)
        m = jnp.full((b, h, block_q), NEG_INF, dtype=jnp.float32)
        l = jnp.zeros((b, h, block_q), dtype=jnp.float32)

        def step(i, carry):
            o, m, l, k_cur, v_cur = carry
            # kv block currently held arrived from ring position my_idx - i.
            k_idx = (my_idx - i) % axis_size

            def attend(carry):
                o, m, l = carry
                if causal:
                    bias = _causal_bias(
                        my_idx, k_idx, block_q, block_k, jnp.float32
                    )
                else:
                    bias = None
                s = _block_attn(
                    q_blk.astype(jnp.float32),
                    k_cur.astype(jnp.float32),
                    v_cur.astype(jnp.float32),
                    bias,
                )
                return _online_update((o, m, l), s, v_cur.astype(jnp.float32))

            if causal:
                # Blocks entirely in the future (k_idx > my_idx) are fully
                # masked: skip their matmuls outright — on a causal ring
                # each device computes only ~half the steps instead of
                # materializing -inf scores for the rest.
                o, m, l = jax.lax.cond(
                    k_idx <= my_idx, attend, lambda c: c, (o, m, l)
                )
            else:
                o, m, l = attend((o, m, l))
            # Rotate kv to the right neighbor; overlapped with next step's
            # compute by XLA latency hiding.
            perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            return o, m, l, k_nxt, v_nxt

        o, m, l, _, _ = jax.lax.fori_loop(
            0, axis_size, step, (o, m, l, k_blk, v_blk)
        )
        out = o / jnp.maximum(l[..., None], 1e-20)
        return out.transpose(0, 2, 1, 3).astype(q_blk.dtype)  # [B,Lq,H,D]

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(query_spec, query_spec, query_spec),
        out_specs=query_spec,
        check_vma=False,
    )(q, k, v)


def reference_attention(q, k, v, causal: bool = True):
    """Unsharded reference for testing parity."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)
