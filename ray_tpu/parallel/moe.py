"""Mixture-of-experts with expert parallelism.

The reference has no MoE/EP support (SURVEY.md §2.4: EP "Absent"). This is
the TPU-native design: experts shard over the "ep" mesh axis; tokens are
routed top-k with a capacity factor and dispatched via einsum against
one-hot combine tensors (the Switch/GShard formulation), which XLA lowers
to all-to-alls over ICI when the expert dim is sharded.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def top_k_routing(
    router_logits: jax.Array,  # [tokens, num_experts]
    k: int,
    capacity: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k token->expert assignment with per-expert capacity.

    Returns:
      dispatch: [tokens, num_experts, capacity] one-hot dispatch mask
      combine:  [tokens, num_experts, capacity] combine weights
      aux_loss: load-balancing auxiliary loss (Switch-style)
    """
    tokens, num_experts = router_logits.shape
    probs = jax.nn.softmax(router_logits, axis=-1)

    # Load-balance loss: mean prob * mean assignment fraction per expert.
    top1 = jnp.argmax(probs, axis=-1)
    me = probs.mean(axis=0)
    ce = jnp.mean(jax.nn.one_hot(top1, num_experts), axis=0)
    aux_loss = num_experts * jnp.sum(me * ce)

    dispatch = jnp.zeros((tokens, num_experts, capacity), dtype=probs.dtype)
    combine = jnp.zeros((tokens, num_experts, capacity), dtype=probs.dtype)
    remaining = probs
    # Track how many slots each expert has filled so far across the k picks.
    fill = jnp.zeros((num_experts,), dtype=jnp.int32)
    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)  # [tokens]
        gate = jnp.take_along_axis(remaining, choice[:, None], axis=-1)[:, 0]
        onehot = jax.nn.one_hot(choice, num_experts, dtype=jnp.int32)
        # Position of each token within its chosen expert's queue.
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1) * onehot
        pos = (pos_in_expert.sum(axis=-1) + fill[choice]).astype(jnp.int32)
        keep = pos < capacity
        pos = jnp.clip(pos, 0, capacity - 1)
        tok_idx = jnp.arange(tokens)
        dispatch = dispatch.at[tok_idx, choice, pos].add(
            keep.astype(probs.dtype)
        )
        combine = combine.at[tok_idx, choice, pos].add(
            keep.astype(probs.dtype) * gate
        )
        fill = fill + (onehot * keep[:, None]).sum(axis=0)
        # Mask out the chosen expert for the next pick.
        remaining = remaining * (1.0 - onehot.astype(probs.dtype))
    return dispatch, combine, aux_loss


def moe_layer(
    x: jax.Array,  # [tokens, d_model]
    router_w: jax.Array,  # [d_model, num_experts]
    expert_fn: Callable,  # (expert_params, [num_experts, capacity, d]) -> same
    expert_params,  # leaves with leading num_experts axis (sharded over "ep")
    k: int = 2,
    capacity_factor: float = 1.25,
):
    """Dense-dispatch MoE layer (GShard formulation).

    The einsum dispatch produces [num_experts, capacity, d_model]; with
    expert_params sharded over "ep", XLA inserts the all-to-alls.
    """
    tokens, d_model = x.shape
    num_experts = router_w.shape[-1]
    capacity = max(1, int(capacity_factor * tokens * k / num_experts))

    logits = x @ router_w
    dispatch, combine, aux_loss = top_k_routing(logits, k, capacity)

    # Dispatch: [E, C, D]
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)
    expert_out = expert_fn(expert_params, expert_in)
    # Combine: [T, D]
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out, aux_loss
