"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

The reference leaves PP unimplemented (SURVEY.md §2.4: "the compiled-DAG
substrate is the intended future home"). The TPU-native design runs all
pipeline stages inside ONE compiled program: stage weights are sharded over
the "pp" mesh axis, microbatches stream through a lax.scan whose body runs
every stage in parallel (on different devices) and rotates activations to
the next stage with ppermute — the standard JAX SPMD pipelining pattern
(cf. the public scaling-book / praxis approach, re-derived here).

Schedule: with S stages and M microbatches the scan runs S+M-1 ticks;
stage s is active on ticks [s, s+M). Bubble fraction (S-1)/(S+M-1).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu._private.jax_compat import shard_map


def pipeline_stages(
    stage_fn: Callable,
    params_stacked,
    x_microbatches: jax.Array,
    mesh: Mesh,
    axis_name: str = "pp",
    params_spec: P = None,
    x_spec: P = None,
):
    """Run a stage-stacked pipeline.

    Args:
      stage_fn: (stage_params, activation) -> activation. One stage's
        compute. `stage_params` is the DEVICE-LOCAL shard of
        `params_stacked`: leaves keep a leading axis of layers-per-stage
        (stack_len / S), so a stage holding several transformer layers
        scans over them inside stage_fn.
      params_stacked: pytree whose leaves have a leading stack axis
        divisible by S, sharded over `axis_name`.
      x_microbatches: [M, microbatch, ...] input microbatches (replicated
        over the pp axis).
      mesh: mesh with the `axis_name` axis of size S.

    Returns [M, microbatch, ...] outputs of the final stage. Differentiable
    (the tick loop has static bounds, so it lowers to scan).
    """
    S = mesh.shape[axis_name]
    M = x_microbatches.shape[0]
    if params_spec is None:
        params_spec = P(axis_name)
    if x_spec is None:
        x_spec = P()

    def local_fn(params_local, xs):
        # params_local: leaves [stack/S, ...] (this device's stage layers);
        # xs: [M, mb, ...]
        stage_params = params_local
        stage_idx = jax.lax.axis_index(axis_name)
        total_ticks = S + M - 1

        buf_shape = xs.shape[1:]
        state = jnp.zeros(buf_shape, dtype=xs.dtype)  # current activation
        outputs = jnp.zeros_like(xs)

        def tick(t, carry):
            state, outputs = carry
            # Stage 0 ingests microbatch t (when valid); others take the
            # activation rotated from the previous stage.
            mb_idx = jnp.clip(t, 0, M - 1)
            injected = jnp.where(
                (stage_idx == 0) & (t < M), xs[mb_idx], state
            )
            out = stage_fn(stage_params, injected)
            # Last stage emits microbatch t - (S-1).
            emit_idx = t - (S - 1)
            valid_emit = (stage_idx == S - 1) & (emit_idx >= 0)
            outputs = jax.lax.cond(
                valid_emit,
                lambda o: o.at[jnp.clip(emit_idx, 0, M - 1)].set(out),
                lambda o: o,
                outputs,
            )
            # Rotate activations forward: stage s -> s+1 (last wraps to 0,
            # its payload is ignored by the injection select above).
            perm = [(j, (j + 1) % S) for j in range(S)]
            state = jax.lax.ppermute(out, axis_name, perm)
            return state, outputs

        _, outputs = jax.lax.fori_loop(0, total_ticks, tick, (state, outputs))
        # Only the last stage holds real outputs; broadcast them to all
        # pp ranks so the caller sees replicated results.
        outputs = jax.lax.all_gather(outputs, axis_name)[S - 1]
        return outputs

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(params_spec, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(params_stacked, x_microbatches)
