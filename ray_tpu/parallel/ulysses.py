"""Ulysses-style sequence parallelism: all-to-all head/sequence reshard.

Alternative SP mode to ring attention (SURVEY.md §5 "long-context /
sequence parallelism ... an Ulysses-style all-to-all head/sequence reshard
as an alternative mode"): activations arrive sequence-sharded; an
all-to-all converts them to head-sharded with full sequence, plain (flash)
attention runs locally, and a second all-to-all converts back.

Technique: Jacobs et al., "DeepSpeed Ulysses" (arXiv:2309.14509),
re-implemented with jax all_to_all over a mesh axis. Best when
heads >= sp_size; ring attention wins when sequence far exceeds what
all-to-all bandwidth tolerates.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu._private.jax_compat import shard_map


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = True,
    attn_fn=None,
    query_spec: P = None,
):
    """Attention with seq sharded over `axis_name` via all-to-all reshard.

    q, k, v: [batch, seq(sharded), heads, head_dim]. heads must be
    divisible by the axis size.
    """
    axis_size = mesh.shape[axis_name]
    if query_spec is None:
        query_spec = P(None, axis_name, None, None)
    if attn_fn is None:
        from ray_tpu.parallel.ring_attention import reference_attention

        attn_fn = reference_attention

    def local_fn(q_blk, k_blk, v_blk):
        # [B, L/n, H, D] -> all-to-all -> [B, L, H/n, D]
        def scatter_heads(x):
            return jax.lax.all_to_all(
                x, axis_name, split_axis=2, concat_axis=1, tiled=True
            )

        def gather_heads(x):
            return jax.lax.all_to_all(
                x, axis_name, split_axis=1, concat_axis=2, tiled=True
            )

        qh, kh, vh = scatter_heads(q_blk), scatter_heads(k_blk), scatter_heads(v_blk)
        out = attn_fn(qh, kh, vh, causal=causal)  # [B, L, H/n, D]
        return gather_heads(out)  # [B, L/n, H, D]

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(query_spec, query_spec, query_spec),
        out_specs=query_spec,
        check_vma=False,
    )(q, k, v)
