"""Device mesh construction and sharding rules.

The scaling-book recipe: pick a mesh, annotate shardings on model/optimizer
pytrees with logical axis names, let GSPMD insert the collectives, profile,
iterate. Axis conventions:

  * "dp"   — pure data parallelism (replicated params, sharded batch)
  * "fsdp" — data parallelism with parameter sharding (ZeRO-3 style:
             XLA all-gathers params per layer, reduce-scatters grads)
  * "tp"   — tensor (megatron-style) parallelism over hidden/head dims
  * "sp"   — sequence/context parallelism (ring attention axis)
  * "pp"   — pipeline stages
  * "ep"   — expert parallelism for MoE

The reference has no analog (its TP/PP/SP rows are empty, SURVEY.md §2.4);
this module is the TPU-native replacement for what DeepSpeed/Megatron do in
the CUDA world.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MESH_AXES = ("dp", "fsdp", "tp", "sp", "pp", "ep")


@dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. Axes of size 1 are kept (GSPMD treats them as
    no-ops) so sharding rules never need to special-case missing axes."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.fsdp * self.tp * self.sp * self.pp * self.ep

    def axis_sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in MESH_AXES}

    @staticmethod
    def for_devices(n: int, tp: int = 1, sp: int = 1, pp: int = 1, ep: int = 1,
                    pure_dp: int = 1) -> "MeshConfig":
        """FSDP-first factorization: whatever is not spent on tp/sp/pp/ep/dp
        becomes the fsdp axis (the usual TPU default)."""
        rest = n // (tp * sp * pp * ep * pure_dp)
        if rest * tp * sp * pp * ep * pure_dp != n:
            raise ValueError(
                f"cannot factor {n} devices into dp={pure_dp} tp={tp} sp={sp} "
                f"pp={pp} ep={ep}"
            )
        return MeshConfig(dp=pure_dp, fsdp=rest, tp=tp, sp=sp, pp=pp, ep=ep)


def build_mesh(config: MeshConfig, devices: Optional[Sequence] = None) -> Mesh:
    """Build a jax.sharding.Mesh with the canonical axis order.

    Axis order puts "tp" and "sp" innermost so they map to the
    fastest/nearest ICI links on real TPU topologies (tensor-parallel
    collectives are the most latency-sensitive), and "dp"/"pp" outermost
    (they tolerate DCN).
    """
    if devices is None:
        devices = jax.devices()
    n = config.num_devices
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(
        config.dp, config.pp, config.ep, config.fsdp, config.sp, config.tp
    )
    # Mesh axis names must match the reshape order above.
    return Mesh(arr, axis_names=("dp", "pp", "ep", "fsdp", "sp", "tp"))


# ---------------------------------------------------------------------------
# Logical axis rules (flax-style rules table, but self-contained)
# ---------------------------------------------------------------------------

# Logical activation/parameter axis -> mesh axes.
DEFAULT_RULES: Dict[str, Optional[Tuple[str, ...]]] = {
    # activations
    "batch": ("dp", "fsdp"),
    "seq": ("sp",),
    "act_embed": None,
    "act_heads": ("tp",),
    "act_mlp": ("tp",),
    # params
    "embed": ("fsdp",),      # ZeRO-3: shard the non-tp dim over fsdp
    "mlp": ("tp",),
    "heads": ("tp",),
    "kv": None,
    "qkv_embed": ("fsdp",),
    "vocab": ("tp",),
    "expert": ("ep",),
    "stage": ("pp",),
    "norm": None,
}


def logical_to_physical(logical_axes: Sequence[Optional[str]],
                        rules: Optional[Dict] = None) -> P:
    """Map a tuple of logical axis names to a PartitionSpec."""
    rules = rules or DEFAULT_RULES
    spec = []
    used: set = set()
    for name in logical_axes:
        if name is None:
            spec.append(None)
            continue
        axes = rules.get(name)
        if axes is None:
            spec.append(None)
            continue
        axes = tuple(a for a in axes if a not in used)
        used.update(axes)
        if not axes:
            spec.append(None)
        elif len(axes) == 1:
            spec.append(axes[0])
        else:
            spec.append(axes)
    return P(*spec)


def shard_params(params, logical_axes_tree, mesh: Mesh, rules=None):
    """Device-put a parameter pytree according to its logical axes tree.

    `logical_axes_tree` mirrors `params` with tuples of logical names (or
    None for replicated). This is the explicit analog of flax's
    `nn.with_partitioning` + `logical_to_mesh`.
    """
    def place(leaf, axes):
        if axes is None:
            sharding = NamedSharding(mesh, P())
        else:
            sharding = NamedSharding(mesh, logical_to_physical(axes, rules))
        return jax.device_put(leaf, sharding)

    return jax.tree.map(place, params, logical_axes_tree,
                        is_leaf=lambda x: x is None)


def with_sharding_constraint(x, logical_axes, mesh: Optional[Mesh] = None,
                             rules=None):
    """Annotate an intermediate activation inside jit.

    Uses the ambient mesh when available (inside `jax.sharding.use_mesh` or
    shard_map); falls back to unconstrained outside.
    """
    spec = logical_to_physical(logical_axes, rules)
    try:
        return jax.lax.with_sharding_constraint(x, spec) if mesh is None else (
            jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
        )
    except (ValueError, RuntimeError):
        return x
