"""Runtime context introspection (reference: python/ray/runtime_context.py)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from ray_tpu._private import worker as worker_mod


@dataclass
class RuntimeContext:
    job_id: Optional[str]
    node_id: Optional[str]
    worker_mode: Optional[str]

    def get_job_id(self) -> Optional[str]:
        return self.job_id

    def get_node_id(self) -> Optional[str]:
        return self.node_id

    def get_assigned_resources(self) -> Dict[str, float]:
        client = worker_mod.get_client()
        if hasattr(client, "cluster_resources"):
            return client.cluster_resources()
        return {}


def get_runtime_context() -> RuntimeContext:
    client = worker_mod.get_client()
    job_id = getattr(client, "job_id", None)
    node_id = getattr(client, "node_id", None)
    return RuntimeContext(
        job_id=job_id.hex() if job_id is not None and hasattr(job_id, "hex") else None,
        node_id=node_id.hex() if isinstance(node_id, bytes) else None,
        worker_mode=worker_mod.get_mode(),
    )
