"""RMSNorm with a fused Pallas TPU kernel and jnp fallback.

The jnp path carries a custom VJP that recomputes the normalizer in the
backward pass instead of saving activations (a rematerialization the
XLA fuser sometimes misses across the scale multiply).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _rmsnorm_ref(x, w, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    return (x.astype(jnp.float32) * inv * w.astype(jnp.float32)).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rmsnorm_xla(x, w, eps):
    return _rmsnorm_ref(x, w, eps)


def _fwd(x, w, eps):
    return _rmsnorm_ref(x, w, eps), (x, w)


def _bwd(eps, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = xf * inv
    dw = jnp.sum(gf * xhat, axis=tuple(range(x.ndim - 1))).astype(w.dtype)
    gw = gf * wf
    dx = inv * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    return dx.astype(x.dtype), dw


_rmsnorm_xla.defvjp(_fwd, _bwd)


def _rmsnorm_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    o_ref[:] = (x * inv * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rmsnorm_bwd_kernel(x_ref, w_ref, g_ref, dx_ref, dw_ref, *, eps: float):
    """Row-local dx plus dw accumulated across the sequential TPU grid.

    The normalizer is recomputed from x (rematerialized, as the fwd kernel
    saves nothing), so the backward reads the same inputs as the forward.
    dw_ref is one (8, d) block every grid step revisits: row 0 accumulates,
    rows 1-7 pad the block up to the fp32 sublane tile.
    """
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = x * inv
    gw = g * w
    dx = inv * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    dx_ref[:] = dx.astype(dx_ref.dtype)
    part = jnp.pad(jnp.sum(g * xhat, axis=0, keepdims=True), ((0, 7), (0, 0)))

    @pl.when(i == 0)
    def _init():
        dw_ref[:] = part

    @pl.when(i != 0)
    def _acc():
        dw_ref[:] = dw_ref[:] + part


def _rmsnorm_pallas_fwd2(x2, w, eps, block_rows, interpret):
    from jax.experimental import pallas as pl

    rows, d = x2.shape
    block_rows = min(block_rows, rows)
    return pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        grid=(pl.cdiv(rows, block_rows),),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x2.dtype),
        interpret=interpret,
    )(x2, w.reshape(1, d))


def _rmsnorm_pallas_bwd2(x2, w, g2, eps, block_rows, interpret):
    from jax.experimental import pallas as pl

    rows, d = x2.shape
    block_rows = min(block_rows, rows)
    nblocks = -(-rows // block_rows)
    # Zero-pad a partial tail block: padded rows give g*xhat = 0, so the
    # dw accumulator adds defined zeros instead of out-of-bounds garbage
    # (real-TPU OOB block contents are undefined).
    rows_pad = nblocks * block_rows
    if rows_pad != rows:
        x2 = jnp.pad(x2, ((0, rows_pad - rows), (0, 0)))
        g2 = jnp.pad(g2, ((0, rows_pad - rows), (0, 0)))
    dx, dw_acc = pl.pallas_call(
        functools.partial(_rmsnorm_bwd_kernel, eps=eps),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((8, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows_pad, d), x2.dtype),
            jax.ShapeDtypeStruct((8, d), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w.reshape(1, d), g2)
    return dx[:rows], dw_acc.sum(axis=0).astype(w.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _rmsnorm_pallas_core(x2, w, eps, block_rows, interpret):
    return _rmsnorm_pallas_fwd2(x2, w, eps, block_rows, interpret)


def _pallas_core_fwd(x2, w, eps, block_rows, interpret):
    return _rmsnorm_pallas_fwd2(x2, w, eps, block_rows, interpret), (x2, w)


def _pallas_core_bwd(eps, block_rows, interpret, res, g):
    x2, w = res
    return _rmsnorm_pallas_bwd2(x2, w, g, eps, block_rows, interpret)


_rmsnorm_pallas_core.defvjp(_pallas_core_fwd, _pallas_core_bwd)


def _rmsnorm_pallas(x, w, eps, block_rows: int = 256, interpret: bool = False):
    orig_shape = x.shape
    d = x.shape[-1]
    rows = int(np_prod(orig_shape[:-1]))  # rtlint: disable=RT001 — static shape math: fine at trace time
    out = _rmsnorm_pallas_core(x.reshape(rows, d), w, eps, block_rows, interpret)
    return out.reshape(orig_shape)


def np_prod(shape):
    out = 1
    for s in shape:
        out *= s
    return out


def rmsnorm(x: jax.Array, w: jax.Array, eps: float = 1e-6,
            use_pallas: Optional[bool] = None, interpret: bool = False):
    """RMS normalization over the last axis, scaled by w."""
    if use_pallas is None:
        try:
            use_pallas = jax.devices()[0].platform == "tpu"
        except Exception:  # noqa: BLE001  # rtlint: disable=RT007 — backend probe: no TPU visible means fall back to XLA path
            use_pallas = False
    if (use_pallas or interpret):
        return _rmsnorm_pallas(x, w, eps, interpret=interpret)
    return _rmsnorm_xla(x, w, eps)
