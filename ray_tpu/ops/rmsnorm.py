"""RMSNorm with a fused Pallas TPU kernel and jnp fallback.

The jnp path carries a custom VJP that recomputes the normalizer in the
backward pass instead of saving activations (a rematerialization the
XLA fuser sometimes misses across the scale multiply).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _rmsnorm_ref(x, w, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    return (x.astype(jnp.float32) * inv * w.astype(jnp.float32)).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rmsnorm_xla(x, w, eps):
    return _rmsnorm_ref(x, w, eps)


def _fwd(x, w, eps):
    return _rmsnorm_ref(x, w, eps), (x, w)


def _bwd(eps, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = xf * inv
    dw = jnp.sum(gf * xhat, axis=tuple(range(x.ndim - 1))).astype(w.dtype)
    gw = gf * wf
    dx = inv * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    return dx.astype(x.dtype), dw


_rmsnorm_xla.defvjp(_fwd, _bwd)


def _rmsnorm_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    o_ref[:] = (x * inv * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rmsnorm_pallas(x, w, eps, block_rows: int = 256, interpret: bool = False):
    from jax.experimental import pallas as pl

    orig_shape = x.shape
    d = x.shape[-1]
    rows = int(np_prod(orig_shape[:-1]))
    x2 = x.reshape(rows, d)
    block_rows = min(block_rows, rows)
    out = pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        grid=(pl.cdiv(rows, block_rows),),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        interpret=interpret,
    )(x2, w)
    return out.reshape(orig_shape)


def np_prod(shape):
    out = 1
    for s in shape:
        out *= s
    return out


def rmsnorm(x: jax.Array, w: jax.Array, eps: float = 1e-6,
            use_pallas: Optional[bool] = None, interpret: bool = False):
    """RMS normalization over the last axis, scaled by w."""
    if use_pallas is None:
        try:
            use_pallas = jax.devices()[0].platform == "tpu"
        except Exception:  # noqa: BLE001
            use_pallas = False
    if (use_pallas or interpret):
        return _rmsnorm_pallas(x, w, eps, interpret=interpret)
    return _rmsnorm_xla(x, w, eps)
