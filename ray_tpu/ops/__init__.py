"""TPU kernels (Pallas) with XLA fallbacks for CPU testing.

The hot ops of the transformer stack: fused flash attention, rmsnorm,
rotary embeddings, and chunked cross-entropy. Each op auto-selects the
Pallas TPU kernel on TPU backends and a mathematically identical jnp
implementation elsewhere, so the full test suite runs on the virtual CPU
mesh (SURVEY.md §4.2).
"""

from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.ops.rmsnorm import rmsnorm
from ray_tpu.ops.rope import apply_rope, rope_frequencies
from ray_tpu.ops.cross_entropy import softmax_cross_entropy

__all__ = [
    "flash_attention",
    "rmsnorm",
    "apply_rope",
    "rope_frequencies",
    "softmax_cross_entropy",
]
