"""Rotary position embeddings (RoPE).

Pure jnp — XLA fuses the elementwise rotation into adjacent matmuls, so a
Pallas kernel buys nothing here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq: int, theta: float = 10000.0,
                     dtype=jnp.float32):
    """Precompute cos/sin tables: [max_seq, head_dim//2]."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               positions: jax.Array = None):
    """Rotate pairs of features. x: [batch, seq, heads, head_dim].

    positions: optional [batch, seq] global positions (for sequence-sharded
    blocks pass the block's global offsets); defaults to arange(seq).
    """
    b, l, h, d = x.shape
    if positions is None:
        cos_p = cos[:l][None, :, None, :]
        sin_p = sin[:l][None, :, None, :]
    else:
        cos_p = cos[positions][:, :, None, :]
        sin_p = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    rot1 = x1 * cos_p - x2 * sin_p
    rot2 = x2 * cos_p + x1 * sin_p
    return jnp.concatenate([rot1, rot2], axis=-1).astype(x.dtype)
