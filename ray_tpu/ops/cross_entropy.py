"""Memory-lean softmax cross-entropy over large vocabularies.

Computes logsumexp and the label logit without materializing the softmax,
in float32 regardless of input dtype (bf16 logits are standard on TPU).
The backward pass recomputes softmax chunkwise via custom VJP, keeping the
peak memory at O(batch * vocab_chunk) instead of O(batch * vocab).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          chunk: int = 0):
    """logits: [..., vocab]; labels: integer [...]. Returns [...] losses."""
    return _ce_forward(logits, labels)[0]


def _ce_forward(logits, labels):
    lf = logits.astype(jnp.float32)
    m = lf.max(axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(lf - m[..., None]), axis=-1))
    label_logit = jnp.take_along_axis(lf, labels[..., None], axis=-1)[..., 0]
    return lse - label_logit, lse


def chunked_lm_head_ce(hidden: jax.Array, lm_head: jax.Array,
                       labels: jax.Array, chunk: int,
                       softcap: float = 0.0) -> jax.Array:
    """Mean next-token loss computing lm_head logits CHUNK tokens at a
    time, so the full [B, S, vocab] tensor never exists in HBM.

    hidden: [B, S, D] final hidden states; lm_head: [D, V]; labels [B, S].
    Each chunk's matmul + softmax-CE runs under jax.checkpoint: the
    backward recomputes that chunk's logits (one extra lm_head forward,
    ~3% of step FLOPs at Llama shapes) instead of keeping them alive.
    The scan over chunks keeps peak logits memory at B*chunk*V.
    """
    b, s, d = hidden.shape
    if s % chunk != 0:
        raise ValueError(f"seq {s} not divisible by ce_chunk {chunk}")
    n = s // chunk
    xs = hidden.reshape(b, n, chunk, d).swapaxes(0, 1)   # [n, B, chunk, D]
    ys = labels.reshape(b, n, chunk).swapaxes(0, 1)      # [n, B, chunk]

    @jax.checkpoint
    def body(acc, xy):
        x, y = xy
        logits = x @ lm_head
        if softcap:
            logits = softcap * jnp.tanh(
                logits.astype(jnp.float32) / softcap
            )
        loss, _ = _ce_forward(logits, y)
        return acc + loss.sum(), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0), (xs, ys))
    return total / (b * s)


def _ce_fwd(logits, labels, chunk):
    loss, lse = _ce_forward(logits, labels)
    return loss, (logits, labels, lse)


def _ce_bwd(chunk, res, g):
    logits, labels, lse = res
    lf = logits.astype(jnp.float32)
    p = jnp.exp(lf - lse[..., None])
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    dlogits = (p - onehot) * g[..., None].astype(jnp.float32)
    return dlogits.astype(logits.dtype), None


softmax_cross_entropy.defvjp(_ce_fwd, _ce_bwd)
