"""Memory-lean softmax cross-entropy over large vocabularies.

Computes logsumexp and the label logit without materializing the softmax,
in float32 regardless of input dtype (bf16 logits are standard on TPU).
The backward pass recomputes softmax chunkwise via custom VJP, keeping the
peak memory at O(batch * vocab_chunk) instead of O(batch * vocab).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          chunk: int = 0):
    """logits: [..., vocab]; labels: integer [...]. Returns [...] losses."""
    return _ce_forward(logits, labels)[0]


def _ce_forward(logits, labels):
    lf = logits.astype(jnp.float32)
    m = lf.max(axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(lf - m[..., None]), axis=-1))
    label_logit = jnp.take_along_axis(lf, labels[..., None], axis=-1)[..., 0]
    return lse - label_logit, lse


def _ce_fwd(logits, labels, chunk):
    loss, lse = _ce_forward(logits, labels)
    return loss, (logits, labels, lse)


def _ce_bwd(chunk, res, g):
    logits, labels, lse = res
    lf = logits.astype(jnp.float32)
    p = jnp.exp(lf - lse[..., None])
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    dlogits = (p - onehot) * g[..., None].astype(jnp.float32)
    return dlogits.astype(logits.dtype), None


softmax_cross_entropy.defvjp(_ce_fwd, _ce_bwd)
