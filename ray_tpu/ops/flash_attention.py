"""Fused (flash) attention, forward and backward.

Pallas TPU kernels: the forward streams K/V blocks with an online-softmax
accumulator so the [Lq, Lk] score matrix never materializes in HBM, and
additionally writes the per-row logsumexp. The backward follows
flash-attention-2: probabilities are recomputed per block from the saved
logsumexp (p = exp(s - lse)) instead of being stored — one kernel computes
dq (grid over q-blocks, inner loop over kv), a second computes dk/dv (grid
over kv-blocks, inner loop over q). delta = rowsum(do * o) is precomputed
outside the kernels.

Sequence lengths that are not multiples of the block sizes are zero-padded
up to the block grid outside the kernels, and the kernels mask scores at
positions beyond the true lengths (s -> -inf), so padded keys contribute
nothing and padded query rows are sliced off on return.

On non-TPU backends an equivalent jnp implementation runs (same math,
XLA-fused, differentiable by tracing).

Kernel structure follows the standard flash-attention-on-TPU shape
(blockwise q outer, kv inner loop, f32 accumulators, MXU-sized tiles) per
/opt/skills/guides/pallas_guide.md. The reference has no analog — it
delegates attention to torch inside user train loops (SURVEY.md §2.4).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001
        return False


def _pad_to(x, length, axis):
    pad = length - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _score_mask(s, q_off, k_off, block_q, block_k, causal, lq, lk, lq_pad,
                lk_pad):
    """Mask scores outside the causal triangle or beyond the true lengths."""
    if not (causal or lq != lq_pad or lk != lk_pad):
        return s
    q_pos = q_off + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_pos = k_off + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    valid = (q_pos < lq) & (k_pos < lk)
    if causal:
        valid &= q_pos >= k_pos
    return jnp.where(valid, s, NEG_INF)


# ---------------------------------------------------------------------------
# Pallas TPU kernels
# ---------------------------------------------------------------------------


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                      causal: bool, sm_scale: float, lq: int, lk: int,
                      lq_pad: int):
    """One (batch*head, q-block) program: loop over kv blocks.

    q_ref: [block_q, d]; k_ref/v_ref: [Lk_pad, d]; o_ref: [block_q, d];
    lse_ref: [block_q, 1] (f32 logsumexp of each row's scores — the
    trailing singleton keeps the row stats 2D, which Mosaic's
    last-two-dims tiling rule requires of every block).
    """
    from jax.experimental import pallas as pl

    q_idx = pl.program_id(1)
    block_q, d = q_ref.shape
    lk_pad = k_ref.shape[0]
    num_kv = pl.cdiv(lk_pad, block_k)

    q = q_ref[:].astype(jnp.float32) * sm_scale

    o = jnp.zeros((block_q, d), dtype=jnp.float32)
    m = jnp.full((block_q, 1), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((block_q, 1), dtype=jnp.float32)

    def body(kv_idx, carry):
        o, m, l = carry
        k_blk = k_ref[pl.ds(kv_idx * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kv_idx * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        s = _score_mask(s, q_idx * block_q, kv_idx * block_k, block_q,
                        block_k, causal, lq, lk, lq_pad, lk_pad)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        o_new = o * corr + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    if causal:
        # Only kv blocks up to and including the diagonal contribute.
        last = jax.lax.div(
            (q_idx + 1) * block_q + block_k - 1, jnp.int32(block_k)
        )
        num_iter = jnp.minimum(last, num_kv)
    else:
        num_iter = num_kv
    o, m, l = jax.lax.fori_loop(0, num_iter, body, (o, m, l))
    l_safe = jnp.maximum(l, 1e-20)
    o_ref[:] = (o / l_safe).astype(o_ref.dtype)
    lse_ref[:] = m + jnp.log(l_safe)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, causal: bool,
                         sm_scale: float, lq: int, lk: int, lq_pad: int):
    """dq for one (batch*head, q-block): loop over kv blocks.

    ds = p * (do @ v^T - delta);  dq = sm_scale * ds @ k.
    """
    from jax.experimental import pallas as pl

    q_idx = pl.program_id(1)
    block_q, d = q_ref.shape
    lk_pad = k_ref.shape[0]
    num_kv = pl.cdiv(lk_pad, block_k)

    q = q_ref[:].astype(jnp.float32) * sm_scale
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:]        # [block_q, 1]
    delta = delta_ref[:]    # [block_q, 1]

    def body(kv_idx, dq):
        k_blk = k_ref[pl.ds(kv_idx * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kv_idx * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        s = _score_mask(s, q_idx * block_q, kv_idx * block_k, block_q,
                        block_k, causal, lq, lk, lq_pad, lk_pad)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    if causal:
        last = jax.lax.div(
            (q_idx + 1) * block_q + block_k - 1, jnp.int32(block_k)
        )
        num_iter = jnp.minimum(last, num_kv)
    else:
        num_iter = num_kv
    dq = jax.lax.fori_loop(
        0, num_iter, body, jnp.zeros((block_q, d), jnp.float32)
    )
    dq_ref[:] = (sm_scale * dq).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          sm_scale: float, lq: int, lk: int, lk_pad: int):
    """dk/dv for one (batch*head, kv-block): loop over q blocks.

    dv = p^T @ do;  dk = sm_scale * ds^T @ q.
    """
    from jax.experimental import pallas as pl

    kv_idx = pl.program_id(1)
    block_k, d = k_ref.shape
    lq_pad = q_ref.shape[0]
    num_q = pl.cdiv(lq_pad, block_q)

    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)

    def body(q_i, carry):
        dk, dv = carry
        q_blk = q_ref[pl.ds(q_i * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[pl.ds(q_i * block_q, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[pl.ds(q_i * block_q, block_q), :]      # [block_q, 1]
        delta_blk = delta_ref[pl.ds(q_i * block_q, block_q), :]  # [block_q, 1]
        s = sm_scale * jnp.dot(
            q_blk, k.T, preferred_element_type=jnp.float32
        )
        s = _score_mask(s, q_i * block_q, kv_idx * block_k, block_q, block_k,
                        causal, lq, lk, lq_pad, lk_pad)
        p = jnp.exp(s - lse_blk)
        dv = dv + jnp.dot(p.T, do_blk, preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk)
        dk = dk + jnp.dot(ds.T, q_blk, preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # First q block that reaches this kv block's first column.
        start = jax.lax.div(kv_idx * block_k, jnp.int32(block_q))
    else:
        start = 0
    dk, dv = jax.lax.fori_loop(
        start,
        num_q,
        body,
        (
            jnp.zeros((block_k, d), jnp.float32),
            jnp.zeros((block_k, d), jnp.float32),
        ),
    )
    dk_ref[:] = (sm_scale * dk).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _fwd_pallas(qt, kt, vt, causal, block_q, block_k, interpret):
    """qt/kt/vt: [b*h, L, d]. Returns (out [b*h, Lq, d], lse [b*h, Lq] f32)."""
    from jax.experimental import pallas as pl

    bh, lq, d = qt.shape
    lk = kt.shape[1]
    sm_scale = d ** -0.5
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    lq_pad = -(-lq // block_q) * block_q
    lk_pad = -(-lk // block_k) * block_k
    qp = _pad_to(qt, lq_pad, 1)
    kp = _pad_to(kt, lk_pad, 1)
    vp = _pad_to(vt, lk_pad, 1)

    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, causal=causal, sm_scale=sm_scale,
        lq=lq, lk=lk, lq_pad=lq_pad,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, lq_pad // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, lk_pad, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, lk_pad, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq_pad, d), qt.dtype),
            jax.ShapeDtypeStruct((bh, lq_pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :lq], lse[:, :lq, 0]


def _bwd_pallas(qt, kt, vt, out, lse, g, causal, block_q, block_k, interpret):
    from jax.experimental import pallas as pl

    bh, lq, d = qt.shape
    lk = kt.shape[1]
    sm_scale = d ** -0.5
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    lq_pad = -(-lq // block_q) * block_q
    lk_pad = -(-lk // block_k) * block_k

    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [bh, lq]

    qp = _pad_to(qt, lq_pad, 1)
    kp = _pad_to(kt, lk_pad, 1)
    vp = _pad_to(vt, lk_pad, 1)
    gp = _pad_to(g, lq_pad, 1)
    # Padded rows carry lse=0, delta=0 so masked scores give p=exp(-1e30)=0.
    # Trailing singleton keeps row stats 2D in-kernel (Mosaic tiling rule).
    lsep = _pad_to(lse, lq_pad, 1)[..., None]
    deltap = _pad_to(delta, lq_pad, 1)[..., None]

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel,
            block_k=block_k,
            causal=causal,
            sm_scale=sm_scale,
            lq=lq,
            lk=lk,
            lq_pad=lq_pad,
        ),
        grid=(bh, lq_pad // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, lk_pad, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, lk_pad, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, lq_pad, d), qt.dtype),
        interpret=interpret,
    )(qp, kp, vp, gp, lsep, deltap)

    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel,
            block_q=block_q,
            causal=causal,
            sm_scale=sm_scale,
            lq=lq,
            lk=lk,
            lk_pad=lk_pad,
        ),
        grid=(bh, lk_pad // block_k),
        in_specs=[
            pl.BlockSpec((None, lq_pad, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, lq_pad, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, lq_pad, 1), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, lq_pad, 1), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lk_pad, d), kt.dtype),
            jax.ShapeDtypeStruct((bh, lk_pad, d), vt.dtype),
        ],
        interpret=interpret,
    )(qp, kp, vp, gp, lsep, deltap)
    return dq[:, :lq], dk[:, :lk], dv[:, :lk]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_pallas_core(qt, kt, vt, causal, block_q, block_k,
                                 interpret):
    """Differentiable Pallas flash attention on [b*h, L, d] tensors."""
    out, _ = _fwd_pallas(qt, kt, vt, causal, block_q, block_k, interpret)
    return out


def _core_fwd(qt, kt, vt, causal, block_q, block_k, interpret):
    out, lse = _fwd_pallas(qt, kt, vt, causal, block_q, block_k, interpret)
    return out, (qt, kt, vt, out, lse)


def _core_bwd(causal, block_q, block_k, interpret, res, g):
    qt, kt, vt, out, lse = res
    return _bwd_pallas(
        qt, kt, vt, out, lse, g, causal, block_q, block_k, interpret
    )


_flash_attention_pallas_core.defvjp(_core_fwd, _core_bwd)


def _flash_attention_pallas(q, k, v, causal: bool, block_q: int, block_k: int,
                            interpret: bool = False):
    b, lq, h, d = q.shape
    lk = k.shape[1]
    # [b, h, l, d] layout for blocking.
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    out = _flash_attention_pallas_core(
        qt, kt, vt, causal, block_q, block_k, interpret
    )
    return out.reshape(b, h, lq, d).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# XLA fallback (identical math)
# ---------------------------------------------------------------------------


def _flash_attention_xla(q, k, v, causal: bool):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
):
    """Fused attention. q,k,v: [batch, seq, heads, head_dim].

    GQA/MQA: if k/v have fewer heads than q, they are broadcast per group
    (the repeat happens outside the kernel, so its VJP sums the per-group
    gradients back onto the shared kv heads).
    """
    if k.shape[2] != q.shape[2]:
        group = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    if use_pallas is None:
        use_pallas = _on_tpu()
    if use_pallas or interpret:
        return _flash_attention_pallas(
            q, k, v, causal, block_q, block_k, interpret=interpret
        )
    return _flash_attention_xla(q, k, v, causal)
