"""Fused (flash) attention.

Pallas TPU kernel: grid over (batch, heads, q-blocks); the kernel streams
K/V blocks from VMEM with an online-softmax accumulator so the full
[Lq, Lk] score matrix never materializes in HBM. On non-TPU backends an
equivalent jnp implementation runs (same math, XLA-fused).

Kernel structure follows the standard flash-attention-on-TPU shape
(blockwise q outer, kv inner loop, f32 accumulators, MXU-sized tiles) per
/opt/skills/guides/pallas_guide.md.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001
        return False


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  sm_scale: float, q_block_idx_dim: int):
    """One (batch*head, q-block) program: loop over kv blocks.

    q_ref: [block_q, d]; k_ref/v_ref: [Lk, d]; o_ref: [block_q, d].
    """
    from jax.experimental import pallas as pl

    q_idx = pl.program_id(q_block_idx_dim)
    block_q, d = q_ref.shape
    lk = k_ref.shape[0]
    num_kv = pl.cdiv(lk, block_k)

    q = q_ref[:].astype(jnp.float32) * sm_scale

    o = jnp.zeros((block_q, d), dtype=jnp.float32)
    m = jnp.full((block_q,), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((block_q,), dtype=jnp.float32)

    def body(kv_idx, carry):
        o, m, l = carry
        k_blk = k_ref[pl.ds(kv_idx * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kv_idx * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    if causal:
        # Only kv blocks up to and including the diagonal contribute.
        last = jax.lax.div(
            (q_idx + 1) * block_q + block_k - 1, jnp.int32(block_k)
        )
        num_iter = jnp.minimum(last, num_kv)
    else:
        num_iter = num_kv
    o, m, l = jax.lax.fori_loop(0, num_iter, body, (o, m, l))
    o_ref[:] = (o / jnp.maximum(l[:, None], 1e-20)).astype(o_ref.dtype)


def _flash_attention_pallas(q, k, v, causal: bool, block_q: int, block_k: int,
                            interpret: bool = False):
    from jax.experimental import pallas as pl

    b, lq, h, d = q.shape
    lk = k.shape[1]
    sm_scale = d ** -0.5
    # [b, h, l, d] layout for blocking.
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, lk, d)

    block_q = min(block_q, lq)
    block_k = min(block_k, lk)

    kernel = functools.partial(
        _flash_kernel,
        block_k=block_k,
        causal=causal,
        sm_scale=sm_scale,
        q_block_idx_dim=1,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, pl.cdiv(lq, block_q)),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, lk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, lk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(b, h, lq, d).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# XLA fallback (identical math)
# ---------------------------------------------------------------------------


def _flash_attention_xla(q, k, v, causal: bool):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
):
    """Fused attention. q,k,v: [batch, seq, heads, head_dim].

    GQA/MQA: if k/v have fewer heads than q, they are broadcast per group.
    """
    if k.shape[2] != q.shape[2]:
        group = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    if use_pallas is None:
        use_pallas = _on_tpu()
    if use_pallas or interpret:
        return _flash_attention_pallas(
            q, k, v, causal, block_q, block_k, interpret=interpret
        )
    return _flash_attention_xla(q, k, v, causal)
