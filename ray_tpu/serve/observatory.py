"""Serve request observatory: per-request phase attribution + SLO plane.

Three pieces the serving stack gains here (ROADMAP serving-observability
item; the request-path mirror of the train-side flight recorder):

  1. ALWAYS-ON phase attribution. Every request is stamped at handle
     enqueue, router dispatch, replica receive, engine admission (slot
     grant), prefill completion (first token) and terminal token; the
     finished request yields a phase vector

         {handle_queue, dispatch, engine_admission_wait,
          prefill, decode, stream}

     that sums to the e2e wall BY CONSTRUCTION (telescoping over the
     stamp chain — the fraction gate in bench_serve_obs.py catches any
     stamp-wiring regression, not float drift). Finished vectors ride a
     per-replica ring (same design as the StepProfiler ring) and feed
     process-wide labeled metrics. Non-engine deployments collapse the
     engine phases into one ``exec`` phase.

  2. Per-tenant / per-deployment SLO accounting. Deployments declare
     optional targets (``SloConfig``: TTFT / TPOT / e2e p-latency
     bounds); the observatory scores every finished request against
     them per tenant, keeps fast/slow sliding windows, and exposes
     attainment + multi-window burn rates (violation rate over the
     window divided by the error budget ``1 - objective``).

  3. The autoscaling signal plane. ``snapshot()`` is the per-replica
     half of the versioned ``ServeSignals`` document the controller
     assembles and publishes to the GCS KV at a fixed cadence
     (controller._publish_signals) — QPS, batch occupancy, slot-wait
     queue depth, TTFT/TPOT percentiles, backlog-drain estimate,
     per-replica health, per-tenant SLO burn. `rt serve` renders it;
     a future autoscaler consumes it.

Clock discipline: cross-process stamps (handle enqueue/dispatch ->
replica receive) use ``time.time()`` (the only clock that compares
across processes; NTP skew lands in the ``dispatch`` phase and is
clamped at >= 0), everything after replica receive uses
``time.perf_counter()`` deltas, immune to clock steps. Sampled requests
(lifecycle head sampling) additionally emit one LIFECYCLE_SPAN event so
serve requests stitch into `rt profile tasks` / `rt timeline
--lifecycle` next to control-plane phases.

The unsampled steady-state cost is a handful of perf_counter stamps and
dict writes per REQUEST (never per decode step); bench_serve_obs.py
gates the paired-median per-request overhead at < 2%.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ray_tpu._private.config import get_config
from ray_tpu.util import journal
from ray_tpu.util.lifecycle import SERVE_PHASE_ORDER

logger = logging.getLogger("ray_tpu.serve")

#: ServeSignals document schema version (bump on breaking shape change).
#: v2 adds paged-KV fields (per-replica kv_util / prefix_hit_rate /
#: prefill_tokens_skipped, per-app "kv" aggregate, target/running
#: replica counts) — purely additive, v1 readers ignore them.
SIGNALS_SCHEMA_VERSION = 2

#: GCS KV key (ns="serve") the controller publishes ServeSignals under.
SIGNALS_KEY = b"serve_signals"

#: SLO kinds a deployment can bound (SloConfig fields <kind>_ms).
SLO_KINDS = ("ttft", "tpot", "e2e")

_tls = threading.local()

_metrics_lock = threading.Lock()
_metrics: Optional[Dict] = None


def _obs_metrics() -> Dict:
    """Lazy module-level metric set (one per process, flushed to GCS by
    the metrics flusher) — created on the first finished request so
    importing this module never spins up the flusher thread."""
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util import metrics as _mx

            _metrics = {
                "phase_s": _mx.get_or_create(
                    _mx.Counter, "serve_request_phase_seconds_total",
                    "Per-request phase attribution: seconds spent in each "
                    "serve phase (handle_queue/dispatch/admission/prefill/"
                    "decode/stream), per deployment",
                    tag_keys=("app", "phase"),
                ),
                "e2e_s": _mx.get_or_create(
                    _mx.Histogram, "serve_request_e2e_seconds",
                    "End-to-end request wall (handle enqueue -> reply), "
                    "per deployment",
                    # Wide tail: macro-load e2e p99s run multi-second and
                    # must not clamp into +Inf (other serve histograms
                    # keep LATENCY_BOUNDARIES).
                    boundaries=_mx.LATENCY_BOUNDARIES_WIDE,
                    tag_keys=("app",),
                ),
                "requests": _mx.get_or_create(
                    _mx.Counter, "serve_requests_total",
                    "Finished serve requests per deployment and tenant",
                    tag_keys=("app", "tenant"),
                ),
                "tokens": _mx.get_or_create(
                    _mx.Counter, "serve_tenant_tokens_total",
                    "Prompt (in) and generated (out) tokens per deployment "
                    "and tenant", tag_keys=("app", "tenant", "direction"),
                ),
                "queue_s": _mx.get_or_create(
                    _mx.Histogram, "serve_tenant_queue_seconds",
                    "Pre-execution queueing per request (handle_queue + "
                    "dispatch + engine admission wait), per tenant",
                    boundaries=_mx.LATENCY_BOUNDARIES,
                    tag_keys=("app", "tenant"),
                ),
                "slo_total": _mx.get_or_create(
                    _mx.Counter, "serve_slo_requests_total",
                    "Requests scored against a declared SLO target",
                    tag_keys=("app", "tenant", "slo"),
                ),
                "slo_viol": _mx.get_or_create(
                    _mx.Counter, "serve_slo_violations_total",
                    "Requests that missed their declared SLO target",
                    tag_keys=("app", "tenant", "slo"),
                ),
                "slo_burn": _mx.get_or_create(
                    _mx.Gauge, "serve_slo_burn_rate",
                    "Fast-window SLO burn rate (violation rate / error "
                    "budget); > 1 consumes budget faster than allowed",
                    tag_keys=("app", "tenant", "slo"),
                ),
                # -- survival plane (PR 8) ---------------------------
                "shed": _mx.get_or_create(
                    _mx.Counter, "serve_requests_shed_total",
                    "Requests rejected by admission control instead of "
                    "queued (reason: queue_full/draining/circuit_open)",
                    tag_keys=("app", "tenant", "reason"),
                ),
                "deadline_expired": _mx.get_or_create(
                    _mx.Counter, "serve_deadline_expired_total",
                    "Requests cancelled because their propagated deadline "
                    "expired, by the hop that noticed (replica/engine_"
                    "admission/engine_decode/handle)",
                    tag_keys=("app", "hop"),
                ),
                "drain_s": _mx.get_or_create(
                    _mx.Histogram, "serve_drain_seconds",
                    "Graceful replica drain duration (admission stop -> "
                    "last in-flight request finished)",
                    boundaries=_mx.LATENCY_BOUNDARIES, tag_keys=("app",),
                ),
                "cb_state": _mx.get_or_create(
                    _mx.Gauge, "serve_circuit_breaker_state",
                    "Per-replica circuit breaker state as seen by a "
                    "handle (0 closed, 1 half-open, 2 open)",
                    tag_keys=("app", "replica"),
                ),
            }
        return _metrics


class RequestContext:
    """Per-request stamp card, threaded from the wire dict the handle
    ships through to the terminal engine token.

    The replica's request thread owns begin()/finish(); the engine
    thread writes only into ``marks`` (distinct keys, single writer per
    key — same discipline as GenerationHandle's engine-side fields).
    """

    __slots__ = ("rid", "tenant", "app", "method", "sampled",
                 "enq_t", "disp_t", "recv_t", "recv_p",
                 "marks", "tokens_in", "tokens_out", "finished")

    def __init__(self, rid: str, tenant: str, app: str, method: str,
                 sampled: bool, enq_t: Optional[float],
                 disp_t: Optional[float]):
        self.rid = rid
        self.tenant = tenant or "default"
        self.app = app
        self.method = method or "__call__"
        self.sampled = sampled
        self.enq_t = enq_t          # caller epoch: handle .remote() entry
        self.disp_t = disp_t        # caller epoch: just before actor call
        self.recv_t = time.time()   # replica epoch: request received
        self.recv_p = time.perf_counter()
        self.marks: Dict[str, float] = {}   # perf-clock stamps
        self.tokens_in = 0
        self.tokens_out = 0
        self.finished = False

    def mark(self, name: str, at: Optional[float] = None) -> None:
        self.marks[name] = time.perf_counter() if at is None else at

    def epoch_of(self, perf_t: float) -> float:
        """Map a replica perf_counter stamp onto the epoch axis."""
        return self.recv_t + (perf_t - self.recv_p)


def make_wire_ctx(tenant: str = "") -> Optional[Dict]:
    """Caller-side half of the stamp card, built at handle enqueue.

    Ships as a plain dict (rid, tenant, epoch stamps, sampled bit); the
    replica rehydrates it into a RequestContext. None when the
    observatory is disabled — every downstream hop then short-circuits.
    """
    if not get_config().serve_observatory:
        return None
    from ray_tpu.util import lifecycle

    return {
        "rid": os.urandom(8).hex(),
        "tenant": tenant,
        "enq_t": time.time(),
        "sampled": bool(lifecycle.enabled and lifecycle.sample()),
        # HLC stamp: the enqueue happens-before everything the replica
        # does for this request, across the process boundary.
        "hlc": journal.wire_stamp(),
    }


def begin(obs_ctx: Optional[Dict], app: str,
          method: str = "__call__") -> Optional[RequestContext]:
    """Open a request context on this (replica) thread.

    Tolerates a missing wire dict (direct replica calls, disabled
    callers): the request still gets local phases, just no
    handle_queue/dispatch attribution.
    """
    if not get_config().serve_observatory:
        return None
    d = obs_ctx or {}
    journal.observe_wire(d.get("hlc"))
    ctx = RequestContext(
        rid=d.get("rid") or os.urandom(8).hex(),
        tenant=d.get("tenant", ""),
        app=app,
        method=method,
        sampled=bool(d.get("sampled")),
        enq_t=d.get("enq_t"),
        disp_t=d.get("disp_t"),
    )
    _tls.ctx = ctx
    return ctx


def current() -> Optional[RequestContext]:
    """The request context active on this thread (engine submit() grabs
    it so engine-thread stamps land on the right card)."""
    return getattr(_tls, "ctx", None)


def finish(ctx: Optional[RequestContext]) -> Optional[Dict]:
    """Close the context: compute the phase vector, feed the ring,
    metrics, tenant SLO accounting, and (sampled) the lifecycle stream.
    Returns the finished record (None when disabled/double-finished)."""
    if ctx is None or ctx.finished:
        return None
    ctx.finished = True
    if getattr(_tls, "ctx", None) is ctx:
        _tls.ctx = None
    return profiler().finish(ctx)


def _compute_phases(ctx: RequestContext, end_p: float) -> Dict[str, float]:
    """Telescoping phase vector over the stamp chain.

    Caller-side epoch stamps cover handle_queue (enqueue -> dispatch)
    and the cross-process wire (dispatch -> receive, folded into
    ``dispatch`` together with replica-side pre-engine work); replica
    perf stamps cover everything after receive. The six phases sum to
    e2e exactly (modulo the >= 0 clamps on cross-clock deltas).
    """
    marks = ctx.marks
    hq = wire = 0.0
    if ctx.enq_t is not None and ctx.disp_t is not None:
        hq = max(ctx.disp_t - ctx.enq_t, 0.0)
        wire = max(ctx.recv_t - ctx.disp_t, 0.0)
    eq = marks.get("engine_enqueue")
    phases: Dict[str, float] = {"handle_queue": hq}
    if eq is None:
        phases["dispatch"] = wire
        phases["exec"] = max(end_p - ctx.recv_p, 0.0)
        return phases
    # Clamp the engine chain monotone (a failed request may miss marks;
    # missing ones collapse their phase to 0 at the end stamp).
    eq = min(max(eq, ctx.recv_p), end_p)
    sg = min(max(marks.get("slot_grant", end_p), eq), end_p)
    ft = min(max(marks.get("first_token", end_p), sg), end_p)
    ed = min(max(marks.get("engine_done", end_p), ft), end_p)
    phases["dispatch"] = wire + (eq - ctx.recv_p)
    phases["engine_admission_wait"] = sg - eq
    phases["prefill"] = ft - sg
    phases["decode"] = ed - ft
    phases["stream"] = end_p - ed
    return phases


class _TenantStats:
    """Per-tenant accumulator: lifetime totals + a time-pruned window of
    per-request SLO outcomes for burn-rate math."""

    __slots__ = ("requests", "tokens_in", "tokens_out", "queue_s",
                 "outcomes")

    def __init__(self):
        self.requests = 0
        self.tokens_in = 0
        self.tokens_out = 0
        self.queue_s = 0.0
        # (epoch_ts, {kind: violated_bool}) — pruned past the slow window.
        self.outcomes: deque = deque(maxlen=8192)

    def window_counts(self, now: float, window_s: float) -> Dict[str, List[int]]:
        """{kind: [good, total]} over the trailing window."""
        out: Dict[str, List[int]] = {}
        lo = now - window_s
        for ts, verdicts in self.outcomes:
            if ts < lo:
                continue
            for kind, violated in verdicts.items():
                row = out.setdefault(kind, [0, 0])
                row[1] += 1
                if not violated:
                    row[0] += 1
        return out


def burn_rate(good: int, total: int, objective: float) -> float:
    """Violation rate over the error budget: 1.0 burns budget exactly at
    the allowed rate, > 1 exhausts it early, 0 is a clean window."""
    if total <= 0:
        return 0.0
    budget = max(1.0 - float(objective), 1e-9)
    return ((total - good) / total) / budget


class RequestProfiler:
    """Per-replica finished-request ring + tenant SLO ledger.

    The serve-side sibling of the train flight recorder's StepProfiler:
    bounded memory, lock only around the ring/tenant maps (the stamps
    themselves are lock-free), aggregates computed at read time.
    """

    def __init__(self, ring: Optional[int] = None, app: str = "",
                 slo=None):
        cfg = get_config()
        self.app = app or "-"
        self.slo = slo
        # Capacity comes from cfg.serve_obs_ring, overridable per process
        # via RT_SERVE_OBS_RING — macro-load runs size it to hold the
        # whole run so the reconciler can join every request.
        self._ring: deque = deque(maxlen=ring or cfg.serve_obs_ring)
        # Overwrite accounting: a full ring silently drops the oldest
        # finished-request record per append. Counted so sustained-QPS
        # runs can tell (and warn) when phase records are being lost.
        self._overwrites = 0
        self._overwrite_warn_t = 0.0
        self._lock = threading.Lock()
        self._tenants: Dict[str, _TenantStats] = {}
        self._finish_ts: deque = deque(maxlen=2048)  # epoch, for QPS
        self._ttft: deque = deque(maxlen=512)        # recent samples the
        self._tpot: deque = deque(maxlen=512)        # controller merges
        self._requests = 0
        # Survival-plane tallies: sheds keyed "tenant|reason", deadline
        # expiries keyed by the hop that noticed. Written by the replica/
        # engine threads under the same lock as the ring.
        self._shed: Dict[str, int] = {}
        self._expired: Dict[str, int] = {}
        # Hot-path metric keys resolved once per (phase)/(tenant) label
        # set — the keyed fast path from util.metrics.
        self._phase_keys: Dict[str, tuple] = {}

    def configure(self, app: str, slo) -> None:
        self.app = app or self.app
        self.slo = slo
        self._phase_keys.clear()

    # -- write side ------------------------------------------------------
    def finish(self, ctx: RequestContext) -> Dict:
        end_p = time.perf_counter()
        phases = _compute_phases(ctx, end_p)
        e2e = sum(phases.values())
        ft = ctx.marks.get("first_token")
        ttft = None
        if ft is not None:
            ttft = (phases["handle_queue"] + phases["dispatch"]
                    + phases.get("engine_admission_wait", 0.0)
                    + phases.get("prefill", 0.0))
        tpot = None
        if ctx.tokens_out > 1 and "decode" in phases:
            tpot = phases["decode"] / (ctx.tokens_out - 1)
        rec = {
            "rid": ctx.rid,
            "tenant": ctx.tenant,
            "method": ctx.method,
            "ts": time.time(),
            "phases": phases,
            "e2e_s": e2e,
            "ttft_s": ttft,
            "tpot_s": tpot,
            "tokens_in": ctx.tokens_in,
            "tokens_out": ctx.tokens_out,
        }
        queue_s = (phases["handle_queue"] + phases["dispatch"]
                   + phases.get("engine_admission_wait", 0.0))
        verdicts = self._score_slo(ttft, tpot, e2e)
        warn_overwrites = 0
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._overwrites += 1
                now_m = time.monotonic()
                if now_m - self._overwrite_warn_t >= 60.0:
                    self._overwrite_warn_t = now_m
                    warn_overwrites = self._overwrites
            self._ring.append(rec)
            self._requests += 1
            self._finish_ts.append(rec["ts"])
            if ttft is not None:
                self._ttft.append(ttft)
            if tpot is not None:
                self._tpot.append(tpot)
            t = self._tenants.get(ctx.tenant)
            if t is None:
                t = self._tenants[ctx.tenant] = _TenantStats()
            t.requests += 1
            t.tokens_in += ctx.tokens_in
            t.tokens_out += ctx.tokens_out
            t.queue_s += queue_s
            if verdicts:
                t.outcomes.append((rec["ts"], verdicts))
        journal.emit("serve.request", rid=ctx.rid, app=self.app,
                     tenant=ctx.tenant, e2e_s=round(e2e, 6),
                     tokens_out=ctx.tokens_out)
        if warn_overwrites:
            # Rate-limited (once per minute per replica): sustained load
            # past ring capacity silently evicts phase records, which
            # starves the reconciler and ServeSignals of attribution.
            logger.warning(
                "observatory ring for app %r is overwriting finished-"
                "request records (%d overwritten so far, capacity %d); "
                "raise RT_SERVE_OBS_RING to keep full attribution for "
                "macro runs", self.app, warn_overwrites,
                self._ring.maxlen,
            )
        self._observe_metrics(ctx, phases, e2e, queue_s, verdicts)
        if ctx.sampled:
            self._emit_lifecycle(ctx, phases, e2e)
        return rec

    def _score_slo(self, ttft, tpot, e2e) -> Dict[str, bool]:
        """{kind: violated} for every target the deployment declared."""
        slo = self.slo
        if slo is None:
            return {}
        out: Dict[str, bool] = {}
        for kind, value in (("ttft", ttft), ("tpot", tpot), ("e2e", e2e)):
            target_ms = getattr(slo, f"{kind}_ms", None)
            if target_ms is None or value is None:
                continue
            out[kind] = value * 1e3 > target_ms
        return out

    def _observe_metrics(self, ctx, phases, e2e, queue_s, verdicts):
        m = _obs_metrics()
        for phase, dur in phases.items():
            key = self._phase_keys.get(phase)
            if key is None:
                key = m["phase_s"]._key({"app": self.app, "phase": phase})
                self._phase_keys[phase] = key
            m["phase_s"].inc_keyed(key, dur)
        m["e2e_s"].observe(e2e, tags={"app": self.app})
        base = {"app": self.app, "tenant": ctx.tenant}
        m["requests"].inc(1, tags=base)
        if ctx.tokens_in:
            m["tokens"].inc(ctx.tokens_in, tags={**base, "direction": "in"})
        if ctx.tokens_out:
            m["tokens"].inc(ctx.tokens_out, tags={**base, "direction": "out"})
        m["queue_s"].observe(queue_s, tags=base)
        for kind, violated in verdicts.items():
            tags = {**base, "slo": kind}
            m["slo_total"].inc(1, tags=tags)
            if violated:
                m["slo_viol"].inc(1, tags=tags)

    def _emit_lifecycle(self, ctx: RequestContext, phases, e2e) -> None:
        """One LIFECYCLE_SPAN per sampled request: serve phases stitch
        into `rt profile tasks` / `rt timeline --lifecycle` alongside the
        control-plane phases (same event stream, same stitcher)."""
        try:
            from ray_tpu._private import worker as worker_mod
            from ray_tpu.util import lifecycle, profiling

            client = worker_mod.get_client_or_none()
            node_id = getattr(client, "node_id", b"") or b""
            start = ctx.enq_t if ctx.enq_t is not None else ctx.recv_t
            marks: Dict[str, List[float]] = {}
            cursor = start
            for phase in SERVE_PHASE_ORDER:
                if phase not in phases:
                    continue
                dur = phases[phase]
                marks[phase] = [cursor, dur]
                cursor += dur
            ev = lifecycle.event(
                task_id=bytes.fromhex(ctx.rid),
                name=f"serve.{self.app}.{ctx.method}",
                job_id=b"",
                node_id=node_id,
                hop="serve_replica",
                phases=marks,
                e2e_s=e2e,
            )
            profiling.buffer_events([ev])
        except Exception:  # rtlint: disable=RT007 — observability must never fail a request
            pass

    def record_shed(self, tenant: str, reason: str) -> None:
        """Account one admission rejection (metric + snapshot tally)."""
        tenant = tenant or "default"
        with self._lock:
            key = f"{tenant}|{reason}"
            self._shed[key] = self._shed.get(key, 0) + 1
        m = _obs_metrics()
        m["shed"].inc(1, tags={"app": self.app, "tenant": tenant,  # rtlint: disable=RT013 — tenant values are validated against the fixed admission table before reaching here
                               "reason": reason})

    def record_deadline_expired(self, hop: str) -> None:
        """Account one deadline cancellation at the hop that noticed."""
        with self._lock:
            self._expired[hop] = self._expired.get(hop, 0) + 1
        m = _obs_metrics()
        m["deadline_expired"].inc(1, tags={"app": self.app, "hop": hop})

    # -- read side -------------------------------------------------------
    def records(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def qps(self, window_s: float = 30.0) -> float:
        now = time.time()
        with self._lock:
            n = sum(1 for ts in self._finish_ts if ts >= now - window_s)
        return n / window_s

    def snapshot(self) -> Dict:
        """The per-replica half of ServeSignals: bounded, JSON-safe."""
        cfg = get_config()
        now = time.time()
        windows = (cfg.serve_slo_fast_window_s, cfg.serve_slo_slow_window_s)
        with self._lock:
            ring = list(self._ring)
            tenants = dict(self._tenants)
            ttft = sorted(self._ttft)
            tpot = sorted(self._tpot)
            requests = self._requests
            shed = dict(self._shed)
            expired = dict(self._expired)
            overwrites = self._overwrites
            ring_cap = self._ring.maxlen or 0
        phase_agg: Dict[str, Dict[str, float]] = {}
        fractions: List[float] = []
        for rec in ring:
            if rec["e2e_s"] > 0:
                fractions.append(
                    sum(rec["phases"].values()) / rec["e2e_s"]
                )
            for phase, dur in rec["phases"].items():
                row = phase_agg.setdefault(phase, {"sum_s": 0.0, "count": 0})
                row["sum_s"] += dur
                row["count"] += 1
        slo_doc = None
        if self.slo is not None:
            slo_doc = {k: getattr(self.slo, f"{k}_ms", None)
                       for k in SLO_KINDS}
            slo_doc["objective"] = self.slo.objective
        tenant_doc: Dict[str, Dict] = {}
        m = _obs_metrics()
        for name, t in tenants.items():
            slo_windows: Dict[str, Dict] = {}
            for w in windows:
                counts = t.window_counts(now, w)
                slo_windows[str(int(w))] = {
                    kind: {
                        "good": good, "total": total,
                        "burn": burn_rate(
                            good, total,
                            self.slo.objective if self.slo else 0.99,
                        ),
                    }
                    for kind, (good, total) in counts.items()
                }
            fast = slo_windows.get(str(int(windows[0])), {})
            for kind, row in fast.items():
                m["slo_burn"].set(row["burn"], tags={
                    "app": self.app, "tenant": name, "slo": kind,
                })
            tenant_doc[name] = {
                "requests": t.requests,
                "tokens_in": t.tokens_in,
                "tokens_out": t.tokens_out,
                "queue_s": t.queue_s,
                "slo_windows": slo_windows,
            }
        return {
            "app": self.app,
            "ts": now,
            "requests_total": requests,
            "ring": {
                "capacity": ring_cap,
                "len": len(ring),
                "overwrites": overwrites,
                # Fraction of finished requests whose record was evicted
                # before this snapshot.
                "overwrite_rate": (
                    overwrites / requests if requests else 0.0
                ),
            },
            "qps": self.qps(),
            "phases": phase_agg,
            "phase_sum_fraction": (
                sum(fractions) / len(fractions) if fractions else None
            ),
            "ttft_samples": ttft[-256:],
            "tpot_samples": tpot[-256:],
            "slo": slo_doc,
            "slo_windows_s": [int(w) for w in windows],
            "tenants": tenant_doc,
            "shed": shed,
            "shed_total": sum(shed.values()),
            "deadline_expired": expired,
        }


_profiler_lock = threading.Lock()
_profiler: Optional[RequestProfiler] = None


def profiler() -> RequestProfiler:
    """Process-global per-replica profiler (one replica per process)."""
    global _profiler
    with _profiler_lock:
        if _profiler is None:
            _profiler = RequestProfiler()
        return _profiler


def configure(app: str, slo=None) -> None:
    """Label this replica process's profiler (called at replica init)."""
    profiler().configure(app, slo)


def record_shed(app: str, tenant: str = "",
                reason: str = "queue_full") -> None:
    """Module-level shed accounting (replica/engine/handle hops call
    this; no-op with the observatory disabled — shedding itself is
    never gated on observability)."""
    if not get_config().serve_observatory:
        return
    p = profiler()
    if app and p.app in ("-", ""):
        p.app = app
    p.record_shed(tenant, reason)
    journal.emit("serve.shed", app=app, tenant=tenant, reason=reason)


# Deadline-storm detector: a burst of expiries across hops is the
# signature of a systemic stall (dead replica, wedged engine), not a
# slow request — it earns an automatic black-box capture.
_expiry_times: deque = deque(maxlen=32)
_EXPIRY_STORM_N = 8
_EXPIRY_STORM_WINDOW_S = 5.0


def record_deadline_expired(app: str, hop: str) -> None:
    """Module-level deadline-expiry accounting, by noticing hop."""
    if not get_config().serve_observatory:
        return
    profiler().record_deadline_expired(hop)
    journal.emit("serve.deadline_expired", app=app, hop=hop)
    now = time.monotonic()
    _expiry_times.append(now)
    if (len(_expiry_times) >= _EXPIRY_STORM_N
            and now - _expiry_times[-_EXPIRY_STORM_N]
            <= _EXPIRY_STORM_WINDOW_S):
        journal.trigger_postmortem(
            f"deadline_storm:{app}", app=app, hop=hop,
            expiries=_EXPIRY_STORM_N, window_s=_EXPIRY_STORM_WINDOW_S,
        )


def record_drain(app: str, seconds: float) -> None:
    """One graceful-drain duration observation (controller-side)."""
    if not get_config().serve_observatory:
        return
    _obs_metrics()["drain_s"].observe(seconds, tags={"app": app or "-"})
    journal.emit("serve.drain", app=app, seconds=round(seconds, 3))


def set_circuit_state(app: str, replica: str, state: int) -> None:
    """Publish a handle's view of one replica's breaker (0 closed,
    1 half-open, 2 open). An open breaker is a client-visible failure
    signal — it triggers a black-box capture."""
    if not get_config().serve_observatory:
        return
    _obs_metrics()["cb_state"].set(
        float(state), tags={"app": app or "-", "replica": replica or "-"})
    journal.emit("serve.breaker", app=app, replica=replica,
                 state=int(state))
    if state == 2:
        journal.trigger_postmortem(
            f"breaker_open:{app}", app=app, replica=replica)


def reset_for_tests() -> None:
    """Drop the process-global profiler (test isolation only)."""
    global _profiler
    with _profiler_lock:
        _profiler = None


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over pre-sorted samples (0 when empty)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]
