"""Dynamic request batching — THE TPU serving feature.

Analog of the reference's @serve.batch (python/ray/serve/batching.py): a
decorated method takes a list of items and returns a list of results;
concurrent callers are transparently coalesced into batches of up to
`max_batch_size`, waiting at most `batch_wait_timeout_s` for the batch to
fill. On a TPU replica this is what turns 32 trickling HTTP requests into
one MXU-shaped forward pass.

Execution model: replicas run requests on actor executor threads
(max_concurrency > 1), so the batcher is thread-based — the first caller
into an empty batch becomes the leader, waits for the window, executes the
underlying function once, and distributes results to the other callers'
futures.
"""

from __future__ import annotations

import concurrent.futures
import threading
from typing import Any, Callable, List, Optional

from ray_tpu._private.config import get_config


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self.lock = threading.Lock()
        self.full = threading.Condition(self.lock)
        self.items: List[Any] = []
        self.futures: List[concurrent.futures.Future] = []
        self.leader_active = False
        # Observability: batch sizes actually executed (tests + tuning).
        self.batch_sizes: List[int] = []

    def submit(self, instance, item) -> Any:
        """Join the current batch; block until the batch runs; return this
        item's result (or raise the batch's exception)."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        with self.lock:
            self.items.append(item)
            self.futures.append(fut)
            is_leader = not self.leader_active
            if is_leader:
                self.leader_active = True
            elif len(self.items) >= self.max_batch_size:
                self.full.notify()
        if is_leader:
            self._lead(instance)
        # Bounded wait: if the leader wedges (e.g. the batch fn hangs on
        # a device), followers surface a timeout instead of deadlocking
        # the replica's whole call slot forever.
        return fut.result(timeout=get_config().serve_result_timeout_s)

    def _lead(self, instance):
        with self.lock:
            deadline = (
                threading.TIMEOUT_MAX
                if self.batch_wait_timeout_s is None
                else self.batch_wait_timeout_s
            )
            if len(self.items) < self.max_batch_size:
                self.full.wait(timeout=deadline)
            items, self.items = self.items, []
            futures, self.futures = self.futures, []
            self.leader_active = False
            self.batch_sizes.append(len(items))
        try:
            if instance is not None:
                results = self.fn(instance, items)
            else:
                results = self.fn(items)
            if len(results) != len(items):
                raise ValueError(
                    f"@serve.batch function returned {len(results)} results "
                    f"for a batch of {len(items)}"
                )
        except BaseException as e:  # noqa: BLE001 — fan the error out
            for f in futures:
                if not f.done():
                    f.set_exception(e)
            return
        for f, r in zip(futures, results):
            if not f.done():
                f.set_result(r)


class _BatchedMethod:
    """Descriptor so @serve.batch works on methods: one queue per instance."""

    def __init__(self, fn, max_batch_size, batch_wait_timeout_s):
        self._fn = fn
        self._max_batch_size = max_batch_size
        self._batch_wait_timeout_s = batch_wait_timeout_s
        self.__name__ = getattr(fn, "__name__", "batched")
        self._free_queue: Optional[_BatchQueue] = None

    def _queue_for(self, instance) -> _BatchQueue:
        if instance is None:
            if self._free_queue is None:
                self._free_queue = _BatchQueue(
                    self._fn, self._max_batch_size, self._batch_wait_timeout_s
                )
            return self._free_queue
        key = f"__serve_batch_queue_{self.__name__}"
        q = instance.__dict__.get(key)
        if q is None:
            q = _BatchQueue(
                self._fn, self._max_batch_size, self._batch_wait_timeout_s
            )
            instance.__dict__[key] = q
        return q

    def __get__(self, instance, owner=None):
        if instance is None:
            return self

        def bound(item):
            return self._queue_for(instance).submit(instance, item)

        bound.__name__ = self.__name__
        bound._batch_queue = self._queue_for(instance)
        return bound

    def __call__(self, item):
        return self._queue_for(None).submit(None, item)


def batch(_fn=None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: fn(self, items: List[T]) -> List[R] becomes callable with
    a single item; concurrent single calls coalesce into batches
    (reference: python/ray/serve/batching.py)."""

    def deco(fn):
        return _BatchedMethod(fn, max_batch_size, batch_wait_timeout_s)

    if _fn is not None:
        return deco(_fn)
    return deco
