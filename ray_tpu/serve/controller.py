"""Serve controller: reconciles deployments to replica actors.

Analog of the reference's ServeController (serve/_private/controller.py:91)
+ DeploymentState reconciliation (deployment_state.py:1211) + the basic
autoscaling loop (autoscaling_policy.py): a named actor owning the desired
state; a background thread reconciles replica counts and applies
queue-length-based autoscaling; handles fetch the replica list with a
version number and long-poll-style refresh on change
(serve/_private/long_poll.py analog via polling).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu as rt
from ray_tpu._private.config import get_config
from ray_tpu.exceptions import (
    ActorError,
    GetTimeoutError,
    WorkerCrashedError,
)
from ray_tpu.serve import autoscale
from ray_tpu.serve.deployment import Application, AutoscalingConfig, Deployment
from ray_tpu.serve.replica import ReplicaActor
from ray_tpu.util import journal

logger = logging.getLogger("ray_tpu.serve")

CONTROLLER_NAME = "RT_SERVE_CONTROLLER"


CHECKPOINT_KEY = b"serve_controller_ckpt"


@rt.remote
class ServeController:
    def __init__(self):
        journal.set_process_label("serve-controller")
        # app name -> {deployment, replicas: [handles], version}
        self.apps: Dict[str, Dict] = {}
        self._health_fails: Dict[bytes, int] = {}
        self._lock = threading.Lock()
        # Event, not a bare bool: shutdown() runs on an actor-call thread
        # while _reconcile_loop reads it — Event gives the cross-thread
        # visibility guarantee without taking self._lock (RT006).
        self._stop = threading.Event()
        # ProxyStateManager state (reference: serve/_private/proxy_state.py
        # ProxyStateManager): when enabled, the reconcile loop keeps ONE
        # proxy actor alive on every ALIVE cluster node, pinned there by
        # node-affinity scheduling, replacing dead ones.
        self._proxy_every_node = False
        self._proxies: Dict[bytes, Dict] = {}  # node_id -> {actor, ...}
        self._proxies_reconciling = False  # single-flight across threads
        # Crash recovery (reference: controller.py:91 checkpointing via
        # KVStore + deployment_state.py:2321 _recover_from_checkpoint):
        # every mutation persists the desired state INCLUDING live replica
        # handles to the GCS KV; a restarted controller re-adopts running
        # replicas, so controller death costs no routes and no replica
        # restarts.
        # ServeSignals publication (observatory): versioned snapshot of
        # per-app load/latency/SLO state written to the GCS KV each
        # serve_signals_interval_s (rt serve + autoscalers read it).
        self._signals_seq = 0
        self._signals_last = 0.0
        # Signals-driven autoscaler hysteresis memory, one entry per app
        # (ray_tpu/serve/autoscale.py). Not checkpointed: hysteresis
        # restarts cold after a controller crash, which only delays the
        # next scaling move by one hold period.
        self._scale_state: Dict[str, "autoscale.AutoscalerState"] = {}
        self._restore()
        self._thread = threading.Thread(target=self._reconcile_loop, daemon=True)
        self._thread.start()

    # -- checkpoint / recovery --------------------------------------------
    def _checkpoint(self):
        import cloudpickle

        from ray_tpu._private import worker as worker_mod

        with self._lock:
            state = {
                "apps": {
                    name: {
                        "deployment": app["deployment"],
                        "init_args": app["init_args"],
                        "init_kwargs": app["init_kwargs"],
                        "replicas": list(app["replicas"]),
                        "version": app["version"],
                        "target": app["target"],
                    }
                    for name, app in self.apps.items()
                },
                "proxy_every_node": self._proxy_every_node,
                "proxies": {
                    nid: {"actor": e["actor"], "http": e["http"],
                          "binary": e["binary"]}
                    for nid, e in self._proxies.items()
                },
            }
        try:
            worker_mod.get_client().kv_put(
                CHECKPOINT_KEY, cloudpickle.dumps(state), ns="serve"
            )
        except Exception:  # noqa: BLE001 — next mutation retries
            logger.warning(
                "serve controller checkpoint write failed for %d app(s); "
                "a controller crash before the next mutation loses routes",
                len(state["apps"]), exc_info=True,
            )

    def _restore(self):
        import cloudpickle

        from ray_tpu._private import worker as worker_mod

        try:
            raw = worker_mod.get_client().kv_get(CHECKPOINT_KEY, ns="serve")
        except Exception:  # noqa: BLE001
            logger.warning(
                "serve controller checkpoint read failed; recovering "
                "with empty state (running replicas will be re-adopted "
                "only on redeploy)", exc_info=True,
            )
            raw = None
        if not raw:
            return
        try:
            state = cloudpickle.loads(raw)
        except Exception:  # noqa: BLE001 — corrupt checkpoint: start fresh
            logger.warning(
                "serve controller checkpoint is corrupt (%d bytes); "
                "starting fresh", len(raw), exc_info=True,
            )
            return
        now = time.monotonic()
        # _restore runs in __init__ before the reconcile thread starts,
        # but take the lock anyway so every apps/_proxy_every_node
        # write is uniformly guarded.
        with self._lock:
            for name, app in state.get("apps", {}).items():
                self.apps[name] = {
                    "deployment": app["deployment"],
                    "init_args": app["init_args"],
                    "init_kwargs": app["init_kwargs"],
                    # Live replicas are re-adopted as-is; the first
                    # health pass reaps any that died while the
                    # controller was down and reconcile replaces them.
                    "replicas": list(app["replicas"]),
                    "version": app["version"] + 1,
                    "target": app["target"],
                    "last_scale_up": now,
                    "last_scale_down": now,
                }
            self._proxy_every_node = state.get("proxy_every_node", False)
            for nid, e in state.get("proxies", {}).items():
                self._proxies[nid] = dict(e)
        # Controller failover: handles kept serving from CACHED routes
        # while we were down. Push an invalidation per app so they
        # re-sync with the restored (version-bumped) table immediately
        # instead of trusting possibly-stale caches for a full TTL.
        for name in list(self.apps):
            self._publish_routes(name)

    # -- API -------------------------------------------------------------
    @staticmethod
    def _same_except_user_config(old_app, deployment, init_args,
                                 init_kwargs) -> bool:
        """True when a redeploy matches the running app in everything
        but (possibly) user_config. With user_config also equal it is a
        no-op redeploy; with it different it is the lightweight-update
        case the reference handles by reconfigure()ing live replicas
        instead of restarting them (deployment_state.py: user_config-only
        version changes)."""
        od: Deployment = old_app["deployment"]

        def ident(obj):
            return (getattr(obj, "__module__", None),
                    getattr(obj, "__qualname__", None))

        import cloudpickle

        def same_code(a, b):
            # (module, qualname) alone is blind to an edited class body
            # redeployed under the same name; compare the serialized
            # bytes too. Any pickling instability reads as "changed" ->
            # full replace, the safe direction.
            if ident(a) != ident(b):
                return False
            try:
                return cloudpickle.dumps(a) == cloudpickle.dumps(b)
            except Exception:  # rtlint: disable=RT007 — by design:
                # pickling instability reads as "changed" -> full
                # replace, the safe direction (nothing to handle/log).
                return False

        return (
            same_code(od.func_or_class, deployment.func_or_class)
            and od.num_replicas == deployment.num_replicas
            and od.ray_actor_options == deployment.ray_actor_options
            and od.autoscaling_config == deployment.autoscaling_config
            and od.max_ongoing_requests == deployment.max_ongoing_requests
            and _safe_eq(old_app["init_args"], init_args)
            and _safe_eq(old_app["init_kwargs"], init_kwargs)
        )

    def _reconfigure_in_place(self, name: str, deployment: Deployment) -> bool:
        """Push the new user_config to every live replica. Re-snapshots
        until stable: a replica the reconcile/autoscale thread spawned
        mid-pass (constructed with the old config) gets picked up on the
        next sweep. Any failure aborts -> the caller falls back to a
        full replace (the reference marks the deployment unhealthy on
        reconfigure errors; replacing is our recovery)."""
        done: set = set()
        for _ in range(3):
            with self._lock:
                app = self.apps.get(name)
                if app is None:
                    return False
                todo = [r for r in app["replicas"]
                        if r._actor_id.binary() not in done]
            if not todo:
                return True
            refs = [r.reconfigure.remote(deployment.user_config)
                    for r in todo]
            ready, not_ready = rt.wait(
                refs, num_returns=len(refs),
                timeout=get_config().serve_ready_timeout_s,
            )
            if not_ready:
                return False
            for r, ref in zip(todo, refs):
                try:
                    rt.get(ref, timeout=1)
                except Exception:  # noqa: BLE001 — user code rejected it
                    logger.warning(
                        "replica %s of app %r rejected user_config; "
                        "falling back to full replace", r._actor_id.hex(),
                        name, exc_info=True,
                    )
                    return False
                done.add(r._actor_id.binary())
        return False  # still churning after 3 sweeps: replace instead

    def deploy(self, name: str, deployment: Deployment, init_args, init_kwargs):
        with self._lock:
            old = self.apps.get(name)
            same_core = bool(
                old and old["replicas"] and self._same_except_user_config(
                    old, deployment, init_args, init_kwargs
                )
            )
            if same_core and _safe_eq(
                old["deployment"].user_config, deployment.user_config
            ):
                # Nothing changed at all: a no-op redeploy must not
                # restart healthy replicas (reference: same-version
                # redeploys are no-ops).
                return True
            lightweight = same_core
            if lightweight:
                old["deployment"] = deployment
        if lightweight:
            # In-place reconfigure: replicas keep serving (and their
            # caches/connections) through the config change.
            if self._reconfigure_in_place(name, deployment):
                self._checkpoint()
                return True
            # Reconfigure failed somewhere: fall through to the full
            # replace below so state and replicas cannot diverge.
        with self._lock:
            old = self.apps.get(name)
            to_retire = list(old["replicas"]) if old else []
            self.apps[name] = {
                "deployment": deployment,
                "init_args": init_args,
                "init_kwargs": init_kwargs,
                "replicas": [],
                # Monotonic across redeploys so handles can compare
                # versions to detect ANY change, including replacement.
                "version": (old["version"] + 1) if old else 0,
                "target": deployment.num_replicas,
                "last_scale_up": 0.0,
                "last_scale_down": time.monotonic(),
            }
        journal.emit("serve.controller", action="deploy", app=name,
                     replicas=deployment.num_replicas)
        self._reconcile_once(name)
        self._checkpoint()
        # New replicas are up and published; the replaced generation
        # drains (finishes in-flight requests) before dying.
        self._drain_then_kill(to_retire, name)
        return True

    def delete(self, name: str):
        with self._lock:
            app = self.apps.pop(name, None)
        journal.emit("serve.controller", action="delete", app=name)
        self._checkpoint()
        if app:
            # Short drain on delete: in-flight requests get a grace
            # window without making serve.shutdown() (which deletes
            # every app) wait out the full drain budget per app.
            self._drain_then_kill(
                app["replicas"], name,
                timeout_s=min(get_config().serve_drain_timeout_s, 1.0),
            )
        return True

    def get_replicas(self, name: str):
        with self._lock:
            app = self.apps.get(name)
            if app is None:
                return {"version": -1, "replicas": [], "max_ongoing": 0}
            return {
                "version": app["version"],
                "replicas": list(app["replicas"]),
                "max_ongoing": app["deployment"].max_ongoing_requests,
                # Prefix-affinity hints (paged KV): actor_id hex -> list of
                # first-page prefix hashes resident in that replica's
                # cache, refreshed each signals tick. Handles route
                # matching prompts to a covering replica.
                "prefix": dict(app.get("prefix_routes") or {}),
                "page_size": app.get("kv_page_size") or 0,
            }

    def status(self) -> Dict:
        with self._lock:
            return {
                name: {
                    "target_replicas": app["target"],
                    "running_replicas": len(app["replicas"]),
                    "deployment": app["deployment"].name,
                }
                for name, app in self.apps.items()
            }

    def shutdown(self):
        self._stop.set()
        with self._lock:
            names = list(self.apps)
        for n in names:
            self.delete(n)
        with self._lock:
            entries = list(self._proxies.values())
            self._proxies.clear()
        for entry in entries:
            _kill_quietly(entry["actor"])
        try:
            from ray_tpu._private import worker as worker_mod

            worker_mod.get_client().kv_del(CHECKPOINT_KEY, ns="serve")
        except Exception:  # noqa: BLE001
            logger.warning(
                "serve shutdown could not delete the controller "
                "checkpoint; a restarted controller will re-adopt "
                "stale state", exc_info=True,
            )
        return True

    # -- reconciliation ---------------------------------------------------
    def _reconcile_once(self, name: str):
        with self._lock:
            app = self.apps.get(name)
            if app is None:
                return
            dep: Deployment = app["deployment"]
            current = len(app["replicas"])
            target = app["target"]
        if current < target:
            new = []
            for _ in range(target - current):
                opts = dict(dep.ray_actor_options)
                replica = ReplicaActor.options(
                    num_cpus=opts.pop("num_cpus", 0.1),
                    resources=opts.pop("resources", None),
                    # Priority tier rides the actor options: a latency-
                    # critical deployment's pending replica may reclaim
                    # chips from lower-priority gangs.
                    priority=opts.pop("priority", 0),
                    # Concurrent request execution inside the replica: the
                    # substrate @serve.batch coalesces across (capped so a
                    # misconfigured deployment can't demand 100 threads).
                    max_concurrency=min(dep.max_ongoing_requests, 32),
                ).remote(
                    dep.func_or_class,
                    app["init_args"],
                    app["init_kwargs"],
                    dep.user_config,
                    name,
                    getattr(dep, "slo", None),
                    dep.max_ongoing_requests,
                )
                new.append(replica)
            with self._lock:
                app["replicas"].extend(new)
                app["version"] += 1
            journal.emit("serve.controller", action="scale_up", app=name,
                         added=len(new), target=target)
            self._publish_routes(name)
            self._checkpoint()
        elif current > target:
            with self._lock:
                excess = app["replicas"][target:]
                app["replicas"] = app["replicas"][:target]
                app["version"] += 1
            journal.emit("serve.controller", action="scale_down", app=name,
                         removed=len(excess), target=target)
            # Routes flip FIRST (handles stop picking the victims), then
            # the victims drain: new requests they still receive bounce
            # with ReplicaDrainingError and redispatch, in-flight ones
            # finish, and only then does the process die.
            self._publish_routes(name)
            self._checkpoint()
            self._drain_then_kill(excess, name)

    def _drain_then_kill(self, replicas: List, name: str = "",
                         timeout_s: Optional[float] = None):
        """Graceful scale-down/replace: each victim stops admitting
        (handles redispatch its refusals), finishes in-flight work —
        bounded by serve_drain_timeout_s — and only then is killed.
        One collective wait bounds the whole pass; a replica that cannot
        drain in time is killed anyway (drain improves the common case,
        the kill below is the guarantee)."""
        if not replicas:
            return
        cfg = get_config()
        if timeout_s is None:
            timeout_s = cfg.serve_drain_timeout_s
        refs = [r.drain.remote(timeout_s) for r in replicas]
        ready, _ = rt.wait(refs, num_returns=len(refs),
                           timeout=timeout_s + 2.0)
        ready_set = set(ready)
        for r, ref in zip(replicas, refs):
            if ref in ready_set:
                try:
                    res = rt.get(ref, timeout=1.0)
                    logger.info(
                        "replica %s of app %r drained in %.3fs "
                        "(remaining=%d)", r._actor_id.hex(), name,
                        res.get("duration_s", 0.0),
                        res.get("remaining", 0),
                    )
                except Exception:  # rtlint: disable=RT007 — drain is best-effort; the kill below is the guarantee
                    pass
            _kill_quietly(r)

    def _publish_routes(self, name: str):
        """Push a routing-table invalidation to subscribed handles — the
        LongPollHost role (serve/_private/long_poll.py:175): handles learn
        of replica set changes immediately instead of on their poll TTL."""
        try:
            from ray_tpu._private import worker as worker_mod

            with self._lock:
                version = self.apps[name]["version"]
            journal.emit("serve.controller", action="route_flip", app=name,
                         version=version)
            worker_mod.get_client().publish(
                f"serve_routes:{name}", {"version": version}
            )
        except Exception:  # noqa: BLE001 — handles fall back to polling
            logger.debug("route-invalidation push failed for app %r "
                         "(handles fall back to polling)", name,
                         exc_info=True)

    def _publish_signals(self):
        """Assemble and publish the ServeSignals snapshot (observatory).

        Fans out observatory_snapshot() to every replica, merges per app
        (QPS sums, occupancy averages, latency sample sets pool before
        the percentile cut, per-tenant SLO window counts add before the
        burn-rate division — burn of sums, not mean of burns), and
        writes ONE versioned JSON document to the GCS KV under
        ns="serve"/serve_signals. Read path needs no actors: rt serve
        and autoscalers kv_get it straight off the GCS."""
        from ray_tpu.serve import observatory

        cfg = get_config()
        if not cfg.serve_observatory:
            return
        now = time.monotonic()
        if now - self._signals_last < cfg.serve_signals_interval_s:
            return
        self._signals_last = now
        with self._lock:
            app_replicas = {
                name: list(app["replicas"]) for name, app in self.apps.items()
            }
        doc = {
            "schema": observatory.SIGNALS_SCHEMA_VERSION,
            "seq": self._signals_seq,
            "ts": time.time(),
            "apps": {},
        }
        self._signals_seq += 1
        for name, replicas in app_replicas.items():
            snaps = []
            refs = [r.observatory_snapshot.remote() for r in replicas]
            ready, _ = rt.wait(
                refs, num_returns=len(refs),
                timeout=cfg.serve_probe_timeout_s,
            )
            per_replica = []
            prefix_routes: Dict[str, List[str]] = {}
            page_size = 0
            for r, ref in zip(replicas, refs):
                entry = {
                    "actor_id": r._actor_id.hex(),
                    "health_fails": self._health_fails.get(
                        r._actor_id.binary(), 0
                    ),
                }
                if ref in ready:
                    try:
                        snap = rt.get(ref, timeout=1.0)
                        snaps.append(snap)
                        entry["ongoing"] = snap.get("ongoing")
                        entry["total_served"] = snap.get("total_served")
                        entry["qps"] = snap.get("qps")
                        kv = (snap.get("engine") or {}).get("kv") or {}
                        if kv.get("mode") == "paged":
                            entry["kv_util"] = kv.get("util")
                            entry["prefix_hit_rate"] = kv.get(
                                "prefix_hit_rate")
                            entry["prefill_tokens_skipped"] = kv.get(
                                "prefill_tokens_skipped")
                            if kv.get("roots"):
                                prefix_routes[entry["actor_id"]] = list(
                                    kv["roots"])
                            page_size = kv.get("page_size") or page_size
                    except Exception:  # rtlint: disable=RT007 — replica mid-death; marked unreachable
                        entry["unreachable"] = True
                else:
                    entry["unreachable"] = True
                per_replica.append(entry)
            app_sig = self._merge_app_signals(name, snaps, per_replica, cfg)
            with self._lock:
                app = self.apps.get(name)
                if app is not None:
                    # Cached for get_replicas(): handles learn prefix
                    # residency on their normal routing-table refresh, no
                    # extra RPC.
                    app["prefix_routes"] = prefix_routes
                    app["kv_page_size"] = page_size
                    app_sig["target_replicas"] = app["target"]
                    app_sig["running_replicas"] = len(app["replicas"])
            doc["apps"][name] = app_sig
        try:
            from ray_tpu._private import worker as worker_mod

            worker_mod.get_client().kv_put(
                observatory.SIGNALS_KEY,
                json.dumps(doc).encode(),
                ns="serve",
            )
        except Exception:  # noqa: BLE001 — next tick republishes
            logger.debug("ServeSignals publish failed", exc_info=True)

    @staticmethod
    def _merge_app_signals(name, snaps, per_replica, cfg):
        from ray_tpu.serve import observatory

        qps = sum(s.get("qps") or 0.0 for s in snaps)
        ttft = sorted(x for s in snaps for x in s.get("ttft_samples") or [])
        tpot = sorted(x for s in snaps for x in s.get("tpot_samples") or [])
        phases: Dict[str, Dict[str, float]] = {}
        fractions = [s["phase_sum_fraction"] for s in snaps
                     if s.get("phase_sum_fraction") is not None]
        for s in snaps:
            for phase, row in (s.get("phases") or {}).items():
                agg = phases.setdefault(phase, {"sum_s": 0.0, "count": 0})
                agg["sum_s"] += row["sum_s"]
                agg["count"] += row["count"]
        waiting = sum(
            (s.get("engine") or {}).get("waiting") or 0 for s in snaps
        )
        occ = [
            (s.get("engine") or {}).get("occupancy")
            for s in snaps if (s.get("engine") or {}).get("occupancy") is not None
        ]
        hol_s = sum(
            ((s.get("engine") or {}).get("hol") or {})
            .get("blocked_slot_seconds") or 0.0
            for s in snaps
        )
        hol_events = [
            ev for s in snaps
            for ev in (((s.get("engine") or {}).get("hol") or {})
                       .get("events") or [])
        ]
        hol_events.sort(key=lambda e: e.get("ts", 0.0))
        slo = next((s["slo"] for s in snaps if s.get("slo")), None)
        objective = (slo or {}).get("objective", 0.99)
        # Per-tenant merge: window counts ADD across replicas, then one
        # burn-rate division over the pooled counts.
        tenants: Dict[str, Dict] = {}
        for s in snaps:
            for tname, t in (s.get("tenants") or {}).items():
                agg = tenants.setdefault(tname, {
                    "requests": 0, "tokens_in": 0, "tokens_out": 0,
                    "queue_s": 0.0, "slo_windows": {},
                })
                for k in ("requests", "tokens_in", "tokens_out"):
                    agg[k] += t.get(k) or 0
                agg["queue_s"] += t.get("queue_s") or 0.0
                for w, kinds in (t.get("slo_windows") or {}).items():
                    aw = agg["slo_windows"].setdefault(w, {})
                    for kind, row in kinds.items():
                        ar = aw.setdefault(kind, {"good": 0, "total": 0})
                        ar["good"] += row["good"]
                        ar["total"] += row["total"]
        for t in tenants.values():
            for kinds in t["slo_windows"].values():
                for row in kinds.values():
                    row["burn"] = observatory.burn_rate(
                        row["good"], row["total"], objective
                    )
        # Paged-KV aggregate (schema v2): pooled page counts across
        # replicas, one hit-rate division over pooled lookups.
        kv_snaps = [
            (s.get("engine") or {}).get("kv") or {} for s in snaps
        ]
        kv_snaps = [k for k in kv_snaps if k.get("mode") == "paged"]
        kv_agg = None
        if kv_snaps:
            hits = sum(k.get("prefix_hits") or 0 for k in kv_snaps)
            misses = sum(k.get("prefix_misses") or 0 for k in kv_snaps)
            total = sum(k.get("pages_total") or 0 for k in kv_snaps)
            in_use = sum(k.get("pages_in_use") or 0 for k in kv_snaps)
            kv_agg = {
                "page_size": kv_snaps[0].get("page_size"),
                "pages_total": total,
                "pages_in_use": in_use,
                "util": (in_use / total) if total else None,
                "prefix_hit_rate": (
                    hits / (hits + misses) if (hits + misses) else None
                ),
                "prefill_tokens_skipped": sum(
                    k.get("prefill_tokens_skipped") or 0 for k in kv_snaps
                ),
            }
        return {
            "replicas": per_replica,
            "qps": qps,
            "waiting": waiting,
            "occupancy": sum(occ) / len(occ) if occ else None,
            # Backlog-drain estimate: queued requests over current
            # throughput — how many seconds of arrivals are waiting.
            "backlog_drain_s": (waiting / qps) if qps > 0 else None,
            "ttft_s": {
                "p50": observatory.percentile(ttft, 0.50),
                "p99": observatory.percentile(ttft, 0.99),
                "n": len(ttft),
            },
            "tpot_s": {
                "p50": observatory.percentile(tpot, 0.50),
                "p99": observatory.percentile(tpot, 0.99),
                "n": len(tpot),
            },
            "phases": phases,
            "phase_sum_fraction": (
                sum(fractions) / len(fractions) if fractions else None
            ),
            "hol": {"blocked_slot_seconds": hol_s,
                    "events": hol_events[-16:]},
            "slo": slo,
            "tenants": tenants,
            "kv": kv_agg,
        }

    def _reconcile_loop(self):
        while not self._stop.is_set():
            time.sleep(get_config().serve_reconcile_interval_s)
            try:
                with self._lock:
                    names = list(self.apps)
                    proxy_mode = self._proxy_every_node
                for name in names:
                    self._check_replica_health(name)
                    self._evict_draining_replicas(name)
                    self._autoscale(name)
                    self._reconcile_once(name)
                if proxy_mode:
                    self._reconcile_proxies()
                self._publish_signals()
            except Exception:  # noqa: BLE001 — keep reconciling; next
                # tick retries. Logged, not swallowed: a persistent error
                # here silently freezes replica replacement (it did once).
                logging.getLogger("ray_tpu.serve").exception(
                    "serve controller reconcile tick failed"
                )

    # -- proxy state manager ---------------------------------------------
    def start_proxies(self) -> int:
        """Enable one-proxy-per-node mode; returns the current live-node
        count (proxies come up within a reconcile tick)."""
        with self._lock:
            self._proxy_every_node = True
        self._reconcile_proxies()
        with self._lock:
            return len(self._proxies)

    def _alive_nodes(self):
        from ray_tpu._private import worker as worker_mod

        client = worker_mod.get_client()
        nodes = client._run(client._gcs_call("get_nodes", {}))["nodes"]
        return [n for n in nodes if n.get("state") == "ALIVE"]

    def _reconcile_proxies(self):
        """Called from both the actor-call thread (start_proxies) and the
        reconcile daemon thread: single-flighted, and every _proxies
        read/write happens under self._lock (the slow actor RPCs do not)."""
        from ray_tpu.serve.proxy import ProxyActor
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        with self._lock:
            if self._proxies_reconciling:
                return
            self._proxies_reconciling = True
        try:
            alive = {n["node_id"]: n for n in self._alive_nodes()}
            with self._lock:
                existing = dict(self._proxies)
            # Reap proxies on dead nodes / dead proxy actors.
            for node_id, entry in existing.items():
                dead = node_id not in alive
                if not dead:
                    try:
                        rt.get(entry["actor"].ready.remote(),
                               timeout=get_config().serve_probe_timeout_s)
                    except (ActorError, WorkerCrashedError,
                            GetTimeoutError):
                        # Only actor-death/unreachable errors mean the
                        # proxy is gone; anything else (a controller-side
                        # bug) should surface, not silently kill proxies.
                        dead = True
                if dead:
                    _kill_quietly(entry["actor"])
                    with self._lock:
                        self._proxies.pop(node_id, None)
            for node_id in alive:
                with self._lock:
                    if node_id in self._proxies:
                        continue
                try:
                    actor = ProxyActor.options(
                        num_cpus=0.01,
                        scheduling_strategy=NodeAffinitySchedulingStrategy(
                            node_id=node_id
                        ),
                    ).remote("127.0.0.1", 0)
                    rt.get(actor.ready.remote(),
                           timeout=get_config().serve_ready_timeout_s)
                    entry = {
                        "actor": actor,
                        "http": rt.get(actor.address.remote(),
                                       timeout=get_config().serve_probe_timeout_s),
                        "binary": rt.get(
                            actor.binary_address.remote(),
                            timeout=get_config().serve_probe_timeout_s,
                        ),
                    }
                    with self._lock:
                        self._proxies[node_id] = entry
                except Exception:  # noqa: BLE001 — retried next tick
                    nid = (node_id.hex()
                           if isinstance(node_id, (bytes, bytearray))
                           else node_id)
                    logger.warning(
                        "proxy spawn failed on node %s; retried next "
                        "reconcile tick", nid, exc_info=True,
                    )
            self._checkpoint()
        finally:
            with self._lock:
                self._proxies_reconciling = False

    def proxy_addresses(self) -> Dict[str, Dict]:
        """node_id hex -> {http, binary} for every live proxy."""
        with self._lock:
            entries = dict(self._proxies)
        return {
            nid.hex() if isinstance(nid, (bytes, bytearray)) else str(nid): {
                "http": e["http"],
                "binary": list(e["binary"]),
            }
            for nid, e in entries.items()
        }

    @staticmethod
    def _actor_state(actor_id: bytes) -> Optional[str]:
        """GCS-recorded state of an actor ("ALIVE"/"DEAD"/...), or None
        when the lookup fails (treat as unknown, fall back to the
        consecutive-failure threshold)."""
        try:
            from ray_tpu._private import worker as worker_mod

            client = worker_mod.get_client()
            info = client._run(
                client._gcs_call("get_actor", {"actor_id": actor_id})
            )["actor"]
            return info["state"] if info else None
        except Exception:  # noqa: BLE001 — control-plane hiccup
            logger.debug("GCS actor-state lookup failed for %s (treated "
                         "as unknown)", actor_id.hex(), exc_info=True)
            return None

    def _evict_draining_replicas(self, name: str):
        """Graceful replica eviction off draining nodes (the preemption /
        maintenance path): route-flip first, then the PR 8 drain-then-kill,
        and _reconcile_once respawns the lost count elsewhere — the GCS
        never places a new actor on a draining node. Zero lost non-shed
        requests: victims stop receiving new work before they die."""
        try:
            draining = {
                n["node_id"] for n in self._alive_nodes()
                if n.get("draining")
            }
        except Exception:  # noqa: BLE001 — control-plane hiccup; next tick
            logger.debug("draining-node sweep could not list nodes for "
                         "app %r (retried next tick)", name, exc_info=True)
            return
        if not draining:
            return
        with self._lock:
            app = self.apps.get(name)
            if app is None:
                return
            replicas = list(app["replicas"])
        victims = []
        for r in replicas:
            try:
                from ray_tpu._private import worker as worker_mod

                client = worker_mod.get_client()
                info = client._run(
                    client._gcs_call(
                        "get_actor", {"actor_id": r._actor_id.binary()}
                    )
                )["actor"]
            except Exception:  # noqa: BLE001 — lookup hiccup; next tick
                logger.debug("replica node lookup failed for app %r "
                             "(retried next tick)", name, exc_info=True)
                continue
            if (
                info
                and info.get("state") == "ALIVE"
                and info.get("node_id") in draining
            ):
                victims.append(r)
        if not victims:
            return
        victim_ids = {v._actor_id.binary() for v in victims}
        with self._lock:
            app = self.apps.get(name)
            if app is None:
                return
            app["replicas"] = [
                r for r in app["replicas"]
                if r._actor_id.binary() not in victim_ids
            ]
            app["version"] += 1
        logger.warning(
            "evicting %d replica(s) of app %r from draining node(s)",
            len(victims), name,
        )
        journal.emit("serve.controller", action="evict_draining", app=name,
                     victims=len(victims))
        self._publish_routes(name)
        self._checkpoint()
        self._drain_then_kill(victims, name)

    def _check_replica_health(self, name: str):
        """Drop dead replicas so reconcile replaces them — the
        DeploymentState failure-recovery role (deployment_state.py:1211).
        Probes run in PARALLEL (one slow app must not stall the reconcile
        loop) and a replica is declared dead only after 3 consecutive
        failed probes, so a replica that is briefly saturated (all
        concurrency slots busy) or still loading a model is not killed.
        Exception: a probe that fails with an actor-death error, or whose
        actor the GCS already marked DEAD, is replaced immediately — the
        threshold protects slow-but-alive replicas, not corpses."""
        with self._lock:
            app = self.apps.get(name)
            if app is None:
                return
            replicas = list(app["replicas"])
        if not replicas:
            return
        refs = [r.health_check.remote() for r in replicas]
        # One collective wait bounds the whole pass (serve_health_wait_s)
        # regardless of how many replicas are hung.
        ready, _not_ready = rt.wait(refs, num_returns=len(refs),
                                    timeout=get_config().serve_health_wait_s)
        ready_set = set(ready)
        dead = []
        for r, ref in zip(replicas, refs):
            key = r._actor_id.binary()
            healthy = False
            actor_dead = False
            if ref in ready_set:
                try:
                    rt.get(ref, timeout=get_config().serve_probe_timeout_s)
                    healthy = True
                except (ActorError, WorkerCrashedError):
                    # The probe failed because the actor PROCESS is gone,
                    # not because the replica was slow — there is nothing
                    # a second probe could learn.
                    actor_dead = True
                except Exception:  # noqa: BLE001 — call errored: unhealthy
                    logger.warning(
                        "health probe errored for replica %s of app %r "
                        "(failure %d/%d)", r._actor_id.hex(), name,
                        self._health_fails.get(key, 0) + 1,
                        get_config().serve_health_fail_threshold,
                        exc_info=True,
                    )
            elif self._actor_state(key) == "DEAD":
                # Probe never completed AND the GCS already declared the
                # actor dead (its worker lost the raylet connection).
                actor_dead = True
            if healthy:
                self._health_fails.pop(key, None)
                continue
            if actor_dead:
                # Confirmed death bypasses the consecutive-failure
                # threshold: the threshold exists to tolerate saturated-
                # but-alive replicas, and waiting it out here just leaves
                # a known-dead replica in the route table for two more
                # reconcile ticks.
                dead.append(r)
                continue
            fails = self._health_fails.get(key, 0) + 1
            self._health_fails[key] = fails
            if fails >= get_config().serve_health_fail_threshold:
                dead.append(r)
        if not dead:
            return
        for r in dead:
            self._health_fails.pop(r._actor_id.binary(), None)
        dead_ids = {d._actor_id.binary() for d in dead}
        with self._lock:
            app = self.apps.get(name)
            if app is None:
                return
            app["replicas"] = [
                r for r in app["replicas"]
                if r._actor_id.binary() not in dead_ids
            ]
            app["version"] += 1
        # A replica the controller had to declare dead is a cluster-
        # visible failure: journal the replacement and freeze the black
        # box so the postmortem shows what killed it.
        journal.emit("serve.controller", action="replace_dead", app=name,
                     dead=[d._actor_id.hex() for d in dead])
        journal.trigger_postmortem(
            f"replica_dead:{name}", app=name,
            dead=[d._actor_id.hex() for d in dead],
        )
        self._publish_routes(name)
        self._checkpoint()
        for r in dead:
            _kill_quietly(r)

    def _autoscale(self, name: str):
        """Replica autoscaling off the published ServeSignals snapshot.

        ONE `kv_get` of the observatory document, zero actor calls: the
        signal plane (PR 7) already carries ongoing requests, admission
        queue depth, TTFT percentiles and SLO burn per app, so the
        decision (ray_tpu/serve/autoscale.py) is a pure function over
        the snapshot with per-app hysteresis memory. Falls back to the
        legacy per-replica queue-length probe when the snapshot is
        missing or stale (observatory disabled, first ticks after boot,
        publisher wedged) — autoscaling never goes blind just because
        telemetry did."""
        with self._lock:
            app = self.apps.get(name)
            if app is None:
                return
            acfg: Optional[AutoscalingConfig] = (
                app["deployment"].autoscaling_config)
            target = app["target"]
            running = len(app["replicas"])
        if acfg is None or running == 0:
            return
        cfg = get_config()
        app_sig = None
        if cfg.serve_observatory:
            from ray_tpu.serve import observatory

            try:
                from ray_tpu._private import worker as worker_mod

                raw = worker_mod.get_client().kv_get(
                    observatory.SIGNALS_KEY, ns="serve")
                doc = json.loads(raw) if raw else None
            except Exception:  # rtlint: disable=RT007 — doc=None routes to the queue-probe fallback below
                doc = None
            stale_after = max(3 * cfg.serve_signals_interval_s, 5.0)
            if doc and time.time() - float(doc.get("ts") or 0) <= stale_after:
                app_sig = (doc.get("apps") or {}).get(name)
        if app_sig is None:
            return self._autoscale_probe(name)
        # _scale_state is only touched on the reconcile thread.
        state = self._scale_state.setdefault(
            name, autoscale.AutoscalerState())
        now = time.monotonic()
        new_target = autoscale.decide(
            app_sig, acfg, state, now, target, running)
        m = _controller_metrics()
        m["as_target"].set(float(new_target), tags={"app": name})
        m["as_actual"].set(float(running), tags={"app": name})
        if new_target == target:
            return
        with self._lock:
            app = self.apps.get(name)
            # Bail if the app vanished or someone else moved the target
            # (redeploy) between our read and this write.
            if app is None or app["target"] != target:
                return
            app["target"] = new_target
            if new_target > target:
                app["last_scale_up"] = now
            else:
                app["last_scale_down"] = now
        logger.info("autoscaler: app %r target %d -> %d (%s)",
                    name, target, new_target, state.last_reason)
        journal.emit("serve.controller", action="autoscale", app=name,
                     old_target=target, new_target=new_target,
                     reason=state.last_reason)
        self._checkpoint()

    def _autoscale_probe(self, name: str):
        """Legacy queue-length autoscaling (reference:
        autoscaling_policy.py): probes every replica's queue depth with
        an actor call. Kept as the fallback for when ServeSignals are
        unavailable."""
        with self._lock:
            app = self.apps.get(name)
            if app is None:
                return
            cfg: Optional[AutoscalingConfig] = app["deployment"].autoscaling_config
            replicas = list(app["replicas"])
        if cfg is None or not replicas:
            return
        try:
            qlens = rt.get([r.queue_len.remote() for r in replicas],
                           timeout=get_config().serve_probe_timeout_s)
        except Exception:  # noqa: BLE001 — next tick re-probes
            logger.debug("autoscale queue-length probe failed for app "
                         "%r; skipping this tick", name, exc_info=True)
            return
        avg = sum(qlens) / len(qlens)
        now = time.monotonic()
        with self._lock:
            app = self.apps.get(name)
            if app is None:
                return
            target = app["target"]
            changed = False
            if avg > cfg.target_ongoing_requests and target < cfg.max_replicas:
                if now - app["last_scale_up"] > cfg.upscale_delay_s:
                    app["target"] = min(target + 1, cfg.max_replicas)
                    app["last_scale_up"] = now
                    changed = True
            elif avg < cfg.target_ongoing_requests * 0.5 and target > cfg.min_replicas:
                if now - app["last_scale_down"] > cfg.downscale_delay_s:
                    app["target"] = max(target - 1, cfg.min_replicas)
                    app["last_scale_down"] = now
                    changed = True
        if changed:
            self._checkpoint()


_METRICS: Optional[Dict[str, Any]] = None


def _controller_metrics() -> Dict[str, Any]:
    # Lazy: the metrics registry must not be touched at import time
    # (same discipline as llm._engine_metrics).
    global _METRICS
    if _METRICS is None:
        from ray_tpu.util.metrics import Gauge, get_or_create

        _METRICS = {
            "as_target": get_or_create(
                Gauge, "serve_autoscaler_target_replicas",
                "Autoscaler's desired replica count per app.",
                tag_keys=("app",)),
            "as_actual": get_or_create(
                Gauge, "serve_autoscaler_actual_replicas",
                "Running replica count per app as seen by the autoscaler.",
                tag_keys=("app",)),
        }
    return _METRICS


def _safe_eq(a, b) -> bool:  # rtlint: disable=RT007
    # Array-like args make == elementwise; any ambiguity (or raising
    # comparison) counts as "changed" -> full replace, never a crash.
    try:
        return bool(a == b)
    except Exception:  # noqa: BLE001
        return False


def _kill_quietly(actor):  # rtlint: disable=RT007
    # Best-effort teardown of an actor that may already be gone; any
    # error here means "nothing left to kill".
    try:
        rt.kill(actor)
    except Exception:
        pass


def get_or_create_controller():
    try:
        return rt.get_actor(CONTROLLER_NAME)
    except ValueError:
        pass
    try:
        return ServeController.options(
            name=CONTROLLER_NAME, num_cpus=0.1, max_restarts=-1
        ).remote()
    except ValueError:
        # Raced with another creator.
        return rt.get_actor(CONTROLLER_NAME)
