"""Deployments: the unit of serving.

Analog of the reference's @serve.deployment + Deployment/Application
objects (python/ray/serve/api.py, serve/deployment.py): a decorated class
or function plus replica/autoscaling config; `.bind(...)` produces an
application graph node for `serve.run`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Per-deployment autoscaling (reference:
    serve/_private/autoscaling_policy.py + serve/config.py)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 10.0
    # Signals-driven pressure thresholds (ray_tpu/serve/autoscale.py).
    # Queued-per-replica above this is upscale pressure even while
    # ongoing looks fine (saturation shows in the admission queue first).
    upscale_queue_depth: Optional[float] = 1.0
    # Opt-in latency/SLO pressure: None disables each signal.
    ttft_p99_high_ms: Optional[float] = None
    burn_rate_high: Optional[float] = None


@dataclass
class SloConfig:
    """Declared latency objectives the request observatory scores every
    finished request against, per tenant (observatory.RequestProfiler).

    Each `*_ms` bound is optional: declare only the dimensions that
    matter for the deployment (TTFT/TPOT only make sense for token
    streams; e2e applies everywhere). `objective` is the attainment
    target the burn-rate math divides by — 0.99 means a 1% error
    budget, and a burn rate of 1.0 consumes it exactly on schedule.
    """

    ttft_ms: Optional[float] = None   # time-to-first-token bound
    tpot_ms: Optional[float] = None   # mean time-per-output-token bound
    e2e_ms: Optional[float] = None    # end-to-end request wall bound
    objective: float = 0.99


@dataclass
class Deployment:
    func_or_class: Any
    name: str
    num_replicas: int = 1
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    max_ongoing_requests: int = 100
    autoscaling_config: Optional[AutoscalingConfig] = None
    user_config: Optional[Dict] = None
    slo: Optional[SloConfig] = None

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def options(self, **overrides) -> "Deployment":
        import copy

        d = copy.copy(self)
        for k, v in overrides.items():
            if not hasattr(d, k):
                raise ValueError(f"unknown deployment option {k!r}")
            if k == "slo" and isinstance(v, dict):
                v = SloConfig(**v)
            setattr(d, k, v)
        return d


@dataclass
class Application:
    """A bound deployment graph node (reference: Application from .bind())."""

    deployment: Deployment
    init_args: tuple
    init_kwargs: dict


def deployment(
    _func_or_class: Optional[Any] = None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    ray_actor_options: Optional[Dict] = None,
    max_ongoing_requests: int = 100,
    autoscaling_config: Optional[AutoscalingConfig] = None,
    user_config: Optional[Dict] = None,
    slo: Optional[SloConfig] = None,
):
    """@serve.deployment decorator (reference: serve/api.py)."""
    if isinstance(slo, dict):
        slo = SloConfig(**slo)

    def wrap(obj):
        return Deployment(
            func_or_class=obj,
            name=name or getattr(obj, "__name__", "deployment"),
            num_replicas=num_replicas,
            ray_actor_options=ray_actor_options or {},
            max_ongoing_requests=max_ongoing_requests,
            autoscaling_config=autoscaling_config,
            user_config=user_config,
            slo=slo,
        )

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
