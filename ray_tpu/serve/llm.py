"""Continuous batching for LLM serving.

The reference's dynamic batcher (python/ray/serve/batching.py) coalesces
requests that ARRIVE together; a static batch then decodes in lockstep
until every member finishes, so at mixed arrival times most of the chip
sits idle (a 1-token straggler pins the whole batch). This module goes
past it: a decode loop over a SLOTTED kv-cache where requests join at
any step boundary (prefill interleaved between decode steps), emit
tokens as they are produced, and free their slot the moment they finish
— the vLLM-style iteration-level scheduling, built TPU-first:

  * Static shapes everywhere: the decode step is jitted ONCE for the
    slot count and prompts prefill in fixed-size CHUNKS (one chunk
    between decode steps — chunked prefill: a long prompt never stalls
    other slots' decoding for more than a chunk), so compilation count
    is bounded and none happens mid-traffic after warmup.
  * Per-slot sequence lengths live in device memory; attention masks by
    each slot's own length, so one batched decode serves slots whose
    sequences started at different times.
  * Cache buffers are donated through the step, so decode updates the
    KV cache in place (no per-step reallocation of the big buffer).
  * The steady-state hot loop does ZERO avoidable host<->device traffic
    per step: sampling params and the active mask are device-resident
    (re-uploaded only on slot admission/eviction), step outputs come
    back through an async double-buffered copy (dispatch step k+1,
    drain step k's already-landed buffer), both decode variants compile
    at engine construction (greedy<->sampled traffic flips never
    compile mid-serving), and stats() exposes the per-step breakdown
    (dispatch/fetch/host ms, compile and upload counters) that proves
    it — the T3-style overlap discipline (arXiv:2401.16677) applied to
    decode, with EQuARX-style step decomposition (arXiv:2506.17615).

Reference provenance: serve/batching.py (the mechanism surpassed);
BASELINE.json configs[4] (the serving north-star).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu._private import chaos
from ray_tpu._private.config import get_config
from ray_tpu.exceptions import (
    PromptTooLongError,
    RequestCancelledError,
    ServeOverloadedError,
)
from ray_tpu.serve import context as request_context
from ray_tpu.serve import observatory
from ray_tpu.serve import paged_kv
from ray_tpu.models.transformer import (
    TransformerConfig,
    _act,
    _embed_tokens,
    project_logits,
)
from ray_tpu.ops import apply_rope, rmsnorm, rope_frequencies

NEG_INF = -1e30

_STEP_MS_BOUNDARIES = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                       100.0, 250.0)
_metrics_lock = threading.Lock()
_metrics: Optional[Dict] = None


def _engine_metrics() -> Dict:
    """Module-level serving metrics (ray_tpu.util.metrics): one set per
    process, shared by every engine, flushed to GCS/Prometheus by the
    metrics flusher. Created lazily so importing llm.py never spins up
    the flusher thread."""
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util import metrics as _mx
            from ray_tpu.util.metrics import Counter, Gauge, Histogram

            _metrics = {
                "dispatch_ms": Histogram(
                    "serve_llm_step_dispatch_ms",
                    "Decode-step dispatch time (enqueue the jitted step)",
                    boundaries=_STEP_MS_BOUNDARIES,
                ),
                "fetch_ms": Histogram(
                    "serve_llm_step_fetch_ms",
                    "Blocking time draining the previous step's async "
                    "device->host token copy",
                    boundaries=_STEP_MS_BOUNDARIES,
                ),
                "host_ms": Histogram(
                    "serve_llm_step_host_ms",
                    "Host-side engine work per step (scheduling, token "
                    "distribution, locking)",
                    boundaries=_STEP_MS_BOUNDARIES,
                ),
                "recompiles": Counter(
                    "serve_llm_recompiles_total",
                    "Jit compilations observed AFTER engine warmup "
                    "(steady-state traffic should never compile)",
                ),
                "param_uploads": Counter(
                    "serve_llm_param_uploads_total",
                    "Host->device sampling-param/active-mask refreshes "
                    "(only on slot admission/eviction, never per step)",
                ),
                # Request-level latency (flight recorder): TTFT is
                # submit->first token (queue wait + prefill), TPOT the
                # mean inter-token interval after the first. Seconds,
                # sub-ms-resolving boundaries.
                "ttft_s": Histogram(
                    "serve_llm_ttft_seconds",
                    "Time to first token: submit() to the first pushed "
                    "token, per request",
                    # Wide tail: queue wait under macro load pushes TTFT
                    # p99 multi-second; don't clamp it into +Inf.
                    boundaries=_mx.LATENCY_BOUNDARIES_WIDE,
                ),
                "tpot_s": Histogram(
                    "serve_llm_tpot_seconds",
                    "Time per output token after the first (decode-rate "
                    "inverse), per finished request",
                    boundaries=_mx.LATENCY_BOUNDARIES,
                ),
                "occupancy": Gauge(
                    "serve_llm_batch_occupancy",
                    "Decoding slots in use / total slots, sampled every "
                    "engine step (how full the continuous batch runs)",
                ),
                "waiting": Gauge(
                    "serve_llm_waiting_requests",
                    "Requests enqueued but not yet granted a decode slot "
                    "(admission queue depth; the backlog half of the "
                    "autoscaling signal next to occupancy)",
                ),
                "admission_wait_s": Histogram(
                    "serve_llm_admission_wait_seconds",
                    "submit() enqueue to decode-slot grant, per request "
                    "(pure queueing: saturation shows here before TTFT)",
                    boundaries=_mx.LATENCY_BOUNDARIES,
                ),
                "hol_s": Counter(
                    "serve_hol_blocked_seconds_total",
                    "Decode-slot-seconds stalled behind prefill passes "
                    "crossing serve_hol_threshold_s (head-of-line "
                    "blocking attributed to the long prefill causing it)",
                ),
                # Paged KV memory plane (ray_tpu/serve/paged_kv).
                "kv_pages": Gauge(
                    "serve_kv_pages_in_use",
                    "KV page-pool pages currently referenced (request "
                    "block tables + prefix-cache entries), sampled every "
                    "engine step",
                ),
                "prefix_hits": Counter(
                    "serve_prefix_cache_hits_total",
                    "Admissions whose prompt prefix was resident in the "
                    "page-level prefix cache (>= 1 full page shared)",
                ),
                "prefix_misses": Counter(
                    "serve_prefix_cache_misses_total",
                    "Admissions that found no resident prompt prefix "
                    "(every prefill chunk recomputed)",
                ),
                "prefill_skipped": Counter(
                    "serve_prefill_tokens_skipped_total",
                    "Prompt tokens NOT re-prefilled because their pages "
                    "were shared from the prefix cache",
                ),
            }
        return _metrics


def init_slotted_cache(cfg: TransformerConfig, slots: int, max_len: int) -> Dict:
    """[layers, slots, max_len, kv_heads, head_dim] cache with PER-SLOT
    lengths — the structural difference from generate.init_kv_cache's
    single shared scalar, and what lets sequences of different ages
    share one decode batch."""
    shape = (cfg.n_layers, slots, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype=cfg.dtype),
        "v": jnp.zeros(shape, dtype=cfg.dtype),
        "lengths": jnp.zeros((slots,), dtype=jnp.int32),
    }


def _grouped_attention(q, kf, vf, valid):
    """q [S, Lq, H, D] vs caches [S, Lk, KVH, D]; valid [S, Lq, Lk]."""
    s_, lq, h, d = q.shape
    kvh = kf.shape[2]
    group = h // kvh
    scale = d ** -0.5
    qg = q.reshape(s_, lq, kvh, group, d).astype(jnp.float32)
    scores = jnp.einsum("sqhgd,skhd->shgqk", qg, kf) * scale
    scores = jnp.where(valid[:, None, None], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("shgqk,skhd->sqhgd", p, vf).reshape(s_, lq, h, d)
    return out.astype(q.dtype)


def _layer_body(x, lp, k_cache_l, v_cache_l, cfg, cos, sin, positions,
                write_kv, valid):
    """One transformer layer shared by slotted decode and prefill.

    The two callers differ only in how K/V land in the cache and what
    the attention source/mask is: `write_kv(kc, vc, k, v) -> (kc, vc,
    k_att, v_att)` encapsulates that, `valid` is the caller's mask over
    (B, Lq, Lk_att)."""
    b, l = x.shape[:2]
    h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ lp["wq"]).reshape(b, l, cfg.n_heads, cfg.head_dim)
    k = (h @ lp["wk"]).reshape(b, l, cfg.n_kv_heads, cfg.head_dim)
    v = (h @ lp["wv"]).reshape(b, l, cfg.n_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        q = rmsnorm(q, lp["q_norm"], cfg.norm_eps, use_pallas=False)
        k = rmsnorm(k, lp["k_norm"], cfg.norm_eps, use_pallas=False)
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)
    k_cache_l, v_cache_l, k_att, v_att = write_kv(k_cache_l, v_cache_l, k, v)
    attn = _grouped_attention(
        q, k_att.astype(jnp.float32), v_att.astype(jnp.float32), valid
    )
    x = x + (attn.reshape(b, l, -1) @ lp["wo"]).astype(x.dtype)
    h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    gate = _act(cfg)((h @ lp["w_gate"]).astype(jnp.float32))
    up = (h @ lp["w_up"]).astype(jnp.float32)
    x = x + (((gate * up).astype(x.dtype)) @ lp["w_down"])
    return x, k_cache_l, v_cache_l


MAX_TOP_K = 64  # per-slot top-k cap (static shape for lax.top_k)


def _pick_tokens(logits, temps, top_ks, top_ps, key):
    """Per-slot next-token selection on device: greedy where temp == 0,
    else temperature-scaled sampling with optional per-slot top-k
    (0 = off, capped at MAX_TOP_K) and top-p (1.0 = off) filtering —
    generate.py's sampling semantics, vectorized over slots so mixed
    greedy/sampled requests share one decode batch."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    # top-k: threshold each row at its k-th largest value. The static k
    # clamps to the vocab so models with vocab_size < MAX_TOP_K don't
    # crash the jitted step (lax.top_k requires k <= last dim).
    k = min(MAX_TOP_K, logits.shape[-1])
    topv = jax.lax.top_k(scaled, k)[0]  # [S, K] sorted desc
    idx = jnp.clip(top_ks - 1, 0, k - 1)
    kth = jnp.take_along_axis(topv, idx[:, None], axis=-1)
    scaled = jnp.where((top_ks > 0)[:, None] & (scaled < kth),
                       -jnp.inf, scaled)
    # top-p: smallest prefix of the sorted distribution reaching p.
    sorted_l = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_l, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_ps[:, None]
    thr = jnp.min(jnp.where(keep, sorted_l, jnp.inf), axis=-1,
                  keepdims=True)
    scaled = jnp.where(scaled < thr, -jnp.inf, scaled)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temps > 0, sampled, greedy)


def _decode_slots(params, tokens, k_cache, v_cache, lengths, active,
                  temps, top_ks, top_ps, key,
                  cfg: TransformerConfig):
    """One decode step for every slot at once.

    tokens [S] int32 (last emitted per slot; 0 for inactive), lengths
    [S] (current valid cache rows per slot), active [S] bool. Returns
    (next_tokens [S], k_cache, v_cache, new_lengths): caches updated
    in place at each ACTIVE slot's own position; inactive slots write
    into their top spare row (masked out forever) and keep their length.
    """
    s_ = tokens.shape[0]
    lmax = k_cache.shape[2]
    x = _embed_tokens(params, tokens[:, None], cfg)  # [S, 1, d]
    cos, sin = rope_frequencies(cfg.head_dim, lmax, cfg.rope_theta)
    positions = lengths[:, None]
    # Inactive slots park their write in the slot's own last row; it is
    # never unmasked (their length does not advance).
    write_at = jnp.where(active, jnp.minimum(lengths, lmax - 1), lmax - 1)
    slot_idx = jnp.arange(s_)
    # Keys valid up to and including the token just written.
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (s_, 1, lmax), 2)
    valid = k_pos <= positions[:, :, None]

    def write_kv(kc, vc, k, v):
        kc = kc.at[slot_idx, write_at].set(k[:, 0].astype(kc.dtype))
        vc = vc.at[slot_idx, write_at].set(v[:, 0].astype(vc.dtype))
        return kc, vc, kc, vc  # attend against the full cache

    def layer(carry, inputs):
        x = carry
        lp, k_cache_l, v_cache_l = inputs
        x, k_cache_l, v_cache_l = _layer_body(
            x, lp, k_cache_l, v_cache_l, cfg, cos, sin, positions,
            write_kv, valid,
        )
        return x, (k_cache_l, v_cache_l)

    x, (k_new, v_new) = jax.lax.scan(
        layer, x, (params["layers"], k_cache, v_cache)
    )
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = project_logits(x[:, -1], params, cfg)
    new_lengths = jnp.where(active, lengths + 1, lengths)
    # Next token computed ON DEVICE so the engine can feed it straight
    # into the next dispatched step without a host round trip (the
    # pipelining that hides host/RTT latency behind decode). temps=None
    # compiles the greedy-only program: no top-k/sort/softmax work on
    # the latency-critical all-greedy path.
    if temps is None:
        next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        next_tokens = _pick_tokens(logits, temps, top_ks, top_ps, key)
    return next_tokens, k_new, v_new, new_lengths


def _prefill_chunk(params, tokens, n_valid, slot, offset, k_cache, v_cache,
                   lengths, cfg: TransformerConfig):
    """CHUNKED prefill: process one fixed-size chunk of a prompt into
    slot `slot` at row `offset` — the scheme that lets a long prompt's
    prefill interleave with other slots' decode steps instead of
    stalling them for the whole prompt.

    tokens [1, C] int32 (first n_valid real), writes K/V rows
    [slot, offset:offset+C]; queries attend causally to the slot's
    whole cache prefix (earlier chunks included). Sets lengths[slot] =
    offset + n_valid and returns the logits of the chunk's last REAL
    position [1, vocab] (meaningful on the final chunk).
    """
    _, c = tokens.shape
    lmax = k_cache.shape[2]
    x = _embed_tokens(params, tokens, cfg)
    cos, sin = rope_frequencies(cfg.head_dim, lmax, cfg.rope_theta)
    positions = offset + jnp.arange(c, dtype=jnp.int32)[None, :]
    # Causal against the slot's full cache: key row j is visible to
    # chunk query i when j <= offset + i and j is a real row.
    q_pos = positions[:, :, None]                              # [1, C, 1]
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (1, c, lmax), 2)
    valid = (k_pos <= q_pos) & (k_pos < offset + n_valid)
    # Row-indexed scatter with mode="drop": a final chunk whose PADDING
    # would run past the cache end simply drops those rows.
    # (dynamic_update_slice would CLAMP the start instead, silently
    # overwriting earlier chunks' rows.) Real rows always fit: prompts
    # are bounded by max_len - 2 at submit.
    rows = offset + jnp.arange(c, dtype=jnp.int32)

    def write_kv(kc, vc, k, v):
        kc = kc.at[slot, rows].set(k[0].astype(kc.dtype), mode="drop")
        vc = vc.at[slot, rows].set(v[0].astype(vc.dtype), mode="drop")
        # Attend against the slot's whole cache row range (masked).
        k_att = jax.lax.dynamic_slice_in_dim(kc, slot, 1, axis=0)
        v_att = jax.lax.dynamic_slice_in_dim(vc, slot, 1, axis=0)
        return kc, vc, k_att, v_att

    def layer(carry, inputs):
        x = carry
        lp, k_cache_l, v_cache_l = inputs
        x, k_cache_l, v_cache_l = _layer_body(
            x, lp, k_cache_l, v_cache_l, cfg, cos, sin, positions,
            write_kv, valid,
        )
        return x, (k_cache_l, v_cache_l)

    x, (k_new, v_new) = jax.lax.scan(
        layer, x, (params["layers"], k_cache, v_cache)
    )
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    last = jax.lax.dynamic_slice(x, (0, n_valid - 1, 0), (1, 1, x.shape[-1]))
    logits = project_logits(last[:, 0], params, cfg)
    new_lengths = lengths.at[slot].set(offset + n_valid)
    return logits, k_new, v_new, new_lengths


class GenerationHandle:
    """Per-request stream: tokens arrive as the engine produces them."""

    def __init__(self, request_id: int):
        self.request_id = request_id
        self._tokens: deque = deque()
        self._done = False
        self._error: Optional[BaseException] = None
        self._cond = threading.Condition()
        # Engine bookkeeping (set at admission).
        self.prompt: Optional[np.ndarray] = None
        self.max_new_tokens = 0
        self.produced = 0
        self.admitted_at_step = -1
        # Sampling params (0 temperature = greedy).
        self.temperature = 0.0
        self.top_k = 0
        self.top_p = 1.0
        # Latency bookkeeping (engine thread only): submit stamps
        # submitted_at; the first/terminal pushes yield TTFT/TPOT.
        self.submitted_at: Optional[float] = None
        self._first_token_t: Optional[float] = None
        # Observatory stamp card (set by submit() from the request
        # thread's context; engine thread writes marks into it).
        self.obs = None
        # Survival plane (set by submit() from the request-scoped
        # serving context): absolute deadline (0 = none), tenant label
        # for the WFQ admission queue, and the caller-side cancel flag
        # the engine loop polls at step boundaries.
        self.deadline_ts = 0.0
        self.tenant = "default"
        self.cancelled = False

    def cancel(self, reason: str = "client"):
        """Caller-side cancellation: the consumer stops waiting NOW
        (``_fail`` wakes it with RequestCancelledError) and the engine
        loop evicts the slot at the next step boundary — the slot is
        reclaimed without waiting for the sequence to finish."""
        self.cancelled = True
        self._fail(RequestCancelledError(
            f"request {self.request_id} cancelled ({reason})",
            reason=reason, rid=str(self.request_id),
        ))

    # -- engine side --
    def _push(self, token: int, done: bool):
        now = time.perf_counter()
        first = self._first_token_t is None
        if first:
            self._first_token_t = now
        with self._cond:
            self._tokens.append(int(token))
            self._done = self._done or done
            self._cond.notify_all()
        # Observe outside the condition: a blocked consumer wakes without
        # waiting on the metrics registry lock.
        m = _engine_metrics()
        if first and self.submitted_at is not None:
            m["ttft_s"].observe(now - self.submitted_at)
        if done and self.produced > 1 and not first:
            m["tpot_s"].observe(
                (now - self._first_token_t) / (self.produced - 1)
            )
        obs = self.obs
        if obs is not None:
            if first:
                obs.marks["first_token"] = now
            if done:
                obs.marks["engine_done"] = now
                obs.tokens_out = self.produced

    def _fail(self, err: BaseException):
        with self._cond:
            if self._done and self._error is None:
                return  # finished cleanly first; late cancel/fail is moot
            self._error = err
            self._done = True
            self._cond.notify_all()

    # -- caller side --
    def __iter__(self):
        while True:
            with self._cond:
                while not self._tokens and not self._done:
                    self._cond.wait(timeout=60.0)
                if self._error is not None:
                    raise self._error
                if self._tokens:
                    yield self._tokens.popleft()
                    continue
                if self._done:
                    return

    def result(self, timeout: float = 120.0) -> list:
        deadline = time.monotonic() + timeout
        out = []
        with self._cond:
            while not self._done:
                rest = deadline - time.monotonic()
                if rest <= 0:
                    raise TimeoutError("generation timed out")
                self._cond.wait(timeout=rest)
            if self._error is not None:
                raise self._error
            out.extend(self._tokens)
            self._tokens.clear()
        return out


class ContinuousBatchingEngine:
    """Iteration-level scheduler over the slotted cache.

    One background thread runs the decode loop; submit() enqueues a
    request which joins at the next step boundary when a slot frees.
    """

    def __init__(self, params, cfg: TransformerConfig, num_slots: int = 4,
                 max_len: int = 256, eos_id: Optional[int] = None,
                 default_max_new_tokens: int = 32,
                 prefill_buckets=None, seed: int = 0,
                 mesh=None, prefill_chunk: int = 64,
                 kv_mode: Optional[str] = None,
                 page_size: Optional[int] = None,
                 kv_pages: Optional[int] = None):
        """mesh: a jax.sharding.Mesh with a "tp" axis for tensor-
        parallel serving (the pods layout): pass params already sharded
        via parallel.shard_params and the engine lays the KV cache out
        with KV heads split over tp — decode collectives then ride ICI
        inside the compiled step (GSPMD inserts them).

        prefill_chunk: prompts prefill in fixed chunks of this many
        tokens, ONE chunk between decode steps — a long prompt never
        stalls other slots' decoding for more than a chunk (chunked
        prefill), and prefill compiles exactly once. prefill_buckets is
        a deprecated no-op (chunking bounds compilation by itself).

        kv_mode / page_size / kv_pages: the KV memory plane. "paged"
        (default; ray_tpu/serve/paged_kv) backs slots with a shared
        page pool + block tables and a prefix cache; "slotted" is the
        original one-[max_len]-row-per-slot cache kept for bit-exact
        baselines. None defers to config (RT_SERVE_KV,
        RT_SERVE_KV_PAGE_SIZE, RT_SERVE_KV_PAGES; kv_pages 0/None =
        slotted-HBM parity)."""
        self.params = params
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_len = max_len
        self.eos_id = eos_id
        self.default_max_new_tokens = default_max_new_tokens
        self.mesh = mesh
        self.prefill_chunk = max(1, min(int(prefill_chunk), max_len))
        if mesh is not None:
            if "tp" not in mesh.shape:
                raise ValueError(
                    "the engine's mesh needs a \"tp\" axis (KV heads "
                    f"shard over it); got axes {tuple(mesh.shape)}"
                )
            if cfg.n_kv_heads % mesh.shape["tp"]:
                raise ValueError(
                    f"the mesh's tp={mesh.shape['tp']} must divide "
                    f"n_kv_heads={cfg.n_kv_heads}"
                )
        rcfg = get_config()
        mode = (kv_mode or rcfg.serve_kv or "paged").lower()
        if mode not in ("paged", "slotted"):
            raise ValueError(
                f"kv_mode must be 'paged' or 'slotted', got {mode!r}"
            )
        self.kv_mode = mode
        self._paged = mode == "paged"
        self._cow = None
        if self._paged:
            self.page_size = max(
                1, min(int(page_size or rcfg.serve_kv_page_size), max_len)
            )
            self._pages_per_slot = -(-max_len // self.page_size)
            self.kv_pages = int(kv_pages or rcfg.serve_kv_pages or 0)
            if self.kv_pages <= 0:
                # HBM parity with the slotted cache it replaces (+ the
                # reserved NULL page).
                self.kv_pages = num_slots * self._pages_per_slot + 1
            self._pool = paged_kv.PagePool(self.kv_pages, self.page_size)
            self._prefix_cache = (
                paged_kv.PrefixCache(self._pool)
                if rcfg.serve_prefix_cache else None
            )
            # Host mirror of the device block table; uploaded as ONE
            # array only when admission/eviction changed it (same
            # discipline — and the same test pins — as the sampling
            # params: the steady-state decode step uploads nothing).
            self._bt_host = np.zeros(
                (num_slots, self._pages_per_slot), dtype=np.int32
            )
            self._bt_dirty = False
            self._bt_uploads = 0
            self._slot_pages: Dict[int, list] = {}
            self._prefix_hits = 0
            self._prefix_misses = 0
            self._prefill_tok_skipped = 0
            self._chaos_held: list = []
        cache = self._fresh_cache()
        self._k, self._v = cache["k"], cache["v"]
        self._lengths = cache["lengths"]
        if self._paged:
            self._bt_dev = cache["block_tables"]
            self._decode_sampled = jax.jit(
                lambda p, t, k, v, ln, a, bt, tp, tk, tpp, key:
                paged_kv.decode_paged(
                    p, t, k, v, ln, a, bt, tp, tk, tpp, key, cfg, max_len
                ),
                donate_argnums=(2, 3),
            )
            self._decode_greedy = jax.jit(
                lambda p, t, k, v, ln, a, bt: paged_kv.decode_paged(
                    p, t, k, v, ln, a, bt, None, None, None, None, cfg,
                    max_len
                ),
                donate_argnums=(2, 3),
            )
            self._prefill = jax.jit(
                lambda p, t, n, s, o, k, v, ln, bt:
                paged_kv.prefill_chunk_paged(
                    p, t, n, s, o, k, v, ln, bt, cfg, max_len
                ),
                donate_argnums=(5, 6),
            )
            self._cow = jax.jit(
                paged_kv.cow_copy_page, donate_argnums=(0, 1)
            )
        else:
            self._decode_sampled = jax.jit(
                lambda p, t, k, v, ln, a, tp, tk, tpp, key: _decode_slots(
                    p, t, k, v, ln, a, tp, tk, tpp, key, cfg
                ),
                donate_argnums=(2, 3),
            )
            self._decode_greedy = jax.jit(
                lambda p, t, k, v, ln, a: _decode_slots(
                    p, t, k, v, ln, a, None, None, None, None, cfg
                ),
                donate_argnums=(2, 3),
            )
            self._prefill = jax.jit(
                lambda p, t, n, s, o, k, v, ln: _prefill_chunk(
                    p, t, n, s, o, k, v, ln, cfg
                ),
                donate_argnums=(5, 6),
            )
        self._pick = jax.jit(_pick_tokens)
        self._lock = threading.Lock()
        self._work = threading.Event()
        # BOUNDED admission queue with per-tenant weighted-fair service:
        # one deque per tenant, served deficit-round-robin (weight w
        # accrues w credits per rotation; one credit admits one request,
        # so with equal weights this is plain round-robin and a chatty
        # tenant can no longer starve the others). The global bound
        # (serve_max_queued_per_engine) converts queue collapse into a
        # fast typed ServeOverloadedError shed at submit().
        self._waiting: Dict[str, deque] = {}
        self._waiting_n = 0
        self._wfq_rr: deque = deque()          # tenant rotation order
        self._wfq_credit: Dict[str, float] = {}
        self._tenant_weights: Dict[str, float] = {}
        self._shed_total = 0
        self._deadline_expired = 0
        self._slots: Dict[int, GenerationHandle] = {}
        # Mid-prefill requests: slot -> {"h": handle, "offset": rows
        # already prefilled}. One chunk advances per loop iteration.
        self._prefilling: Dict[int, Dict] = {}
        self._free = deque(range(num_slots))
        # Next input token per slot, ON DEVICE: the decode loop feeds
        # each step's argmax straight into the next dispatch and fetches
        # results one step behind (host/RTT latency hides under decode).
        self._tokens_dev = jnp.zeros(num_slots, dtype=jnp.int32)
        # Per-slot admission generation: suppresses the one in-flight
        # token a just-evicted slot still produces under the lag.
        self._gen = np.zeros(num_slots, dtype=np.int64)
        # Per-slot sampling params + active mask: HOST mirrors (written
        # at admission/eviction) with DEVICE-RESIDENT copies the decode
        # step reads. The steady-state step touches only the device
        # copies; _params_dirty triggers ONE host->device refresh when
        # slot membership changes — never four jnp.asarray uploads per
        # step, which over a TPU tunnel costs an RTT each.
        self._temps = np.zeros(num_slots, dtype=np.float32)
        self._top_ks = np.zeros(num_slots, dtype=np.int32)
        self._top_ps = np.ones(num_slots, dtype=np.float32)
        self._active = np.zeros(num_slots, dtype=bool)
        self._temps_dev = jnp.asarray(self._temps)
        self._top_ks_dev = jnp.asarray(self._top_ks)
        self._top_ps_dev = jnp.asarray(self._top_ps)
        self._active_dev = jnp.asarray(self._active)
        self._params_dirty = False
        self._sampled_active = False
        self._param_uploads = 0  # refresh events (tests pin steady state)
        # Per-step timing breakdown (loop thread writes, stats() reads).
        self._t_dispatch = 0.0
        self._t_fetch = 0.0
        self._t_host = 0.0
        self._timed_steps = 0
        self._rng = jax.random.PRNGKey(seed)
        self._next_id = 0
        self._steps = 0  # decode-step counter (observability + tests)
        self._recompiles = 0  # compilations observed after warmup
        # Head-of-line ledger (engine thread writes, stats() reads under
        # the lock): recent prefill passes that stalled active decode
        # slots past serve_hol_threshold_s, blamed on the prefilling
        # request(s) that ran in the pass.
        self._hol_events: deque = deque(maxlen=64)
        self._hol_blocked_s = 0.0
        self._last_prefill_work: list = []
        self._warmup()
        self._warm_compiles = self._compile_count()
        self._last_compiles = self._warm_compiles
        # Event, not a bare bool: set by shutdown() on the caller thread,
        # polled by the engine thread (RT006).
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="llm-engine", daemon=True
        )
        self._thread.start()

    def _warmup(self):  # rtlint: disable=RT010 — runs before the loop thread starts; Thread.start() is the happens-before
        """Compile every steady-state program up front — BOTH decode
        variants (greedy and sampled), the prefill chunk, and the
        prefill-token picker — so traffic flipping between greedy and
        sampled never compiles mid-serving. All warmup calls run with
        `active` all-False: decode writes land in each slot's parking
        row (lmax - 1, never unmasked) and the prefill rows it touches
        are re-written by any real occupant before its length exposes
        them, so cache contents stay semantically untouched."""
        self._rng, k1, k2 = jax.random.split(self._rng, 3)
        pad = jnp.zeros((1, self.prefill_chunk), dtype=jnp.int32)
        if self._paged:
            (_, self._k, self._v, self._lengths) = self._decode_greedy(
                self.params, self._tokens_dev, self._k, self._v,
                self._lengths, self._active_dev, self._bt_dev,
            )
            (_, self._k, self._v, self._lengths) = self._decode_sampled(
                self.params, self._tokens_dev, self._k, self._v,
                self._lengths, self._active_dev, self._bt_dev,
                self._temps_dev, self._top_ks_dev, self._top_ps_dev, k1,
            )
            logits, self._k, self._v, self._lengths = self._prefill(
                self.params, pad, jnp.int32(1), jnp.int32(0), jnp.int32(0),
                self._k, self._v, self._lengths, self._bt_dev,
            )
            # Warm the copy-on-write page fork too (NULL page onto
            # itself: contents never observable).
            self._k, self._v = self._cow(
                self._k, self._v, jnp.int32(0), jnp.int32(0)
            )
        else:
            (_, self._k, self._v, self._lengths) = self._decode_greedy(
                self.params, self._tokens_dev, self._k, self._v,
                self._lengths, self._active_dev,
            )
            (_, self._k, self._v, self._lengths) = self._decode_sampled(
                self.params, self._tokens_dev, self._k, self._v,
                self._lengths, self._active_dev, self._temps_dev,
                self._top_ks_dev, self._top_ps_dev, k1,
            )
            logits, self._k, self._v, self._lengths = self._prefill(
                self.params, pad, jnp.int32(1), jnp.int32(0), jnp.int32(0),
                self._k, self._v, self._lengths,
            )
        self._pick(
            logits, jnp.full(1, 0.5, jnp.float32),
            jnp.full(1, 1, jnp.int32), jnp.full(1, 1.0, jnp.float32), k2,
        )
        # Undo the warmup prefill's lengths[0] = 1 (device-side, keeps
        # the mesh sharding of the lengths array).
        self._lengths = self._lengths * 0
        jax.block_until_ready(self._lengths)

    def _compile_count(self) -> int:
        """Total compiled-program count across the engine's jitted
        callables (the wrapper-counter the recompile guard pins: jit
        cache growth == a recompilation happened)."""
        n = 0
        fns = [self._decode_greedy, self._decode_sampled,
               self._prefill, self._pick]
        if self._cow is not None:
            fns.append(self._cow)
        for f in fns:
            try:
                n += f._cache_size()
            except (AttributeError, TypeError):
                # Introspection-only: a jax version without _cache_size
                # just disables the recompile guard's counter.
                pass
        return n

    # Single-writer: every *_dev array is owned by the engine thread
    # (this runs on it); submit() only flips _params_dirty under
    # self._lock.
    def _upload_sampling_state(self):  # rtlint: disable=RT006,RT010 — loop-thread-only; the lock is for submit()-side visibility
        """ONE host->device refresh of sampling params + active mask.
        Called only when slot membership changed (admission/eviction) —
        the steady-state decode step reads the device-resident copies
        and does zero uploads."""
        self._temps_dev = jnp.asarray(self._temps)
        self._top_ks_dev = jnp.asarray(self._top_ks)
        self._top_ps_dev = jnp.asarray(self._top_ps)
        self._active_dev = jnp.asarray(self._active)
        self._sampled_active = bool((self._temps[self._active] > 0).any())
        self._params_dirty = False
        self._param_uploads += 1
        _engine_metrics()["param_uploads"].inc(1)

    # Single-writer: _bt_dev is engine-thread-owned device state.
    def _upload_block_table(self):  # rtlint: disable=RT006,RT010 — loop-thread-only; the lock is for submit()-side visibility
        """ONE host->device refresh of the block table. Admission-
        reserved paging means the table only changes when slot
        membership does — never per decode step (the paged analog of
        _upload_sampling_state, with its own counter so tests can pin
        the steady state)."""
        bt = jnp.asarray(self._bt_host)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            bt = jax.device_put(bt, NamedSharding(self.mesh, P()))
        self._bt_dev = bt
        self._bt_dirty = False
        self._bt_uploads += 1

    # Single-writer: pool/cache are engine-thread-owned host state.
    def _apply_kv_chaos(self):  # rtlint: disable=RT006
        """Consume pending paged-KV chaos injections (RT_CHAOS=1 only):
        a one-shot prefix-cache flush, and a persistent pool-pressure
        target — the engine holds `frac` of the usable pages hostage,
        adjusting toward the target as pages free up, until the frac is
        set back to 0."""
        if self._prefix_cache is not None and chaos.take_flush_prefix_cache():
            with self._lock:
                self._prefix_cache.flush()
        frac = chaos.kv_exhaust_frac()
        if frac is None and not self._chaos_held:
            return
        target = int(round((frac or 0.0) * self._pool.usable))
        with self._lock:
            if len(self._chaos_held) > target:
                give_back = self._chaos_held[target:]
                del self._chaos_held[target:]
                self._pool.release(give_back)
            elif len(self._chaos_held) < target:
                grab = min(target - len(self._chaos_held),
                           self._pool.free_pages)
                if grab > 0:
                    self._chaos_held.extend(self._pool.alloc(grab))

    def _fresh_cache(self) -> Dict:
        if self._paged:
            return paged_kv.init_paged_cache(
                self.cfg, self.num_slots, self.kv_pages, self.page_size,
                self._pages_per_slot, mesh=self.mesh,
            )
        cache = init_slotted_cache(self.cfg, self.num_slots, self.max_len)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            kv_sharding = NamedSharding(
                self.mesh, P(None, None, None, "tp", None)
            )
            cache = {
                "k": jax.device_put(cache["k"], kv_sharding),
                "v": jax.device_put(cache["v"], kv_sharding),
                "lengths": jax.device_put(
                    cache["lengths"], NamedSharding(self.mesh, P())
                ),
            }
        return cache

    # -- public API ------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               temperature: float = 0.0, top_k: Optional[int] = None,
               top_p: Optional[float] = None) -> GenerationHandle:
        """temperature=0 decodes greedily (the default); >0 samples,
        optionally filtered by per-request top_k (<= MAX_TOP_K) and
        top_p — mixed greedy/sampled requests share one decode batch."""
        if top_k is not None and not 0 < top_k <= MAX_TOP_K:
            raise ValueError(f"top_k must be in (0, {MAX_TOP_K}]")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        limit = self.max_len - 2
        detail = f"max_len - 2 = {self.max_len - 2} positions"
        if self._paged:
            # The pool must hold the whole prompt plus one generated
            # token (+1 margin row for the pipelined in-flight step).
            pool_limit = self._pool.usable * self.page_size - 2
            if pool_limit < limit:
                limit = pool_limit
                detail = (
                    f"page pool = {self._pool.usable} pages x "
                    f"{self.page_size} tokens - 2 = {pool_limit}"
                )
        if len(prompt) > limit:
            raise PromptTooLongError(
                f"prompt length {len(prompt)} exceeds this engine's "
                f"limit of {limit} tokens ({detail})",
                prompt_len=len(prompt), max_prompt_len=limit,
            )
        if max_new_tokens is None:
            max_new_tokens = self.default_max_new_tokens
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        obs = observatory.current()
        meta = request_context.current()
        tenant = (meta.tenant if meta is not None else "") or "default"
        deadline_ts = meta.deadline_ts if meta is not None else 0.0
        cfg = get_config()
        if deadline_ts and time.time() > deadline_ts:
            # Budget already burned upstream (slow dispatch/wire): never
            # enqueue work that cannot make its deadline.
            with self._lock:
                self._deadline_expired += 1
            observatory.record_deadline_expired("", "engine_admission")
            raise RequestCancelledError(
                "deadline expired before engine admission",
                reason="deadline", rid=meta.rid if meta else "",
            )
        with self._lock:
            if self._waiting_n >= cfg.serve_max_queued_per_engine:
                # Fast shed: reject BEFORE allocating anything. The
                # retry hint is a coarse backlog-drain estimate (queue
                # depth over slot count, capped) — good enough to spread
                # retries, not a latency promise.
                self._shed_total += 1
                retry = min(5.0, max(
                    0.1, 0.05 * self._waiting_n / max(1, self.num_slots)
                ))
                observatory.record_shed("", tenant, "queue_full")
                raise ServeOverloadedError(
                    f"engine admission queue full "
                    f"({self._waiting_n} waiting >= "
                    f"{cfg.serve_max_queued_per_engine})",
                    tenant=tenant, reason="queue_full", retry_after_s=retry,
                )
            h = GenerationHandle(self._next_id)
            self._next_id += 1
            h.submitted_at = time.perf_counter()
            h.prompt = prompt
            h.max_new_tokens = int(max_new_tokens)
            h.temperature = float(temperature)
            h.top_k = int(top_k or 0)
            h.top_p = float(1.0 if top_p is None else top_p)
            h.tenant = tenant
            h.deadline_ts = deadline_ts
            # Adopt the request thread's stamp card: engine admission
            # wait is measured from THIS enqueue, not from slot grant.
            h.obs = obs
            if obs is not None:
                obs.marks["engine_enqueue"] = h.submitted_at
                obs.tokens_in = len(prompt)
            q = self._waiting.get(tenant)
            if q is None:
                q = self._waiting[tenant] = deque()
                self._wfq_rr.append(tenant)
            q.append(h)
            self._waiting_n += 1
            _engine_metrics()["waiting"].set(float(self._waiting_n))
        self._work.set()
        return h

    def set_tenant_weight(self, tenant: str, weight: float) -> None:
        """Give a tenant a WFQ share (> 1 admits proportionally more per
        rotation, < 1 less; default 1.0 — equal shares)."""
        if weight <= 0:
            raise ValueError("tenant weight must be > 0")
        with self._lock:
            self._tenant_weights[tenant or "default"] = float(weight)

    def _kv_stats_locked(self) -> Dict:
        """The KV memory plane's health (stats()["kv"]): pool occupancy,
        prefix-cache effectiveness, and — for affinity routing — the
        cache's advertised root keys."""
        if not self._paged:
            return {"mode": "slotted", "page_size": 0}
        lookups = self._prefix_hits + self._prefix_misses
        cache_pages = (self._prefix_cache.pages_held
                       if self._prefix_cache is not None else 0)
        return {
            "mode": "paged",
            "page_size": self.page_size,
            "pages_total": self._pool.usable,
            "pages_in_use": self._pool.in_use,
            "pages_free": self._pool.free_pages,
            "util": self._pool.in_use / max(1, self._pool.usable),
            "prefix_cache_pages": cache_pages,
            "prefix_hits": self._prefix_hits,
            "prefix_misses": self._prefix_misses,
            "prefix_hit_rate": (self._prefix_hits / lookups
                                if lookups else None),
            "prefill_tokens_skipped": self._prefill_tok_skipped,
            "bt_uploads": self._bt_uploads,
            "chaos_held_pages": len(self._chaos_held),
            "roots": (self._prefix_cache.roots()
                      if self._prefix_cache is not None else []),
        }

    def stats(self) -> Dict:
        with self._lock:
            ts = max(self._timed_steps, 1)
            return {
                "kv": self._kv_stats_locked(),
                "steps": self._steps,
                "active": len(self._slots),
                "waiting": self._waiting_n,
                "waiting_tenants": {
                    t: len(q) for t, q in self._waiting.items() if q
                },
                "shed_total": self._shed_total,
                "deadline_expired": self._deadline_expired,
                "prefilling": len(self._prefilling),
                "free_slots": len(self._free),
                # Hot-loop hygiene (tests pin these in steady state).
                "compiles": self._compile_count(),
                "warm_compiles": self._warm_compiles,
                "recompiles_post_warm": self._recompiles,
                "param_uploads": self._param_uploads,
                # Per-step wall-time decomposition: where an engine step
                # goes beyond the raw decode step (EQuARX discipline —
                # you cannot shrink a step you cannot decompose).
                # _total fields are cumulative: probes delta two stats()
                # snapshots for a clean steady-state window (the avgs
                # include admission/prefill-heavy iterations).
                "timing": {
                    "steps_timed": self._timed_steps,
                    "dispatch_ms_avg": self._t_dispatch / ts * 1e3,
                    "fetch_ms_avg": self._t_fetch / ts * 1e3,
                    "host_ms_avg": self._t_host / ts * 1e3,
                    "dispatch_ms_total": self._t_dispatch * 1e3,
                    "fetch_ms_total": self._t_fetch * 1e3,
                    "host_ms_total": self._t_host * 1e3,
                },
                # Request-level latency (flight recorder): process-wide
                # lifetime summaries of the TTFT/TPOT histograms, plus
                # the instantaneous batch occupancy.
                "latency": {
                    "ttft": _engine_metrics()["ttft_s"].summary(),
                    "tpot": _engine_metrics()["tpot_s"].summary(),
                    "occupancy": len(self._slots) / self.num_slots,
                },
                # Head-of-line ledger: decode stalls attributed to the
                # long prefill that caused them (observatory + rt serve).
                "hol": {
                    "blocked_slot_seconds": self._hol_blocked_s,
                    "events": list(self._hol_events),
                },
            }

    def shutdown(self):
        self._stop_evt.set()
        self._work.set()
        self._thread.join(timeout=10)
        # Outstanding handles must resolve: a streaming consumer blocked
        # in __iter__ would otherwise wait forever.
        err = RuntimeError("engine shut down")
        with self._lock:
            pending = (list(self._slots.values())
                       + self._drain_waiting_locked()
                       + [e["h"] for e in self._prefilling.values()])
            for h in pending:
                h._fail(err)
            self._slots.clear()
            self._prefilling.clear()

    # -- engine loop -----------------------------------------------------
    def _drain_waiting_locked(self) -> list:
        """Flatten and empty every tenant queue (shutdown/failure)."""
        out: list = []
        for q in self._waiting.values():
            out.extend(q)
        self._waiting.clear()
        self._wfq_rr.clear()
        self._wfq_credit.clear()
        self._waiting_n = 0
        return out

    def _pop_waiting_locked(self) -> Optional[GenerationHandle]:
        """Next request under deficit-round-robin over tenant queues.

        Each rotation a tenant earns its weight in credits; one credit
        admits one request. Tenants whose queue empties leave the
        rotation (and forfeit leftover credit — standard DRR, so idle
        tenants cannot bank a burst). Terminates: credits grow every
        full rotation while any queue is non-empty."""
        while self._wfq_rr:
            t = self._wfq_rr.popleft()
            q = self._waiting.get(t)
            if not q:
                self._waiting.pop(t, None)
                self._wfq_credit.pop(t, None)
                continue
            credit = (self._wfq_credit.get(t, 0.0)
                      + self._tenant_weights.get(t, 1.0))
            h = None
            if credit >= 1.0:
                h = q.popleft()
                self._waiting_n -= 1
                credit -= 1.0
            self._wfq_credit[t] = credit
            self._wfq_rr.append(t)
            if h is not None:
                return h
        return None

    def _admit_locked(self):
        """Assign free slots to waiting requests; their prompts then
        prefill ONE chunk per loop iteration (_advance_prefills), so a
        long prompt never stalls other slots' decode for more than a
        chunk. Requests whose deadline expired while queued (or that the
        caller cancelled) are dropped here instead of burning a slot."""
        admitted = bool(self._waiting_n and self._free)
        now = time.time()
        while self._free and self._waiting_n:
            h = self._pop_waiting_locked()
            if h is None:
                break
            if h.cancelled:
                continue  # cancel() already failed the handle
            if h.deadline_ts and now > h.deadline_ts:
                self._deadline_expired += 1
                h._fail(RequestCancelledError(
                    f"deadline expired in admission queue "
                    f"(request {h.request_id})",
                    reason="deadline", rid=str(h.request_id),
                ))
                observatory.record_deadline_expired("", "engine_admission")
                continue
            # Deliverable budget: the loop cuts a sequence at lengths >=
            # max_len - 2 (one in-flight pipelined step keeps a margin
            # row), so a prompt of P rows can emit max_len - 1 - P
            # tokens; submit() guarantees that is >= 1. Clamp to what
            # will actually be delivered.
            h.max_new_tokens = min(
                h.max_new_tokens, self.max_len - 1 - len(h.prompt)
            )
            res = None
            if self._paged:
                # Reserve EVERY page the request can ever touch now:
                # decode then never allocates, so the block table (like
                # the sampling params) uploads only on slot membership
                # changes and pool exhaustion can never strand a
                # mid-decode sequence.
                res = self._reserve_paged_locked(h)
                if res is None:
                    # Pool pressure: back to the FRONT of its tenant
                    # queue; retried as decoding slots release pages.
                    q = self._waiting.get(h.tenant)
                    if q is None:
                        q = self._waiting[h.tenant] = deque()
                        self._wfq_rr.append(h.tenant)
                    q.appendleft(h)
                    self._waiting_n += 1
                    break
            grant_t = time.perf_counter()
            if h.submitted_at is not None:
                _engine_metrics()["admission_wait_s"].observe(
                    grant_t - h.submitted_at
                )
            if h.obs is not None:
                h.obs.marks["slot_grant"] = grant_t
            slot = self._free.popleft()
            entry = {"h": h, "offset": 0}
            if self._paged:
                entry["offset"] = res["skip"]
                entry["pages"] = res["pages"]
                entry["hashes"] = res["hashes"]
                row = self._bt_host[slot]
                row[:] = 0
                row[:len(res["pages"])] = res["pages"]
                self._bt_dirty = True
            self._prefilling[slot] = entry
        if admitted:
            _engine_metrics()["waiting"].set(float(self._waiting_n))

    # Caller holds self._lock (the `_locked` contract); the KV counters
    # it bumps are read back under the same lock in _kv_stats_locked.
    def _reserve_paged_locked(self, h) -> Optional[Dict]:  # rtlint: disable=RT006
        """Pages for one admission: shared prefix pages from the cache
        (refcount bump, prefill skipped below `skip`) plus freshly
        allocated pages covering the rest of the request's maximum
        footprint. None = pool exhausted even after LRU-evicting cache
        entries; the caller requeues."""
        ps = self.page_size
        p_len = len(h.prompt)
        hashes = (paged_kv.page_hashes(h.prompt, ps)
                  if self._prefix_cache is not None else [])
        shared = self._prefix_cache.match(hashes) if hashes else []
        # Footprint: prompt + generated tokens + one margin row for the
        # pipelined in-flight step, capped by addressable positions.
        rows = min(p_len + h.max_new_tokens + 1, self.max_len)
        need = -(-rows // ps) - len(shared)
        try:
            own = self._pool.alloc(need)
        except paged_kv.OutOfPages:
            own = None
            if self._prefix_cache is not None and self._prefix_cache.pages_held:
                self._prefix_cache.evict_pages(
                    need - self._pool.free_pages
                )
                try:
                    own = self._pool.alloc(need)
                except paged_kv.OutOfPages:
                    own = None
        if own is None:
            if shared:
                self._pool.release(shared)
            return None
        pages = shared + own
        # Always recompute at least the final prompt token: its logits
        # seed the first generated token, and a partial tail page is
        # never cached anyway.
        skip = min(len(shared) * ps, p_len - 1)
        m = _engine_metrics()
        if hashes:
            if shared:
                self._prefix_hits += 1
                m["prefix_hits"].inc(1)
            else:
                self._prefix_misses += 1
                m["prefix_misses"].inc(1)
        if skip > 0:
            self._prefill_tok_skipped += skip
            m["prefill_skipped"].inc(skip)
        fw = skip // ps
        if skip and fw < len(shared):
            # Full-prefix hit: the recomputed final token's K/V lands in
            # the LAST shared page — fork it copy-on-write first
            # (refcount > 1 pages are never written).
            try:
                fork = self._pool.alloc(1)[0]
            except paged_kv.OutOfPages:
                self._pool.release(pages)
                return None
            self._k, self._v = self._cow(
                self._k, self._v, jnp.int32(pages[fw]), jnp.int32(fork)
            )
            self._pool.release([pages[fw]])
            pages[fw] = fork
        return {"pages": pages, "hashes": hashes, "skip": skip}

    def _release_slot_pages_locked(self, slot: int):
        """Return a decoding slot's page references to the pool (slot
        eviction; prefix-cache entries keep their own references)."""
        pages = self._slot_pages.pop(slot, None)
        if pages:
            self._pool.release(pages)

    # Single-writer: KV cache, rng, and token buffers are engine-thread-
    # owned device state; no other thread touches them after __init__.
    def _advance_prefills(self):  # rtlint: disable=RT006
        """One prefill chunk for every mid-prefill slot (interleaved
        between decode dispatches). A request whose final chunk lands
        emits its first token and joins the decode set.

        First tokens stay ON DEVICE through admission: each finishing
        slot's pick feeds _tokens_dev device-to-device, and ONE batched
        fetch (async copy started at dispatch, drained once) delivers
        all of this round's first tokens to their handles — not one
        blocking scalar device_get per request."""
        c = self.prefill_chunk
        # Chaos hook: a deterministic stretch stands in for a genuinely
        # huge prompt so HOL-attribution tests don't need one. Inside
        # the timed window on purpose — the watchdog must see it.
        injected = chaos.take_prefill_delay()
        if injected:
            time.sleep(injected)
        if self._paged and self._bt_dirty:
            self._upload_block_table()
        self._last_prefill_work = [
            {
                "request_id": e["h"].request_id,
                "prompt_tokens": int(len(e["h"].prompt)),
                "offset": int(e["offset"]),
            }
            for e in self._prefilling.values()
        ]
        finished = []  # (slot, handle, first-token device array [1])
        now_wall = time.time()
        for slot, entry in list(self._prefilling.items()):
            h, off = entry["h"], entry["offset"]
            if h.cancelled or (h.deadline_ts and now_wall > h.deadline_ts):
                # Abandon the partial prefill: remaining chunks would be
                # work for a request nobody is waiting on.
                if not h.cancelled:
                    h._fail(RequestCancelledError(
                        f"deadline expired mid-prefill "
                        f"(request {h.request_id})",
                        reason="deadline", rid=str(h.request_id),
                    ))
                    observatory.record_deadline_expired("", "engine_decode")
                with self._lock:
                    self._deadline_expired += int(not h.cancelled)
                    del self._prefilling[slot]
                    self._free.append(slot)
                    if self._paged:
                        self._pool.release(entry["pages"])
                continue
            chunk = h.prompt[off:off + c]
            n = len(chunk)
            padded = np.zeros((1, c), dtype=np.int32)
            padded[0, :n] = chunk
            if self._paged:
                logits, self._k, self._v, self._lengths = self._prefill(
                    self.params, jnp.asarray(padded),
                    jnp.int32(n), jnp.int32(slot), jnp.int32(off),
                    self._k, self._v, self._lengths, self._bt_dev,
                )
            else:
                logits, self._k, self._v, self._lengths = self._prefill(
                    self.params, jnp.asarray(padded),
                    jnp.int32(n), jnp.int32(slot), jnp.int32(off),
                    self._k, self._v, self._lengths,
                )
            entry["offset"] = off + n
            if entry["offset"] < len(h.prompt):
                continue
            # Final chunk: first token under the request's sampling.
            if h.temperature > 0:
                self._rng, key = jax.random.split(self._rng)
                tok_dev = self._pick(
                    logits,
                    jnp.full(1, h.temperature, jnp.float32),
                    jnp.full(1, h.top_k, jnp.int32),
                    jnp.full(1, h.top_p, jnp.float32),
                    key,
                )
            else:
                tok_dev = jnp.argmax(logits, -1).astype(jnp.int32)
            # Feed the decode loop device-side (no host round trip) and
            # start the non-blocking copy for the handle push below.
            self._tokens_dev = self._tokens_dev.at[slot].set(tok_dev[0])
            try:
                tok_dev.copy_to_host_async()
            except Exception:  # rtlint: disable=RT007 — optional prefetch; sharded layouts fetch below
                pass
            finished.append((slot, h, tok_dev, entry))
        if not finished:
            return
        toks_np = jax.device_get([t for _, _, t, _ in finished])
        for (slot, h, _, entry), tok_arr in zip(finished, toks_np):
            tok = int(tok_arr[0])
            h.produced = 1
            # admitted_at_step must be visible before the push wakes a
            # consumer (a request finishing on its prefill token would
            # otherwise be observable with the -1 sentinel). _steps is
            # only written by this thread.
            h.admitted_at_step = self._steps  # rtlint: disable=RT010 — _steps is loop-thread-only (see comment)
            done = (tok == self.eos_id if self.eos_id is not None
                    else False) or h.produced >= h.max_new_tokens
            h._push(tok, done)
            with self._lock:
                if self._paged and self._prefix_cache is not None:
                    # Publish the prompt's full pages NOW (not at
                    # request completion): a concurrent same-prefix
                    # request admitted next tick already shares them.
                    hashes = entry.get("hashes") or []
                    if hashes:
                        self._prefix_cache.insert(
                            hashes, entry["pages"][:len(hashes)]
                        )
                del self._prefilling[slot]
                if done:
                    self._free.append(slot)
                    if self._paged:
                        self._pool.release(entry["pages"])
                else:
                    if self._paged:
                        self._slot_pages[slot] = entry["pages"]
                    self._slots[slot] = h
                    self._gen[slot] += 1
                    self._temps[slot] = h.temperature
                    self._top_ks[slot] = h.top_k
                    self._top_ps[slot] = h.top_p
                    self._active[slot] = True
                    self._params_dirty = True

    def _note_hol(self, prefill_s: float, n_active: int):
        """Attribute a slow prefill pass to the decode slots it stalled.

        Chunked prefill bounds the stall at one chunk per pass, but a
        pass can still cross the threshold (huge chunk, slow host, chaos
        injection). Cost: one get_config() + comparison per PREFILL
        pass; the steady-state decode loop never reaches here."""
        if n_active <= 0 or prefill_s < get_config().serve_hol_threshold_s:
            return
        blocked = prefill_s * n_active  # slot-seconds of stalled decode
        culprits = self._last_prefill_work
        with self._lock:
            self._hol_blocked_s += blocked
            self._hol_events.append({
                "ts": time.time(),
                "prefill_s": prefill_s,
                "victims": n_active,
                "blocked_slot_seconds": blocked,
                "culprits": culprits,
            })
        _engine_metrics()["hol_s"].inc(blocked)
        from ray_tpu.util import journal

        journal.emit("serve.hol", prefill_s=round(prefill_s, 4),
                     victims=n_active,
                     blocked_slot_seconds=round(blocked, 4))
        journal.trigger_postmortem(
            "hol_blocking", prefill_s=round(prefill_s, 4),
            victims=n_active)

    def _loop(self):
        """Pipelined decode loop with ASYNC double-buffered fetch:
        dispatch step k+1 (inputs taken from step k's ON-DEVICE pick),
        start the non-blocking device->host copy of step k+1's outputs,
        then drain step k's copy — which was started a full iteration
        ago and has had an entire decode step to complete — and
        distribute its tokens. Eviction therefore lags one step (a
        finished slot rides one extra suppressed step before its slot
        frees), buying max(step, fetch) instead of step + fetch per
        token; in steady state the drain returns an already-landed
        buffer and the loop does ZERO avoidable host<->device traffic
        per step (sampling params device-resident, no per-step
        uploads)."""
        inflight = None  # (snapshot [(slot, gen, handle)], tokens_dev, lengths_dev)
        while not self._stop_evt.is_set():
            try:
                t_iter = time.perf_counter()
                if self._paged:
                    self._apply_kv_chaos()
                with self._lock:
                    self._admit_locked()
                # HOL watchdog: prefill passes (never the bare decode
                # path) are timed, and a pass that stalls active decode
                # slots past serve_hol_threshold_s is recorded with the
                # prefilling request(s) to blame. Zero cost when nothing
                # is prefilling.
                if self._prefilling:  # rtlint: disable=RT010 — _prefilling is only mutated on this loop thread; the lock covers submit()-side readers
                    n_active = len(self._slots)
                    t_pf = time.perf_counter()
                    self._advance_prefills()
                    self._note_hol(time.perf_counter() - t_pf, n_active)
                with self._lock:
                    snapshot = [
                        (s, int(self._gen[s]), h)
                        for s, h in self._slots.items()
                    ]
                dispatch_s = 0.0
                if snapshot:
                    if self._params_dirty:
                        self._upload_sampling_state()
                    if self._paged and self._bt_dirty:
                        self._upload_block_table()
                    t0 = time.perf_counter()
                    if self._paged:
                        if self._sampled_active:
                            self._rng, step_key = jax.random.split(self._rng)
                            (next_dev, self._k, self._v,
                             self._lengths) = self._decode_sampled(
                                self.params, self._tokens_dev,
                                self._k, self._v, self._lengths,
                                self._active_dev, self._bt_dev,
                                self._temps_dev, self._top_ks_dev,
                                self._top_ps_dev, step_key,
                            )
                        else:
                            (next_dev, self._k, self._v,
                             self._lengths) = self._decode_greedy(
                                self.params, self._tokens_dev,
                                self._k, self._v, self._lengths,
                                self._active_dev, self._bt_dev,
                            )
                    elif self._sampled_active:
                        self._rng, step_key = jax.random.split(self._rng)
                        (next_dev, self._k, self._v,
                         self._lengths) = self._decode_sampled(
                            self.params, self._tokens_dev,
                            self._k, self._v, self._lengths,
                            self._active_dev, self._temps_dev,
                            self._top_ks_dev, self._top_ps_dev, step_key,
                        )
                    else:
                        (next_dev, self._k, self._v,
                         self._lengths) = self._decode_greedy(
                            self.params, self._tokens_dev,
                            self._k, self._v, self._lengths,
                            self._active_dev,
                        )
                    self._tokens_dev = next_dev
                    # Start the D2H copy NOW: it lands while this thread
                    # distributes the previous step's tokens and the
                    # next iteration dispatches — the drain below then
                    # finds a finished buffer instead of blocking.
                    try:
                        next_dev.copy_to_host_async()
                        self._lengths.copy_to_host_async()
                    except Exception:  # rtlint: disable=RT007 — optional prefetch; device_get covers it
                        pass
                    dispatch_s = time.perf_counter() - t0
                    new_inflight = (snapshot, next_dev, self._lengths)
                else:
                    new_inflight = None
                fetch_s = 0.0
                if inflight is not None:
                    prev_snapshot, prev_tokens, prev_lengths = inflight
                    t0 = time.perf_counter()
                    # Intentional single drain: copy_to_host_async above
                    # started this transfer a full step ago, so this is
                    # the double-buffered collect, not a per-step sync.
                    toks, lengths_np = jax.device_get(  # rtlint: disable=RT001
                        (prev_tokens, prev_lengths)
                    )
                    fetch_s = time.perf_counter() - t0
                    now_wall = time.time()
                    with self._lock:
                        self._steps += 1
                        for s, gen, h in prev_snapshot:
                            if (self._gen[s] != gen
                                    or self._slots.get(s) is not h):
                                continue  # evicted under the lag
                            if h.cancelled or (
                                h.deadline_ts and now_wall > h.deadline_ts
                            ):
                                # Dead work never holds a TPU slot: evict
                                # mid-decode, fail the handle (cancel()
                                # already did for the cancelled case),
                                # and let the one in-flight step's token
                                # be suppressed by the generation bump.
                                if not h.cancelled:
                                    self._deadline_expired += 1
                                    h._fail(RequestCancelledError(
                                        f"deadline expired mid-decode "
                                        f"(request {h.request_id}, "
                                        f"{h.produced} tokens produced)",
                                        reason="deadline",
                                        rid=str(h.request_id),
                                    ))
                                    observatory.record_deadline_expired(
                                        "", "engine_decode"
                                    )
                                del self._slots[s]
                                self._free.append(s)
                                self._gen[s] += 1
                                self._active[s] = False
                                self._temps[s] = 0.0
                                self._top_ks[s] = 0
                                self._top_ps[s] = 1.0
                                self._params_dirty = True
                                if self._paged:
                                    self._release_slot_pages_locked(s)
                                continue
                            tok = int(toks[s])
                            h.produced += 1
                            done = (
                                (self.eos_id is not None
                                 and tok == self.eos_id)
                                or h.produced >= h.max_new_tokens
                                # One in-flight step may still write:
                                # keep a row of margin.
                                or int(lengths_np[s]) >= self.max_len - 2
                            )
                            h._push(tok, done)
                            if done:
                                del self._slots[s]
                                self._free.append(s)
                                self._gen[s] += 1
                                self._active[s] = False
                                self._temps[s] = 0.0
                                self._top_ks[s] = 0
                                self._top_ps[s] = 1.0
                                self._params_dirty = True
                                if self._paged:
                                    self._release_slot_pages_locked(s)
                inflight = new_inflight
                if snapshot:
                    host_s = max(
                        time.perf_counter() - t_iter - dispatch_s - fetch_s,
                        0.0,
                    )
                    m = _engine_metrics()
                    m["dispatch_ms"].observe(dispatch_s * 1e3)
                    m["fetch_ms"].observe(fetch_s * 1e3)
                    m["host_ms"].observe(host_s * 1e3)
                    m["occupancy"].set(len(snapshot) / self.num_slots)
                    m["waiting"].set(float(self._waiting_n))  # rtlint: disable=RT010 — gauge snapshot: a stale int is fine
                    if self._paged:
                        m["kv_pages"].set(float(self._pool.in_use))
                    compiles = self._compile_count()
                    grew = compiles - self._last_compiles
                    if grew > 0:
                        self._last_compiles = compiles
                        m["recompiles"].inc(grew)
                    with self._lock:
                        self._t_dispatch += dispatch_s
                        self._t_fetch += fetch_s
                        self._t_host += host_s
                        self._timed_steps += 1
                        if grew > 0:
                            self._recompiles += grew
                if inflight is None and not self._prefilling:
                    self._work.wait(timeout=0.5)
                    self._work.clear()
            except BaseException as e:  # noqa: BLE001 — fail all, keep serving
                with self._lock:
                    pending = (
                        list(self._slots.values())
                        + self._drain_waiting_locked()
                        + [en["h"] for en in self._prefilling.values()]
                    )
                    for h in pending:
                        h._fail(e)
                    self._slots.clear()
                    self._prefilling.clear()
                    self._free = deque(range(self.num_slots))
                    # Donated buffers may have been consumed mid-failure:
                    # rebuild the cache (mesh placement included) before
                    # serving again.
                    cache = self._fresh_cache()
                    self._k, self._v = cache["k"], cache["v"]
                    self._lengths = cache["lengths"]
                    if self._paged:
                        # Every outstanding page reference pointed into
                        # the dead cache: reset the allocator, drop the
                        # prefix cache WITHOUT releasing (the refs are
                        # void), zero the table.
                        self._bt_dev = cache["block_tables"]
                        self._pool.reset()
                        if self._prefix_cache is not None:
                            self._prefix_cache.reset()
                        self._slot_pages.clear()
                        self._chaos_held = []
                        self._bt_host[:] = 0
                        self._bt_dirty = False
                    self._tokens_dev = jnp.zeros(
                        self.num_slots, dtype=jnp.int32
                    )
                    self._gen += 1  # orphan any in-flight snapshot
                    self._active[:] = False
                    self._temps[:] = 0.0
                    self._top_ks[:] = 0
                    self._top_ps[:] = 1.0
                    self._params_dirty = True
                inflight = None
                time.sleep(0.1)


class LLMReplica:
    """Replica class wrapping the engine: blocking generate, token
    streaming (rides the replica generator protocol -> SSE at the
    proxy), and engine stats for observability."""

    def __init__(self, model_loader, num_slots: int = 4, max_len: int = 256,
                 eos_id: Optional[int] = None,
                 default_max_new_tokens: int = 32,
                 prefill_chunk: int = 64, kv_mode: Optional[str] = None,
                 page_size: Optional[int] = None,
                 kv_pages: Optional[int] = None):
        # The loader runs IN the replica process and may return
        # (params, cfg) or (params, cfg, mesh) — a Mesh cannot cross
        # the actor boundary as an argument, so tensor-parallel serving
        # builds its mesh (and shards params) inside the loader.
        loaded = model_loader()
        mesh = None
        if len(loaded) == 3:
            params, cfg, mesh = loaded
        else:
            params, cfg = loaded
        self.engine = ContinuousBatchingEngine(
            params, cfg, num_slots=num_slots, max_len=max_len,
            eos_id=eos_id, default_max_new_tokens=default_max_new_tokens,
            mesh=mesh, prefill_chunk=prefill_chunk, kv_mode=kv_mode,
            page_size=page_size, kv_pages=kv_pages,
        )

    def __call__(self, prompt, max_new_tokens: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None):
        # A propagated deadline bounds the blocking wait too (the engine
        # would cancel the slot anyway — don't outlive it by waiting the
        # full configured timeout).
        budget = request_context.remaining_budget()
        timeout = get_config().serve_result_timeout_s
        if budget != float("inf"):
            timeout = max(0.01, min(timeout, budget))
        return self.engine.submit(
            prompt, max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p,
        ).result(timeout=timeout)

    def stream(self, prompt, max_new_tokens: Optional[int] = None,
               temperature: float = 0.0, top_k: Optional[int] = None,
               top_p: Optional[float] = None):
        h = self.engine.submit(
            prompt, max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p,
        )
        try:
            yield from h
        except GeneratorExit:
            # The consumer abandoned the stream (replica cancel_stream,
            # deadline expiry, client disconnect): free the decode slot
            # instead of generating tokens nobody reads.
            h.cancel("client")
            raise

    def stats(self):
        return self.engine.stats()

    def __del__(self):  # rtlint: disable=RT007
        # Finalizer during interpreter teardown: modules may already be
        # unloaded, and raising from __del__ only prints noise.
        try:
            self.engine.shutdown()
        except Exception:  # noqa: BLE001
            pass


def llm_deployment(model_loader, *, num_slots: int = 4, max_len: int = 256,
                   eos_id: Optional[int] = None,
                   default_max_new_tokens: int = 32, num_replicas: int = 1,
                   max_ongoing_requests: int = 64,
                   ray_actor_options: Optional[dict] = None,
                   prefill_chunk: int = 64, kv_mode: Optional[str] = None,
                   page_size: Optional[int] = None,
                   kv_pages: Optional[int] = None):
    """A ready-to-run continuous-batching LLM application.

        app = llm_deployment(lambda: (params, cfg), num_slots=8)
        handle = serve.run(app, name="llm")
        tokens = handle.remote([1, 2, 3])          # blocking generate
        for t in handle.options(stream=True, method_name="stream") \
                .remote([1, 2, 3]): ...            # token stream

    max_ongoing_requests defaults high: admission control lives in the
    engine (waiting queue + slots), not the router."""
    from ray_tpu.serve.deployment import deployment

    dep = deployment(
        LLMReplica,
        name="LLMReplica",
        num_replicas=num_replicas,
        max_ongoing_requests=max_ongoing_requests,
        ray_actor_options=ray_actor_options or {},
    )
    return dep.bind(
        model_loader, num_slots=num_slots, max_len=max_len, eos_id=eos_id,
        default_max_new_tokens=default_max_new_tokens,
        prefill_chunk=prefill_chunk, kv_mode=kv_mode,
        page_size=page_size, kv_pages=kv_pages,
    )
