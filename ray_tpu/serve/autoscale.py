"""ServeSignals-driven replica autoscaling policy.

The controller's original `_autoscale` probed every replica with an
actor call per reconcile tick — O(replicas) RPCs just to learn the
queue depth the observatory already publishes. This module is the
other half of PR 7's signal plane: a PURE decision function over the
published ServeSignals snapshot (one `kv_get`, zero actor calls) that
the controller consults each tick.

Signals consulted, in order of authority:

  * mean ongoing requests per reachable replica vs
    `target_ongoing_requests` (the reference autoscaler's primary);
  * engine admission-queue depth per replica vs `upscale_queue_depth`
    (saturation shows here before latency does);
  * TTFT p99 vs `ttft_p99_high_ms` and the max tenant SLO burn rate vs
    `burn_rate_high` — both opt-in (None disables), both upscale-only
    pressure plus a hold against scaling down while elevated.

Hysteresis: pressure must persist for `upscale_delay_s` (resp.
`downscale_delay_s`) before the target moves, one replica per move,
with the same delay as a cooldown between moves — so a traffic ramp
walks the replica count up and back down instead of flapping. The
function is pure in `now`, which is what makes the hysteresis unit-
testable with a fake clock (tests/test_paged_kv.py drives it through
minutes of synthetic traffic in microseconds).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional


@dataclass
class AutoscalerState:
    """Per-app hysteresis memory the controller keeps between ticks."""

    high_since: Optional[float] = None   # pressure-up first seen at
    low_since: Optional[float] = None    # pressure-down first seen at
    last_change: Optional[float] = None  # last target move (cooldown)
    last_reason: str = ""                # why the last decision happened


def _max_burn(app_signals: Dict) -> float:
    burn = 0.0
    for rows in (app_signals.get("tenants") or {}).values():
        for windows in (rows.get("slo_windows") or {}).values():
            for w in windows.values():
                try:
                    burn = max(burn, float(w.get("burn") or 0.0))
                except (TypeError, ValueError):
                    pass
    return burn


def extract_load(app_signals: Dict) -> Dict:
    """Flatten one app's signals into the numbers decide() scores.
    Tolerant of missing fields (older publishers)."""
    reps = [r for r in (app_signals.get("replicas") or [])
            if not r.get("unreachable")]
    ongoing = [float(r.get("ongoing") or 0.0) for r in reps]
    n = max(1, len(ongoing))
    ttft = (app_signals.get("ttft_s") or {})
    return {
        "replicas": len(reps),
        "ongoing_mean": sum(ongoing) / n,
        "waiting": float(app_signals.get("waiting") or 0.0),
        "waiting_per_replica": float(app_signals.get("waiting") or 0.0) / n,
        "ttft_p99_ms": (float(ttft["p99"]) * 1e3
                        if ttft.get("p99") is not None else None),
        "burn_max": _max_burn(app_signals),
    }


def decide(app_signals: Dict, acfg, state: AutoscalerState, now: float,
           current_target: int, running: int) -> int:
    """New replica target for one app. Mutates `state` (hysteresis
    memory); clamps to [min_replicas, max_replicas]; moves at most one
    replica per call. `now` is any monotonic clock."""
    load = extract_load(app_signals)
    up_reasons = []
    if load["ongoing_mean"] > acfg.target_ongoing_requests:
        up_reasons.append(
            f"ongoing {load['ongoing_mean']:.2f} > "
            f"target {acfg.target_ongoing_requests:g}")
    queue_high = getattr(acfg, "upscale_queue_depth", 1.0)
    if queue_high is not None and load["waiting_per_replica"] > queue_high:
        up_reasons.append(
            f"queued/replica {load['waiting_per_replica']:.2f} > "
            f"{queue_high:g}")
    ttft_high = getattr(acfg, "ttft_p99_high_ms", None)
    ttft_hot = (ttft_high is not None and load["ttft_p99_ms"] is not None
                and load["ttft_p99_ms"] > ttft_high)
    if ttft_hot:
        up_reasons.append(
            f"ttft p99 {load['ttft_p99_ms']:.0f}ms > {ttft_high:g}ms")
    burn_high = getattr(acfg, "burn_rate_high", None)
    burn_hot = burn_high is not None and load["burn_max"] > burn_high
    if burn_hot:
        up_reasons.append(f"burn {load['burn_max']:.2f} > {burn_high:g}")

    pressure_up = bool(up_reasons)
    # Downscale only when EVERY signal is comfortably idle: ongoing
    # under half the target, nothing queued, and no elevated latency or
    # burn holding the fleet where it is.
    pressure_down = (not pressure_up
                     and load["ongoing_mean"]
                     < 0.5 * acfg.target_ongoing_requests
                     and load["waiting"] == 0
                     and not ttft_hot and not burn_hot)

    target = current_target
    if pressure_up:
        state.low_since = None
        if state.high_since is None:
            state.high_since = now
        held = now - state.high_since
        cooled = (state.last_change is None
                  or now - state.last_change >= acfg.upscale_delay_s)
        if (held >= acfg.upscale_delay_s and cooled
                and current_target < acfg.max_replicas):
            target = current_target + 1
            state.last_change = now
            state.high_since = now  # re-arm: next step needs its own hold
            state.last_reason = "up: " + "; ".join(up_reasons)
    elif pressure_down:
        state.high_since = None
        if state.low_since is None:
            state.low_since = now
        held = now - state.low_since
        cooled = (state.last_change is None
                  or now - state.last_change >= acfg.downscale_delay_s)
        if (held >= acfg.downscale_delay_s and cooled
                and current_target > acfg.min_replicas):
            target = current_target - 1
            state.last_change = now
            state.low_since = now
            state.last_reason = (
                f"down: idle (ongoing {load['ongoing_mean']:.2f}, "
                f"waiting {load['waiting']:g})")
    else:
        state.high_since = None
        state.low_since = None

    return max(acfg.min_replicas, min(acfg.max_replicas, target))
