"""ray_tpu.serve: model serving.

Public surface mirrors the reference's ray.serve: @serve.deployment,
serve.run / serve.delete / serve.status / serve.shutdown,
DeploymentHandle composition, queue-length autoscaling, and an HTTP proxy.
TPU-aware replica placement comes from ray_actor_options resources (e.g.
{"TPU": 4} or a pod gang resource) flowing into the actor scheduler.
"""

from __future__ import annotations

import logging
from typing import Optional

import ray_tpu as rt
from ray_tpu._private.config import get_config
from ray_tpu.serve.controller import CONTROLLER_NAME, get_or_create_controller
from ray_tpu.serve.deployment import (
    Application,
    AutoscalingConfig,
    Deployment,
    SloConfig,
    deployment,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.proxy import ProxyActor
from ray_tpu.serve.schema import run_from_config

logger = logging.getLogger("ray_tpu.serve")

_proxy = None


def run(app: Application, name: Optional[str] = None,
        _blocking: bool = True) -> DeploymentHandle:
    """Deploy an application (reference: serve.run, serve/api.py:429).

    Bound applications nested in init args/kwargs deploy first and
    arrive as DeploymentHandles — the reference's composition idiom:

        handle = serve.run(Pipeline.bind(Preprocess.bind()))

    Duplicate deployment names within one composition pass are
    uniquified with _1/_2 suffixes (the reference's DAG builder does
    the same), so two bound instances of the same class route to their
    own deployments instead of the second silently replacing the first.
    Suffix assignment is deterministic left-to-right, so re-running the
    same graph redeploys over the same names.
    """
    controller = get_or_create_controller()
    return _run_app(app, name, controller, set(), {})


def _run_app(app: Application, name: Optional[str], controller,
             used_names: set, resolved: dict) -> DeploymentHandle:
    # The same Application OBJECT appearing twice in a graph (a shared
    # dependency) stays one deployment; only distinct .bind() calls
    # with colliding names are uniquified.
    if id(app) in resolved:
        return resolved[id(app)]
    base = name or app.deployment.name
    app_name, i = base, 1
    while app_name in used_names:
        app_name = f"{base}_{i}"
        i += 1
    used_names.add(app_name)

    def resolve(obj):
        if isinstance(obj, Application):
            return _run_app(obj, None, controller, used_names, resolved)
        if isinstance(obj, (list, tuple)):
            return type(obj)(resolve(v) for v in obj)
        if isinstance(obj, dict):
            return {k: resolve(v) for k, v in obj.items()}
        return obj

    init_args = tuple(resolve(a) for a in app.init_args)
    init_kwargs = {k: resolve(v) for k, v in app.init_kwargs.items()}
    _reject_buried_applications((init_args, init_kwargs), app_name)
    rt.get(
        controller.deploy.remote(
            app_name, app.deployment, init_args, init_kwargs
        ),
        timeout=get_config().serve_deploy_timeout_s,
    )
    handle = DeploymentHandle(app_name)
    resolved[id(app)] = handle
    return handle


def _reject_buried_applications(obj, app_name: str, _seen=None, _depth=0):
    """An Application that survives resolution (e.g. buried in a user
    object's attributes) would arrive at the replica as a raw graph node
    and fail there with an opaque error; fail here with a clear one.
    Containers were already resolved — this walks one extra level into
    plain-object attributes, bounded by depth and an id-set."""
    if isinstance(obj, Application):
        raise ValueError(
            f"init args of deployment {app_name!r} contain a bound "
            "Application inside an unsupported container or object "
            "attribute; pass nested .bind() apps directly, or in "
            "lists/tuples/dicts, so serve.run can deploy them "
            "and inject DeploymentHandles."
        )
    if _depth > 4:
        return
    if _seen is None:
        _seen = set()
    if id(obj) in _seen:
        return
    _seen.add(id(obj))
    if isinstance(obj, (list, tuple, set, frozenset)):
        for v in obj:
            _reject_buried_applications(v, app_name, _seen, _depth + 1)
    elif isinstance(obj, dict):
        for v in obj.values():
            _reject_buried_applications(v, app_name, _seen, _depth + 1)
    elif hasattr(obj, "__dict__") and not isinstance(obj, type):
        for v in vars(obj).values():
            _reject_buried_applications(v, app_name, _seen, _depth + 1)


def call(app_name: str, *args, method: str = "__call__", **kwargs):
    """Invoke a deployment and return its result, synchronously.

    The cross-language serving entry point: a foreign client submits the
    task `ray_tpu.serve:call` with plain args (e.g. the C++ client's
    Submit("ray_tpu.serve:call", {app, payload...})), the executing pool
    worker builds a handle and routes through the normal data plane —
    power-of-two choice, batching, multiplexing all apply. (Reference
    analog: the gRPC proxy's role for non-Python serve clients.)
    """
    handle = get_app_handle(app_name)
    if method != "__call__":
        handle = handle.options(method_name=method)
    return handle.remote(*args, **kwargs).result(
        timeout=get_config().serve_result_timeout_s
    )


def get_app_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def delete(name: str):
    controller = get_or_create_controller()
    rt.get(controller.delete.remote(name),
           timeout=get_config().serve_admin_timeout_s)


def status() -> dict:
    controller = get_or_create_controller()
    return rt.get(controller.status.remote(),
                  timeout=get_config().serve_admin_timeout_s)


def shutdown():
    global _proxy
    try:
        controller = rt.get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    try:
        rt.get(controller.shutdown.remote(),
               timeout=get_config().serve_admin_timeout_s)
        rt.kill(controller)
    except Exception:  # noqa: BLE001 — teardown is best-effort
        logger.warning("serve controller shutdown did not complete "
                       "cleanly; its actors may linger", exc_info=True)
    _proxy = None


def start_http_proxy(host: str = "127.0.0.1", port: int = 8000):
    """Start the HTTP ingress (reference: proxies start with serve.start)."""
    global _proxy
    if _proxy is None:
        _proxy = ProxyActor.options(num_cpus=0.1).remote(host, port)
        rt.get(_proxy.ready.remote(),
               timeout=get_config().serve_ready_timeout_s)
    return rt.get(_proxy.address.remote(),
                  timeout=get_config().serve_ready_timeout_s)


def start(proxy_location: str = "HeadOnly", host: str = "127.0.0.1",
          port: int = 8000):
    """Start serve's ingress tier (reference: serve.start + ProxyLocation).

    ``proxy_location="EveryNode"`` hands proxy lifecycle to the
    controller's ProxyStateManager: one proxy actor per ALIVE node
    (node-affinity pinned, dead ones replaced each reconcile tick), each
    exposing HTTP and a binary msgpack-framed ingress. Returns the
    node_id -> address map ({"http": ..., "binary": [host, port]})."""
    controller = get_or_create_controller()
    if proxy_location == "EveryNode":
        rt.get(controller.start_proxies.remote(),
               timeout=get_config().serve_deploy_timeout_s)
        return rt.get(controller.proxy_addresses.remote(),
                      timeout=get_config().serve_admin_timeout_s)
    return {"head": {"http": start_http_proxy(host, port), "binary": None}}


def proxy_addresses() -> dict:
    """Live per-node proxy addresses (EveryNode mode)."""
    controller = get_or_create_controller()
    return rt.get(controller.proxy_addresses.remote(),
                  timeout=get_config().serve_admin_timeout_s)


__all__ = [
    "deployment",
    "Deployment",
    "Application",
    "AutoscalingConfig",
    "SloConfig",
    "DeploymentHandle",
    "DeploymentResponse",
    "run",
    "get_app_handle",
    "delete",
    "status",
    "shutdown",
    "start",
    "start_http_proxy",
    "proxy_addresses",
    "run_from_config",
]
