"""Replica actors: host the user callable.

Analog of the reference's ReplicaActor (serve/_private/replica.py:240;
UserCallableWrapper :667; streaming handler :478): wraps the deployment's
class/function, tracks ongoing requests (the queue-length signal the
router and autoscaler consume), executes calls — concurrently on executor
threads when the deployment allows it — and streams generator responses
chunk-by-chunk to pollers.
"""

from __future__ import annotations

import inspect
import itertools
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import ray_tpu as rt
from ray_tpu._private.config import get_config
from ray_tpu.exceptions import (
    ReplicaDrainingError,
    RequestCancelledError,
    ServeOverloadedError,
)
from ray_tpu.serve.context import RequestMeta, bind as bind_meta


class _StreamBuf:
    """Chunks produced by a generator request, consumed by long-polls."""

    def __init__(self):
        self.chunks: list = []
        self.done = False
        self.error: Optional[str] = None
        self.cond = threading.Condition()
        self.last_read = time.monotonic()
        self.cancelled = False


@rt.remote
class ReplicaActor:
    def __init__(self, cls_or_fn, init_args, init_kwargs, user_config=None,
                 app_name: str = "", slo=None, max_ongoing: int = 0):
        self._is_function = not inspect.isclass(cls_or_fn)
        if self._is_function:
            self.callable = cls_or_fn
        else:
            self.callable = cls_or_fn(*init_args, **init_kwargs)
            if user_config is not None and hasattr(
                self.callable, "reconfigure"
            ):
                self.callable.reconfigure(user_config)
        self.ongoing = 0
        self.total_served = 0
        self._streams: Dict[int, _StreamBuf] = {}
        self._stream_ids = itertools.count(1)
        self._lock = threading.Lock()
        # Survival plane: bounded admission (max_ongoing executing +
        # serve_max_queued_per_replica queued streams; 0 = unbounded),
        # the drain latch scale-down flips before this process exits,
        # and the idempotency cache that makes redispatch-after-death
        # safe to send twice.
        self._max_ongoing = int(max_ongoing)
        self._draining = False
        self._idem: "OrderedDict[str, Dict]" = OrderedDict()
        # Label this process's request observatory with the deployment
        # name + declared SLO (one replica per process).
        self._app_name = app_name or type(self.callable).__name__
        from ray_tpu.serve import observatory
        from ray_tpu.util import journal

        observatory.configure(self._app_name, slo)
        journal.set_process_label(f"replica:{self._app_name}")

    def _target(self, method: str):
        if self._is_function:
            return self.callable
        return getattr(self.callable, method or "__call__")

    # -- admission (survival plane) -----------------------------------
    def _admit(self, meta: RequestMeta) -> None:
        """Gate every request BEFORE any work happens: draining replicas
        refuse (handle redispatches like a death), expired deadlines
        cancel (the budget is gone — executing would be dead work), and
        past the bounded queue we shed with a typed 429-shaped error
        instead of letting the backlog collapse."""
        from ray_tpu.serve import observatory

        if self._draining:  # rtlint: disable=RT010 — racy fast-path refusal by design: drain's lock-guarded ongoing check is the real fence
            observatory.record_shed(self._app_name, meta.tenant, "draining")
            raise ReplicaDrainingError(
                f"replica for {self._app_name!r} is draining",
                app=self._app_name,
            )
        if meta.expired():
            observatory.record_deadline_expired(self._app_name, "replica")
            raise RequestCancelledError(
                f"deadline expired before replica execution "
                f"(rid={meta.rid or '-'})",
                reason="deadline", app=self._app_name, rid=meta.rid,
            )
        if self._max_ongoing > 0:
            bound = (self._max_ongoing
                     + get_config().serve_max_queued_per_replica)
            with self._lock:
                cur = self.ongoing
            if cur >= bound:
                observatory.record_shed(
                    self._app_name, meta.tenant, "queue_full"
                )
                raise ServeOverloadedError(
                    f"replica admission queue full "
                    f"({cur} ongoing >= {bound})",
                    app=self._app_name, tenant=meta.tenant,
                    reason="queue_full",
                )

    # -- idempotency (safe redispatch) --------------------------------
    def _idem_claim(self, key: str) -> Optional[Dict]:
        """Claim or join an idempotency entry. Returns None when this
        call is the owner (it must execute and publish via
        _idem_publish); otherwise the existing entry to wait on."""
        with self._lock:
            entry = self._idem.get(key)
            if entry is not None:
                self._idem.move_to_end(key)
                return entry
            self._idem[key] = {
                "evt": threading.Event(), "value": None, "error": None,
            }
            while len(self._idem) > get_config().serve_idem_cache_size:
                self._idem.popitem(last=False)
            return None

    def _idem_publish(self, key: str, value=None, error=None) -> None:
        """Publish the owner's outcome. Successes stay cached (bounded
        LRU) so a duplicate redispatch returns the SAME result; errors
        are handed to current waiters but evicted so a later retry
        re-executes."""
        with self._lock:
            entry = self._idem.get(key)
            if entry is None:
                return
            entry["value"] = value
            entry["error"] = error
            entry["evt"].set()
            if error is not None:
                self._idem.pop(key, None)

    def _idem_join(self, entry: Dict, meta: RequestMeta):
        """Wait (deadline-bounded) for the owning execution's outcome."""
        budget = meta.remaining()
        timeout = get_config().serve_result_timeout_s
        if budget != float("inf"):
            timeout = max(0.01, min(timeout, budget))
        if not entry["evt"].wait(timeout=timeout):
            raise RequestCancelledError(
                "timed out joining the in-flight duplicate of this "
                f"request (idem_key race, rid={meta.rid or '-'})",
                reason="deadline", app=self._app_name, rid=meta.rid,
            )
        if entry["error"] is not None:
            raise entry["error"]
        return entry["value"]

    def handle_request(self, method: str, args, kwargs, model_id: str = "",
                       trace_ctx: Optional[Dict[str, str]] = None,
                       obs_ctx: Optional[Dict] = None,
                       meta: Optional[Dict] = None):
        """Execute one request (reference: replica.py handle_request).

        ``meta`` is the survival-plane wire dict (deadline, tenant,
        idem_key): admission is gated on it, and it is bound to the
        request thread so engine code the callable reaches can read the
        deadline without plumbing."""
        from ray_tpu.serve.multiplex import _set_request_model_id
        from ray_tpu.serve import observatory
        from ray_tpu.util import tracing

        rmeta = RequestMeta.from_wire(meta)
        self._admit(rmeta)
        # Idempotent redispatch: a duplicate of an already-seen logical
        # request joins/returns the original execution instead of
        # running twice (a retry after ActorUnavailableError may race a
        # still-executing first attempt).
        if rmeta.idem_key:
            entry = self._idem_claim(rmeta.idem_key)
            if entry is not None:
                return self._idem_join(entry, rmeta)
        with self._lock:
            self.ongoing += 1
        octx = observatory.begin(obs_ctx, self._app_name, method)
        try:
            _set_request_model_id(model_id)
            target = self._target(method)
            with tracing.activate(
                trace_ctx,
                f"serve.{type(self.callable).__name__}"
                f".{method or '__call__'}",
            ), bind_meta(rmeta):
                if inspect.iscoroutinefunction(target):
                    import asyncio

                    out = asyncio.run(target(*args, **kwargs))
                else:
                    out = target(*args, **kwargs)
            if rmeta.idem_key:
                self._idem_publish(rmeta.idem_key, value=out)
            return out
        except BaseException as e:  # noqa: BLE001 — published then re-raised
            if rmeta.idem_key:
                self._idem_publish(rmeta.idem_key, error=e)
            raise
        finally:
            observatory.finish(octx)
            _set_request_model_id("")
            with self._lock:
                self.ongoing -= 1
                self.total_served += 1

    # -- streaming (reference: handle_request_streaming, replica.py:478) --
    def start_stream(self, method: str, args, kwargs,
                     model_id: str = "",
                     trace_ctx: Optional[Dict[str, str]] = None,
                     obs_ctx: Optional[Dict] = None,
                     meta: Optional[Dict] = None) -> int:
        """Begin a generator request; returns a stream id to poll."""
        rmeta = RequestMeta.from_wire(meta)
        self._admit(rmeta)
        sid = next(self._stream_ids)
        buf = _StreamBuf()
        with self._lock:
            self._streams[sid] = buf
            self.ongoing += 1

        def run():
            from ray_tpu.serve.multiplex import _set_request_model_id
            from ray_tpu.serve import observatory
            from ray_tpu.util import tracing

            # begin() in THIS thread: the generator body (and its
            # engine submit()) executes here, so thread-local capture
            # lands the engine's marks on this request's card.
            octx = observatory.begin(obs_ctx, self._app_name, method)
            try:
                _set_request_model_id(model_id)
                with tracing.activate(
                    trace_ctx,
                    f"serve.{type(self.callable).__name__}"
                    f".{method or '__call__'} [stream]",
                ), bind_meta(rmeta):
                    gen = self._target(method)(*args, **kwargs)
                    for chunk in gen:
                        # Abandoning the for-loop closes `gen`
                        # (GeneratorExit reaches engine-backed streams'
                        # cancel path via LLMReplica.stream).
                        if buf.cancelled:
                            gen.close()
                            raise RequestCancelledError(
                                f"stream {sid} cancelled by caller",
                                reason="client", app=self._app_name,
                                rid=rmeta.rid,
                            )
                        if rmeta.expired():
                            gen.close()
                            observatory.record_deadline_expired(
                                self._app_name, "replica"
                            )
                            raise RequestCancelledError(
                                f"deadline expired mid-stream "
                                f"(stream {sid})",
                                reason="deadline", app=self._app_name,
                                rid=rmeta.rid,
                            )
                        with buf.cond:
                            buf.chunks.append(chunk)
                            buf.cond.notify_all()
            except BaseException as e:  # noqa: BLE001 — crosses the wire
                with buf.cond:
                    buf.error = f"{type(e).__name__}: {e}"
            finally:
                observatory.finish(octx)
                _set_request_model_id("")
                with buf.cond:
                    buf.done = True
                    buf.cond.notify_all()
                with self._lock:
                    self.ongoing -= 1
                    self.total_served += 1

        threading.Thread(target=run, daemon=True).start()
        return sid

    def cancel_stream(self, stream_id: int) -> bool:
        """Caller-side stream cancellation: flips the buffer's cancel
        latch (the producer thread notices at its next chunk boundary,
        closes the generator — engine streams free their decode slot via
        GeneratorExit -> GenerationHandle.cancel) and wakes any poller."""
        # start_stream registers under the lock from other request
        # threads; read under it too so a cancel can never miss a
        # stream whose registration is mid-flight.
        with self._lock:
            buf = self._streams.get(stream_id)
        if buf is None:
            return False
        with buf.cond:
            buf.cancelled = True
            buf.cond.notify_all()
        return True

    def next_chunks(self, stream_id: int, start: int,
                    max_wait_s: float = 2.0) -> Dict:
        """Long-poll chunks [start:]; returns {chunks, done, error}."""
        # Same rationale as cancel_stream: registration happens under
        # the lock on another request thread.
        with self._lock:
            buf = self._streams.get(stream_id)
        if buf is None:
            return {"chunks": [], "done": True,
                    "error": f"unknown stream {stream_id}"}
        with buf.cond:
            if len(buf.chunks) <= start and not buf.done:
                buf.cond.wait(timeout=max_wait_s)
            out = buf.chunks[start:]
            done = buf.done and start + len(out) >= len(buf.chunks)
            err = buf.error
            buf.last_read = time.monotonic()
        if done:
            with self._lock:
                self._streams.pop(stream_id, None)
        else:
            self._gc_streams()
        return {"chunks": out, "done": done, "error": err}

    def _gc_streams(self, idle_s: float = 300.0):
        now = time.monotonic()
        with self._lock:
            stale = [
                sid for sid, b in self._streams.items()
                if b.done and now - b.last_read > idle_s
            ]
            for sid in stale:
                self._streams.pop(sid, None)

    def queue_len(self) -> int:
        """Queue-length probe (reference: power-of-two router probes)."""
        return self.ongoing  # rtlint: disable=RT010 — racy probe by design (power-of-two routing tolerates staleness)

    def drain(self, timeout_s: Optional[float] = None) -> Dict:
        """Graceful drain: stop admitting (new requests see
        ReplicaDrainingError and redispatch elsewhere), then wait —
        bounded by serve_drain_timeout_s — for in-flight requests to
        finish. The controller calls this before killing the process on
        scale-down/replace, so accepted requests complete instead of
        dying with the actor. Returns {drained, duration_s, remaining}."""
        from ray_tpu.serve import observatory

        if timeout_s is None:
            timeout_s = get_config().serve_drain_timeout_s
        with self._lock:
            self._draining = True
        t0 = time.monotonic()
        deadline = t0 + max(0.0, float(timeout_s))
        while time.monotonic() < deadline:
            with self._lock:
                if self.ongoing <= 0:
                    break
            time.sleep(0.02)
        dur = time.monotonic() - t0
        with self._lock:
            remaining = self.ongoing
        observatory.record_drain(self._app_name, dur)
        return {"drained": remaining <= 0, "duration_s": dur,
                "remaining": remaining}

    def is_draining(self) -> bool:
        return self._draining

    def stats(self) -> Dict:
        out = {"ongoing": self.ongoing, "total_served": self.total_served}  # rtlint: disable=RT010 — stats snapshot: torn reads are acceptable
        # Batch-size observability for @serve.batch methods.
        if not self._is_function:
            sizes = {}
            for k, v in self.callable.__dict__.items():
                if k.startswith("__serve_batch_queue_"):
                    sizes[k.removeprefix("__serve_batch_queue_")] = list(
                        v.batch_sizes
                    )
            if sizes:
                out["batch_sizes"] = sizes
        return out

    def observatory_records(self) -> List[Dict]:
        """Finished-request phase records from this replica's
        observatory ring (bounded by RT_SERVE_OBS_RING). The loadgen
        reconciler joins these by rid against client stamp cards to
        compute per-request unattributed gaps."""
        from ray_tpu.serve import observatory

        return observatory.profiler().records()

    def observatory_snapshot(self) -> Dict:
        """Per-replica half of ServeSignals (controller merges these
        across replicas each publish tick)."""
        from ray_tpu.serve import observatory

        snap = observatory.profiler().snapshot()
        snap["ongoing"] = self.ongoing
        snap["total_served"] = self.total_served
        snap["draining"] = self._draining
        # Engine-backed deployments contribute occupancy/backlog/HOL.
        if not self._is_function:
            engine = getattr(self.callable, "engine", None)
            if engine is not None and hasattr(engine, "stats"):
                try:
                    es = engine.stats()
                    snap["engine"] = {
                        "active": es.get("active"),
                        "waiting": es.get("waiting"),
                        "prefilling": es.get("prefilling"),
                        "occupancy": es.get("latency", {}).get("occupancy"),
                        "hol": es.get("hol"),
                        "kv": es.get("kv"),
                    }
                except Exception:  # rtlint: disable=RT007 — snapshot is best-effort
                    pass
        return snap

    def reconfigure(self, user_config):
        if hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)
        return True

    def health_check(self) -> bool:
        if hasattr(self.callable, "check_health"):
            self.callable.check_health()
        return True
