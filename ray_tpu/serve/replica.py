"""Replica actors: host the user callable.

Analog of the reference's ReplicaActor (serve/_private/replica.py:240;
UserCallableWrapper :667): wraps the deployment's class/function, tracks
ongoing requests (the queue-length signal the router and autoscaler
consume), and executes calls.
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, Optional

import ray_tpu as rt


@rt.remote
class ReplicaActor:
    def __init__(self, cls_or_fn, init_args, init_kwargs, user_config=None):
        self._is_function = not inspect.isclass(cls_or_fn)
        if self._is_function:
            self.callable = cls_or_fn
        else:
            self.callable = cls_or_fn(*init_args, **init_kwargs)
            if user_config is not None and hasattr(
                self.callable, "reconfigure"
            ):
                self.callable.reconfigure(user_config)
        self.ongoing = 0
        self.total_served = 0

    def handle_request(self, method: str, args, kwargs):
        """Execute one request (reference: replica.py handle_request)."""
        self.ongoing += 1
        try:
            if self._is_function:
                target = self.callable
            else:
                target = getattr(self.callable, method or "__call__")
            if inspect.iscoroutinefunction(target):
                import asyncio

                return asyncio.run(target(*args, **kwargs))
            return target(*args, **kwargs)
        finally:
            self.ongoing -= 1
            self.total_served += 1

    def queue_len(self) -> int:
        """Queue-length probe (reference: power-of-two router probes)."""
        return self.ongoing

    def stats(self) -> Dict:
        return {"ongoing": self.ongoing, "total_served": self.total_served}

    def reconfigure(self, user_config):
        if hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)
        return True

    def health_check(self) -> bool:
        if hasattr(self.callable, "check_health"):
            self.callable.check_health()
        return True
