"""Replica actors: host the user callable.

Analog of the reference's ReplicaActor (serve/_private/replica.py:240;
UserCallableWrapper :667; streaming handler :478): wraps the deployment's
class/function, tracks ongoing requests (the queue-length signal the
router and autoscaler consume), executes calls — concurrently on executor
threads when the deployment allows it — and streams generator responses
chunk-by-chunk to pollers.
"""

from __future__ import annotations

import inspect
import itertools
import threading
import time
from typing import Any, Dict, Optional

import ray_tpu as rt


class _StreamBuf:
    """Chunks produced by a generator request, consumed by long-polls."""

    def __init__(self):
        self.chunks: list = []
        self.done = False
        self.error: Optional[str] = None
        self.cond = threading.Condition()
        self.last_read = time.monotonic()


@rt.remote
class ReplicaActor:
    def __init__(self, cls_or_fn, init_args, init_kwargs, user_config=None,
                 app_name: str = "", slo=None):
        self._is_function = not inspect.isclass(cls_or_fn)
        if self._is_function:
            self.callable = cls_or_fn
        else:
            self.callable = cls_or_fn(*init_args, **init_kwargs)
            if user_config is not None and hasattr(
                self.callable, "reconfigure"
            ):
                self.callable.reconfigure(user_config)
        self.ongoing = 0
        self.total_served = 0
        self._streams: Dict[int, _StreamBuf] = {}
        self._stream_ids = itertools.count(1)
        self._lock = threading.Lock()
        # Label this process's request observatory with the deployment
        # name + declared SLO (one replica per process).
        self._app_name = app_name or type(self.callable).__name__
        from ray_tpu.serve import observatory

        observatory.configure(self._app_name, slo)

    def _target(self, method: str):
        if self._is_function:
            return self.callable
        return getattr(self.callable, method or "__call__")

    def handle_request(self, method: str, args, kwargs, model_id: str = "",
                       trace_ctx: Optional[Dict[str, str]] = None,
                       obs_ctx: Optional[Dict] = None):
        """Execute one request (reference: replica.py handle_request)."""
        from ray_tpu.serve.multiplex import _set_request_model_id
        from ray_tpu.serve import observatory
        from ray_tpu.util import tracing

        with self._lock:
            self.ongoing += 1
        octx = observatory.begin(obs_ctx, self._app_name, method)
        try:
            _set_request_model_id(model_id)
            target = self._target(method)
            with tracing.activate(
                trace_ctx,
                f"serve.{type(self.callable).__name__}"
                f".{method or '__call__'}",
            ):
                if inspect.iscoroutinefunction(target):
                    import asyncio

                    return asyncio.run(target(*args, **kwargs))
                return target(*args, **kwargs)
        finally:
            observatory.finish(octx)
            _set_request_model_id("")
            with self._lock:
                self.ongoing -= 1
                self.total_served += 1

    # -- streaming (reference: handle_request_streaming, replica.py:478) --
    def start_stream(self, method: str, args, kwargs,
                     model_id: str = "",
                     trace_ctx: Optional[Dict[str, str]] = None,
                     obs_ctx: Optional[Dict] = None) -> int:
        """Begin a generator request; returns a stream id to poll."""
        sid = next(self._stream_ids)
        buf = _StreamBuf()
        with self._lock:
            self._streams[sid] = buf
            self.ongoing += 1

        def run():
            from ray_tpu.serve.multiplex import _set_request_model_id
            from ray_tpu.serve import observatory
            from ray_tpu.util import tracing

            # begin() in THIS thread: the generator body (and its
            # engine submit()) executes here, so thread-local capture
            # lands the engine's marks on this request's card.
            octx = observatory.begin(obs_ctx, self._app_name, method)
            try:
                _set_request_model_id(model_id)
                with tracing.activate(
                    trace_ctx,
                    f"serve.{type(self.callable).__name__}"
                    f".{method or '__call__'} [stream]",
                ):
                    gen = self._target(method)(*args, **kwargs)
                    for chunk in gen:
                        with buf.cond:
                            buf.chunks.append(chunk)
                            buf.cond.notify_all()
            except BaseException as e:  # noqa: BLE001 — crosses the wire
                with buf.cond:
                    buf.error = f"{type(e).__name__}: {e}"
            finally:
                observatory.finish(octx)
                _set_request_model_id("")
                with buf.cond:
                    buf.done = True
                    buf.cond.notify_all()
                with self._lock:
                    self.ongoing -= 1
                    self.total_served += 1

        threading.Thread(target=run, daemon=True).start()
        return sid

    def next_chunks(self, stream_id: int, start: int,
                    max_wait_s: float = 2.0) -> Dict:
        """Long-poll chunks [start:]; returns {chunks, done, error}."""
        buf = self._streams.get(stream_id)
        if buf is None:
            return {"chunks": [], "done": True,
                    "error": f"unknown stream {stream_id}"}
        with buf.cond:
            if len(buf.chunks) <= start and not buf.done:
                buf.cond.wait(timeout=max_wait_s)
            out = buf.chunks[start:]
            done = buf.done and start + len(out) >= len(buf.chunks)
            err = buf.error
            buf.last_read = time.monotonic()
        if done:
            with self._lock:
                self._streams.pop(stream_id, None)
        else:
            self._gc_streams()
        return {"chunks": out, "done": done, "error": err}

    def _gc_streams(self, idle_s: float = 300.0):
        now = time.monotonic()
        with self._lock:
            stale = [
                sid for sid, b in self._streams.items()
                if b.done and now - b.last_read > idle_s
            ]
            for sid in stale:
                self._streams.pop(sid, None)

    def queue_len(self) -> int:
        """Queue-length probe (reference: power-of-two router probes)."""
        return self.ongoing

    def stats(self) -> Dict:
        out = {"ongoing": self.ongoing, "total_served": self.total_served}
        # Batch-size observability for @serve.batch methods.
        if not self._is_function:
            sizes = {}
            for k, v in self.callable.__dict__.items():
                if k.startswith("__serve_batch_queue_"):
                    sizes[k.removeprefix("__serve_batch_queue_")] = list(
                        v.batch_sizes
                    )
            if sizes:
                out["batch_sizes"] = sizes
        return out

    def observatory_snapshot(self) -> Dict:
        """Per-replica half of ServeSignals (controller merges these
        across replicas each publish tick)."""
        from ray_tpu.serve import observatory

        snap = observatory.profiler().snapshot()
        snap["ongoing"] = self.ongoing
        snap["total_served"] = self.total_served
        # Engine-backed deployments contribute occupancy/backlog/HOL.
        if not self._is_function:
            engine = getattr(self.callable, "engine", None)
            if engine is not None and hasattr(engine, "stats"):
                try:
                    es = engine.stats()
                    snap["engine"] = {
                        "active": es.get("active"),
                        "waiting": es.get("waiting"),
                        "prefilling": es.get("prefilling"),
                        "occupancy": es.get("latency", {}).get("occupancy"),
                        "hol": es.get("hol"),
                    }
                except Exception:  # rtlint: disable=RT007 — snapshot is best-effort
                    pass
        return snap

    def reconfigure(self, user_config):
        if hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)
        return True

    def health_check(self) -> bool:
        if hasattr(self.callable, "check_health"):
            self.callable.check_health()
        return True
