"""Model multiplexing: many models per deployment, LRU-loaded per replica.

Analog of the reference's @serve.multiplexed + get_multiplexed_model_id
(python/ray/serve/multiplex.py): the client tags a request with a model id
(handle.options(multiplexed_model_id=...)), the router prefers replicas
that already have that model resident, and the replica's loader caches up
to max_num_models_per_replica models, evicting least-recently-used.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Optional

# Per-request context (set by the replica around user-code invocation).
_request_ctx = threading.local()


def get_multiplexed_model_id() -> str:
    """Inside a request: the model id this request was tagged with."""
    return getattr(_request_ctx, "model_id", "")


def _set_request_model_id(model_id: str):
    _request_ctx.model_id = model_id or ""


class _MultiplexWrapper:
    """Descriptor wrapping the user's model-loader method."""

    def __init__(self, fn: Callable, max_num_models_per_replica: int):
        self._fn = fn
        self.max_models = max_num_models_per_replica
        self.__name__ = getattr(fn, "__name__", "multiplexed")

    def _state_for(self, instance):
        key = f"__serve_multiplex_{self.__name__}"
        st = instance.__dict__.get(key)
        if st is None:
            st = {"models": OrderedDict(), "lock": threading.Lock()}
            instance.__dict__[key] = st
        return st

    def __get__(self, instance, owner=None):
        if instance is None:
            return self

        def load(model_id: str):
            st = self._state_for(instance)
            with st["lock"]:
                if model_id in st["models"]:
                    st["models"].move_to_end(model_id)
                    return st["models"][model_id]
            # Load outside the lock (loads can be slow: HBM transfers).
            model = self._fn(instance, model_id)
            with st["lock"]:
                st["models"][model_id] = model
                st["models"].move_to_end(model_id)
                while len(st["models"]) > self.max_models:
                    _mid, evicted = st["models"].popitem(last=False)
                    # Give the model a chance to release device memory.
                    del evicted
            return model

        load.__name__ = self.__name__
        return load


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    """Decorator for the per-replica model loader:

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id: str): ...

    Called with a model id, returns the (cached) model.
    """

    def deco(fn):
        return _MultiplexWrapper(fn, max_num_models_per_replica)

    if _fn is not None:
        return deco(_fn)
    return deco
