"""Request-scoped serving metadata: the survival-plane half of the wire ctx.

The observatory's ``RequestContext`` answers "where did the time go"; this
module answers "is this request still worth running".  A ``RequestMeta``
is built once at the handle (absolute ``deadline_ts``, tenant label,
idempotency key), shipped alongside every hop (handle→proxy→replica→
engine) as a plain dict, and re-hydrated into a thread-local on the
replica's request thread so code the user callable calls into — notably
``ContinuousBatchingEngine.submit`` — can read the deadline without the
user threading it through their own signatures.

Deadlines are *absolute* wall-clock timestamps, not budgets: every hop
compares ``time.time()`` against the same number, so elapsed time is
subtracted implicitly and no hop can accidentally reset the clock.
Single-node clocks are shared; on multi-host this inherits normal NTP
skew, which is fine at the ≥100 ms deadlines serving uses.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional


class RequestMeta:
    """Per-request survival metadata (immutable after construction)."""

    __slots__ = ("deadline_ts", "tenant", "idem_key", "rid")

    def __init__(self, deadline_ts: float = 0.0, tenant: str = "",
                 idem_key: str = "", rid: str = ""):
        self.deadline_ts = deadline_ts  # 0.0 == no deadline
        self.tenant = tenant
        self.idem_key = idem_key
        self.rid = rid

    # -- wire form ---------------------------------------------------
    def to_wire(self) -> Dict[str, Any]:
        return {"deadline_ts": self.deadline_ts, "tenant": self.tenant,
                "idem_key": self.idem_key, "rid": self.rid}

    @classmethod
    def from_wire(cls, wire: Optional[Dict[str, Any]]) -> "RequestMeta":
        if not wire:
            return cls()
        return cls(
            deadline_ts=float(wire.get("deadline_ts", 0.0) or 0.0),
            tenant=str(wire.get("tenant", "") or ""),
            idem_key=str(wire.get("idem_key", "") or ""),
            rid=str(wire.get("rid", "") or ""),
        )

    # -- deadline arithmetic -----------------------------------------
    def remaining(self, now: Optional[float] = None) -> float:
        """Seconds of budget left; ``inf`` when no deadline is set."""
        if not self.deadline_ts:
            return float("inf")
        return self.deadline_ts - (time.time() if now is None else now)

    def expired(self, now: Optional[float] = None) -> bool:
        return bool(self.deadline_ts) and self.remaining(now) <= 0.0


_local = threading.local()


def current() -> Optional[RequestMeta]:
    """The RequestMeta bound to this thread, or None outside a request."""
    return getattr(_local, "meta", None)


class bind:
    """Context manager binding a RequestMeta to the current thread.

    The replica wraps each request-thread body in ``with bind(meta):`` so
    engine code deep in the user callable sees the right deadline even
    though the callable's signature never mentions one.
    """

    def __init__(self, meta: Optional[RequestMeta]):
        self._meta = meta
        self._prev: Optional[RequestMeta] = None

    def __enter__(self):
        self._prev = getattr(_local, "meta", None)
        _local.meta = self._meta
        return self._meta

    def __exit__(self, *exc):
        _local.meta = self._prev
        return False


def remaining_budget(default: float = float("inf")) -> float:
    """Budget left for the current request (``default`` when unbound)."""
    meta = current()
    if meta is None:
        return default
    return meta.remaining()
