"""Declarative Serve deploys from config files.

Analog of the reference's `serve deploy` YAML path (serve/scripts.py:256 +
the Serve REST schema): a config file names applications by import path
with deployment overrides; `serve.run_from_config` builds and deploys
them. JSON is first-class (always stdlib); YAML is used when PyYAML is
present in the image.

Config shape (mirrors the reference's ServeDeploySchema subset):

    {
      "applications": [
        {
          "name": "summarizer",
          "import_path": "my_module:app",       # module:attribute
          "args": {"init": "kwargs"},           # optional bind overrides
          "deployments": [
            {"name": "Summarizer", "num_replicas": 2,
             "max_ongoing_requests": 16,
             "ray_actor_options": {"resources": {"TPU": 4}},
             "slo": {"ttft_ms": 200, "e2e_ms": 2000,
                     "objective": 0.99}}       # observatory SLO targets
          ]
        }
      ],
      "http": {"host": "127.0.0.1", "port": 8000}   # optional proxy
    }
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Any, Dict, List


def load_config(path: str) -> Dict[str, Any]:
    with open(path) as f:
        text = f.read()
    if path.endswith((".yaml", ".yml")):
        try:
            import yaml

            return yaml.safe_load(text)
        except ImportError as e:
            raise RuntimeError(
                "YAML config requires PyYAML; use a .json config instead"
            ) from e
    return json.loads(text)


def _import_attr(import_path: str):
    if ":" not in import_path:
        raise ValueError(
            f"import_path must be 'module:attribute', got {import_path!r}"
        )
    mod_name, attr = import_path.split(":", 1)
    mod = importlib.import_module(mod_name)
    return getattr(mod, attr)


def build_application(app_cfg: Dict[str, Any]):
    """Resolve an application entry to a bound Application."""
    from ray_tpu.serve.deployment import Application, Deployment

    target = _import_attr(app_cfg["import_path"])
    args = app_cfg.get("args") or {}
    if isinstance(target, Application):
        app = target
    elif isinstance(target, Deployment):
        app = target.bind(**args)
    elif callable(target):  # builder fn taking the args dict
        app = target(**args) if args else target()
        if isinstance(app, Deployment):
            app = app.bind()
    else:
        raise TypeError(
            f"{app_cfg['import_path']} resolved to {type(target).__name__}; "
            "expected an Application, Deployment, or builder function"
        )
    # Per-deployment overrides.
    for dep_over in app_cfg.get("deployments") or ():
        if dep_over.get("name") not in (None, app.deployment.name):
            continue
        overrides = {k: v for k, v in dep_over.items() if k != "name"}
        app = type(app)(
            app.deployment.options(**overrides), app.init_args,
            app.init_kwargs,
        )
    return app


def run_from_config(path_or_dict, _blocking: bool = False) -> Dict[str, Any]:
    """Deploy every application in the config; returns {name: handle}."""
    from ray_tpu import serve

    cfg = (
        load_config(path_or_dict)
        if isinstance(path_or_dict, (str, os.PathLike))
        else path_or_dict
    )
    handles = {}
    for app_cfg in cfg.get("applications", ()):
        app = build_application(app_cfg)
        name = app_cfg.get("name") or app.deployment.name
        handles[name] = serve.run(app, name=name)
    http = cfg.get("http")
    if http:
        serve.start_http_proxy(
            host=http.get("host", "127.0.0.1"), port=http.get("port", 8000)
        )
    return handles
