"""DeploymentHandle: route requests to replicas.

Analog of the reference's DeploymentHandle (serve/handle.py:830) + Router
(serve/_private/router.py:924, assign_request :1040) with the
PowerOfTwoChoicesReplicaScheduler (:295). Unlike round 1, replica choice
uses HANDLE-LOCAL in-flight counts (sample two replicas, pick the one this
handle has fewer outstanding requests on) — zero probe RPCs on the request
path, which is also how the reference's router tracks queue length client-
side between probes. Requests can be tagged with a multiplexed model id;
those route by stable hash so a model's requests land on the replica that
already has it loaded.
"""

from __future__ import annotations

import random
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

import ray_tpu as rt
from ray_tpu._private.config import get_config


class DeploymentResponse:
    """Awaitable-ish response wrapper: `.result()` blocks; `.ref` is the
    underlying ObjectRef (reference: serve/handle.py DeploymentResponse).

    A replica that died mid-request (crash, scale-down, self-healing
    restart) re-dispatches to another replica up to `max_retries` times —
    the reference router's retry-on-replica-failure behavior."""

    def __init__(self, ref, on_done=None, redispatch=None, max_retries=2):
        self.ref = ref
        self._redispatch = redispatch
        self._retries_left = max_retries
        if on_done is not None and ref._future is not None:
            ref._future.add_done_callback(lambda _f: on_done())

    def result(self, timeout: Optional[float] = 60.0):
        # ActorError covers died AND unavailable (connection lost while
        # the controller replaces the replica) — both mean "this replica
        # will not answer; send the request somewhere else".
        from ray_tpu.exceptions import ActorError, WorkerCrashedError

        attempt = 0
        while True:
            try:
                return rt.get(self.ref, timeout=timeout)
            except (ActorError, WorkerCrashedError):
                if self._redispatch is None or self._retries_left <= 0:
                    raise
                self._retries_left -= 1
                # Capped exponential backoff with jitter before the next
                # dispatch: when a replica dies under load, every queued
                # caller retries at once — unjittered they'd stampede the
                # survivors (and the controller's route table) in
                # lockstep while self-healing is still replacing it.
                cfg = get_config()
                delay = min(
                    cfg.serve_redispatch_backoff_s * (2 ** attempt),
                    cfg.serve_redispatch_backoff_max_s,
                )
                if delay > 0:
                    time.sleep(delay * (0.5 + 0.5 * random.random()))
                attempt += 1
                self.ref = self._redispatch()


class DeploymentHandle:
    def __init__(self, app_name: str, method: str = "__call__",
                 multiplexed_model_id: str = "", stream: bool = False,
                 max_retries: int = 2, tenant: str = "", _shared=None):
        self.app_name = app_name
        self.method = method
        self.multiplexed_model_id = multiplexed_model_id
        self._stream = stream
        # Observatory attribution label: requests from this handle are
        # accounted (tokens, queue time, SLO burn) under this tenant.
        self.tenant = tenant
        # Retry-on-replica-failure count (reference: router retry config).
        # Retries re-dispatch the same args — at-least-once semantics, so
        # mutating deployments should set max_retries=0 via .options().
        self.max_retries = max_retries
        # Router state shared across .options() copies of this handle.
        if _shared is None:
            _shared = {
                "replicas": [],
                "version": -1,
                "last_refresh": 0.0,
                "inflight": {},  # actor_id -> handle-local outstanding
                "lock": threading.Lock(),
                "subscribed": False,
            }
        self._shared = _shared

    def options(self, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None,
                max_retries: Optional[int] = None,
                tenant: Optional[str] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.app_name,
            method_name if method_name is not None else self.method,
            (multiplexed_model_id if multiplexed_model_id is not None
             else self.multiplexed_model_id),
            stream if stream is not None else self._stream,
            max_retries if max_retries is not None else self.max_retries,
            tenant if tenant is not None else self.tenant,
            _shared=self._shared,
        )

    def _controller(self):
        from ray_tpu.serve.controller import CONTROLLER_NAME

        return rt.get_actor(CONTROLLER_NAME)

    def _subscribe_invalidation(self):
        """Push invalidation from the controller (LongPollHost analog):
        a routes push zeroes last_refresh so the NEXT request refetches,
        instead of waiting out the poll TTL. Polling stays as fallback."""
        s = self._shared
        with s["lock"]:
            if s["subscribed"]:
                return
            s["subscribed"] = True
        try:
            from ray_tpu._private import worker as worker_mod

            def on_push(_payload, _s=s):
                with _s["lock"]:
                    _s["last_refresh"] = 0.0

            worker_mod.get_client().subscribe_push(
                f"serve_routes:{self.app_name}", on_push
            )
        except Exception:  # noqa: BLE001 — polling still works
            pass

    def _refresh(self, force: bool = False):
        self._subscribe_invalidation()
        s = self._shared
        now = time.monotonic()
        with s["lock"]:
            lr0 = s["last_refresh"]
            if not force and s["replicas"] and now - lr0 < 1.0:
                return
        # Request-dispatch path: rides the data-plane rpc timeout, NOT the
        # deploy-readiness knob (tuning deploys must not break dispatch).
        info = rt.get(self._controller().get_replicas.remote(self.app_name),
                      timeout=get_config().serve_rpc_timeout_s)
        with s["lock"]:
            if info["version"] >= s["version"]:
                s["version"] = info["version"]
                s["replicas"] = info["replicas"]
            if s["last_refresh"] == lr0:
                s["last_refresh"] = time.monotonic()
            # else: a push invalidation zeroed last_refresh while our RPC
            # was in flight — leave it zeroed so the next request refetches
            # the post-change table instead of trusting this possibly-stale
            # response for a full TTL.
            live = {r._actor_id.binary() for r in s["replicas"]}
            s["inflight"] = {
                k: v for k, v in s["inflight"].items() if k in live
            }

    def _pick_replica(self, exclude=frozenset()):
        """Power-of-two by handle-local in-flight count (router.py:295) —
        no probe RPCs on the request path. Multiplexed requests hash the
        model id to a stable replica so its weights stay resident.
        `exclude`: actor ids observed dead by a retrying response — skip
        them while the controller's table still lists them."""
        self._refresh()
        s = self._shared
        with s["lock"]:
            replicas = list(s["replicas"])
        live = [r for r in replicas if r._actor_id.binary() not in exclude]
        if not live:
            self._refresh(force=True)
            with s["lock"]:
                replicas = list(s["replicas"])
            live = [r for r in replicas
                    if r._actor_id.binary() not in exclude] or replicas
            if not live:
                raise RuntimeError(
                    f"no running replicas for app {self.app_name!r}"
                )
        replicas = live
        if self.multiplexed_model_id:
            idx = zlib.crc32(self.multiplexed_model_id.encode()) % len(replicas)
            return replicas[idx]
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)
        with s["lock"]:
            ia = s["inflight"].get(a._actor_id.binary(), 0)
            ib = s["inflight"].get(b._actor_id.binary(), 0)
        return a if ia <= ib else b

    def _track(self, replica):
        s = self._shared
        key = replica._actor_id.binary()
        with s["lock"]:
            s["inflight"][key] = s["inflight"].get(key, 0) + 1

        def done():
            with s["lock"]:
                n = s["inflight"].get(key, 0) - 1
                if n <= 0:
                    s["inflight"].pop(key, None)
                else:
                    s["inflight"][key] = n

        return done

    def remote(self, *args, **kwargs):
        """Dispatch a request; returns a DeploymentResponse (streaming
        handles return an iterator over chunks instead)."""
        from ray_tpu.util import tracing

        if self._stream:
            return self._stream_call(args, kwargs)
        # Serve-path trace propagation: the caller's active span (or a
        # fresh root when tracing is enabled) rides the request so the
        # replica's execution joins the request's span tree.
        from ray_tpu.serve import observatory

        obs_ctx = observatory.make_wire_ctx(self.tenant)
        trace_ctx = tracing.inject()
        replica = self._pick_replica()
        done = self._track(replica)
        if obs_ctx is not None:
            # handle_queue ends here: routing done, dispatching now.
            obs_ctx["disp_t"] = time.time()
        ref = replica.handle_request.remote(
            self.method, args, kwargs, self.multiplexed_model_id, trace_ctx,
            obs_ctx,
        )

        failed = {replica._actor_id.binary()}

        def redispatch():
            # The chosen replica died: drop the cached route table, pick
            # a replica we haven't seen fail (the controller's table may
            # still list the dead one while self-healing replaces it).
            self._refresh(force=True)
            r = self._pick_replica(exclude=frozenset(failed))
            failed.add(r._actor_id.binary())
            d = self._track(r)
            if obs_ctx is not None:
                # Re-dispatch restarts the wire leg; the backoff before
                # it stays attributed to handle_queue-side waiting.
                obs_ctx["disp_t"] = time.time()
            new_ref = r.handle_request.remote(
                self.method, args, kwargs, self.multiplexed_model_id,
                trace_ctx, obs_ctx,
            )
            if new_ref._future is not None:
                new_ref._future.add_done_callback(lambda _f: d())
            return new_ref

        return DeploymentResponse(ref, on_done=done, redispatch=redispatch,
                                  max_retries=self.max_retries)

    def _stream_call(self, args, kwargs):
        """Generator deployment: yields chunks as the replica produces
        them (reference: handle_request_streaming, replica.py:478)."""
        from ray_tpu.serve import observatory
        from ray_tpu.util import tracing

        obs_ctx = observatory.make_wire_ctx(self.tenant)
        trace_ctx = tracing.inject()
        replica = self._pick_replica()
        if obs_ctx is not None:
            obs_ctx["disp_t"] = time.time()
        sid = rt.get(
            replica.start_stream.remote(
                self.method, args, kwargs, self.multiplexed_model_id,
                trace_ctx, obs_ctx,
            ),
            timeout=get_config().serve_rpc_timeout_s,
        )

        def gen():
            start = 0
            while True:
                out = rt.get(
                    replica.next_chunks.remote(sid, start),
                    timeout=get_config().serve_rpc_timeout_s,
                )
                for c in out["chunks"]:
                    yield c
                start += len(out["chunks"])
                if out["error"]:
                    raise RuntimeError(
                        f"stream failed in replica: {out['error']}"
                    )
                if out["done"]:
                    return

        return gen()

    def __reduce__(self):
        # Router state (locks, in-flight counts) is process-local: a handle
        # shipped to another process (deployment composition) starts fresh.
        return (
            DeploymentHandle,
            (self.app_name, self.method, self.multiplexed_model_id,
             self._stream, self.max_retries, self.tenant),
        )

    def __call__(self, *args, **kwargs):
        raise TypeError("use handle.remote(...) for deployment calls")
