"""DeploymentHandle: route requests to replicas.

Analog of the reference's DeploymentHandle (serve/handle.py:830) + Router
(serve/_private/router.py:924, assign_request :1040) with the
PowerOfTwoChoicesReplicaScheduler (:295): pick two random replicas, probe
their queue lengths, send to the shorter queue.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, List, Optional

import ray_tpu as rt


class DeploymentHandle:
    def __init__(self, app_name: str, method: str = "__call__"):
        self.app_name = app_name
        self.method = method
        self._replicas: List = []
        self._version = -1
        self._last_refresh = 0.0
        self._lock = threading.Lock()

    def options(self, method_name: str = "__call__") -> "DeploymentHandle":
        h = DeploymentHandle(self.app_name, method_name)
        return h

    def _controller(self):
        from ray_tpu.serve.controller import CONTROLLER_NAME

        return rt.get_actor(CONTROLLER_NAME)

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        with self._lock:
            if not force and self._replicas and now - self._last_refresh < 1.0:
                return
        info = rt.get(self._controller().get_replicas.remote(self.app_name),
                      timeout=30)
        with self._lock:
            self._version = info["version"]
            self._replicas = info["replicas"]
            self._last_refresh = now

    def _pick_replica(self):
        """Power-of-two-choices (reference: router.py:295)."""
        self._refresh()
        with self._lock:
            replicas = list(self._replicas)
        if not replicas:
            self._refresh(force=True)
            with self._lock:
                replicas = list(self._replicas)
            if not replicas:
                raise RuntimeError(
                    f"no running replicas for app {self.app_name!r}"
                )
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)
        try:
            qa, qb = rt.get([a.queue_len.remote(), b.queue_len.remote()],
                            timeout=5)
        except Exception:
            return a
        return a if qa <= qb else b

    def remote(self, *args, **kwargs):
        """Async call: returns an ObjectRef resolving to the response."""
        replica = self._pick_replica()
        return replica.handle_request.remote(self.method, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError("use handle.remote(...) for deployment calls")
