"""DeploymentHandle: route requests to replicas.

Analog of the reference's DeploymentHandle (serve/handle.py:830) + Router
(serve/_private/router.py:924, assign_request :1040) with the
PowerOfTwoChoicesReplicaScheduler (:295). Unlike round 1, replica choice
uses HANDLE-LOCAL in-flight counts (sample two replicas, pick the one this
handle has fewer outstanding requests on) — zero probe RPCs on the request
path, which is also how the reference's router tracks queue length client-
side between probes. Requests can be tagged with a multiplexed model id;
those route by stable hash so a model's requests land on the replica that
already has it loaded.

Survival plane (PR 8) layered on the router:

  * Deadlines: ``handle.options(deadline_s=...)`` stamps an ABSOLUTE
    deadline into the request's wire meta; every hop (handle dispatch,
    replica admission, engine queue, decode loop) compares wall clock
    against the same number, so elapsed time is subtracted implicitly
    and expired requests are cancelled instead of executed.
  * Admission shed: when every live replica is already loaded past
    ``max_ongoing + serve_max_queued_per_replica`` by THIS handle's own
    in-flight counts, dispatch fails fast with ServeOverloadedError —
    no RPC, sub-millisecond shed decisions under overload.
  * Idempotency keys: each logical request carries a stable idem_key
    across redispatches, so retry-after-replica-death can safely send
    the same request twice (the replica's idempotency cache joins or
    replays the first execution).
  * Per-replica circuit breaker: consecutive dispatch failures (deaths
    weigh a full threshold, sheds weigh one) open the breaker for
    ``serve_cb_reset_s``; _pick_replica skips open replicas while a
    recent-outcome window ("burn rate" of this handle's own traffic)
    keeps half-open trials honest. All replicas open => serve anyway
    (the breaker protects against SOME sick replicas, not against
    having none).
  * Controller failover: _refresh serves CACHED routes when the
    controller is unreachable (it restarts with max_restarts=-1 and
    republishes); death of a picked replica forces an immediate
    route refetch instead of waiting out the poll TTL.
  * Streaming resume-or-restart: a stream cut by replica death is
    re-started on another replica up to serve_stream_resume_attempts
    times and resumes AT THE CHUNK OFFSET already delivered. Contract:
    the client never sees a duplicated or missing chunk INDEX, but
    chunk CONTENTS are only guaranteed identical for deterministic
    requests (greedy decode); sampled requests may resume with a
    different continuation.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

import ray_tpu as rt
from ray_tpu._private import chaos
from ray_tpu._private.config import get_config
from ray_tpu.util import journal
from ray_tpu.exceptions import (
    ActorError,
    GetTimeoutError,
    ReplicaDrainingError,
    RequestCancelledError,
    ServeOverloadedError,
    TaskError,
    WorkerCrashedError,
)


logger = logging.getLogger("ray_tpu.serve.handle")


def _is_death(err: BaseException) -> bool:
    """True when an error means the replica process is gone. Death has
    two wire shapes: raised LOCALLY by rt.get (ActorError /
    WorkerCrashedError), or wrapped in TaskError when the death surfaced
    remotely (e.g. the raylet answered 'actor not hosted by this worker'
    for a just-killed replica)."""
    if isinstance(err, (ActorError, WorkerCrashedError)):
        return True
    if isinstance(err, TaskError):
        if isinstance(getattr(err, "cause", None),
                      (ActorError, WorkerCrashedError)):
            return True
        return getattr(err, "cause_cls_name", "") in (
            "ActorDiedError", "ActorUnavailableError", "WorkerCrashedError")
    return False


def _is_draining(err: BaseException) -> bool:
    if not isinstance(err, TaskError):
        return False
    return (isinstance(getattr(err, "cause", None), ReplicaDrainingError)
            or getattr(err, "cause_cls_name", "") == "ReplicaDrainingError")


def _retry_class(err: BaseException):
    """Classify a dispatch failure: (retryable_elsewhere, replica_dead,
    backoff_before_retry). Draining/overloaded replicas are healthy
    processes refusing work — retry another replica immediately (shed)
    or after backoff (overload); deaths force a route refetch + backoff.
    Everything else (user exceptions, deadline cancellations) is NOT
    retryable: the request executed (or its budget is gone)."""
    if _is_death(err):
        return True, True, True
    if isinstance(err, TaskError):
        if _is_draining(err):
            return True, False, False
        cause = getattr(err, "cause", None)
        if isinstance(cause, ServeOverloadedError):
            return True, False, True
        # Unpickleable causes still carry the class name.
        if getattr(err, "cause_cls_name", "") == "ServeOverloadedError":
            return True, False, True
    return False, False, False


class DeploymentResponse:
    """Awaitable-ish response wrapper: `.result()` blocks; `.ref` is the
    underlying ObjectRef (reference: serve/handle.py DeploymentResponse).

    A replica that died mid-request (crash, scale-down, self-healing
    restart) re-dispatches to another replica up to `max_retries` times —
    the reference router's retry-on-replica-failure behavior. Draining
    and overloaded replicas redispatch the same way (they are typed,
    retryable refusals), and `.result()`'s default timeout honors the
    request deadline when one was set instead of the fixed 60 s."""

    def __init__(self, ref, on_done=None, redispatch=None, max_retries=2,
                 deadline_ts: float = 0.0, replica_key: bytes = b"",
                 cb_ok=None, cb_fail=None, rid: str = ""):
        self.ref = ref
        # Observatory request id: joins this response's client-side
        # timing against the server's phase attribution (loadgen
        # reconciler). "" when the observatory is disabled.
        self.rid = rid
        self._redispatch = redispatch
        self._retries_left = max_retries
        self._deadline_ts = deadline_ts
        self._replica_key = replica_key
        self._cb_ok = cb_ok
        self._cb_fail = cb_fail
        if on_done is not None and ref._future is not None:
            ref._future.add_done_callback(lambda _f: on_done())

    def _default_timeout(self) -> float:
        if self._deadline_ts:
            return max(0.01, self._deadline_ts - time.time())
        return 60.0

    def result(self, timeout: Optional[float] = None):
        attempt = 0
        while True:
            t = self._default_timeout() if timeout is None else timeout
            try:
                out = rt.get(self.ref, timeout=t)
                if self._cb_ok is not None:
                    self._cb_ok(self._replica_key)
                return out
            except GetTimeoutError:
                if (timeout is None and self._deadline_ts
                        and time.time() >= self._deadline_ts):
                    raise RequestCancelledError(
                        "request deadline expired while waiting for the "
                        "reply", reason="deadline",
                    ) from None
                raise
            except (ActorError, WorkerCrashedError, TaskError) as e:
                retryable, dead, backoff = _retry_class(e)
                if dead and self._cb_fail is not None:
                    self._cb_fail(self._replica_key, death=True)
                if (not retryable or self._redispatch is None
                        or self._retries_left <= 0):
                    journal.emit(
                        "serve.request_error", error=type(e).__name__,
                        replica=(self._replica_key.hex()
                                 if isinstance(self._replica_key, bytes)
                                 else str(self._replica_key or "")),
                    )
                    raise
            self._retries_left -= 1
            if backoff:
                # Capped exponential backoff with jitter before the next
                # dispatch: when a replica dies under load, every queued
                # caller retries at once — unjittered they'd stampede the
                # survivors (and the controller's route table) in
                # lockstep while self-healing is still replacing it.
                cfg = get_config()
                delay = min(
                    cfg.serve_redispatch_backoff_s * (2 ** attempt),
                    cfg.serve_redispatch_backoff_max_s,
                )
                if delay > 0:
                    time.sleep(delay * (0.5 + 0.5 * random.random()))
            attempt += 1
            self.ref, self._replica_key = self._redispatch()
            journal.emit(
                "serve.redispatch", attempt=attempt,
                replica=(self._replica_key.hex()
                         if isinstance(self._replica_key, bytes)
                         else str(self._replica_key or "")),
            )


class StreamingResponse:
    """Iterator over a streaming call's chunks, carrying the request's
    observatory ``rid`` so client-side witnesses (ray_tpu.loadgen) can
    join their stamp cards against the server's phase attribution.
    Behaves exactly like the bare generator it wraps — existing
    ``for chunk in handle.remote(...)`` consumers are unaffected."""

    __slots__ = ("rid", "_gen")

    def __init__(self, gen, rid: str = ""):
        self._gen = gen
        self.rid = rid

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)

    def close(self):
        self._gen.close()


class DeploymentHandle:
    def __init__(self, app_name: str, method: str = "__call__",
                 multiplexed_model_id: str = "", stream: bool = False,
                 max_retries: int = 2, tenant: str = "",
                 deadline_s: float = 0.0, _shared=None):
        self.app_name = app_name
        self.method = method
        self.multiplexed_model_id = multiplexed_model_id
        self._stream = stream
        # Observatory attribution label: requests from this handle are
        # accounted (tokens, queue time, SLO burn) under this tenant.
        self.tenant = tenant
        # Retry-on-replica-failure count (reference: router retry config).
        # Retries re-dispatch the same args — the idempotency key makes
        # that safe for deployments that opt into the replica-side cache;
        # otherwise semantics stay at-least-once and mutating deployments
        # should set max_retries=0 via .options().
        self.max_retries = max_retries
        # Per-request budget in seconds (0 = serve_default_deadline_s,
        # which itself defaults to "no deadline").
        self.deadline_s = deadline_s
        # Router state shared across .options() copies of this handle.
        if _shared is None:
            _shared = {
                "replicas": [],
                "version": -1,
                "last_refresh": 0.0,
                "inflight": {},  # actor_id -> handle-local outstanding
                "lock": threading.Lock(),
                "subscribed": False,
                "max_ongoing": 0,  # published by the controller's table
                # Prefix-affinity hints (paged KV): actor_id hex -> set
                # of resident first-page prefix hashes, plus the page
                # size the hashes were computed with.
                "prefix": {},
                "page_size": 0,
                # actor_id -> {"fails", "open_until", "window"} — the
                # handle-side circuit breaker ledger.
                "cb": {},
            }
        self._shared = _shared

    def options(self, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None,
                max_retries: Optional[int] = None,
                tenant: Optional[str] = None,
                deadline_s: Optional[float] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.app_name,
            method_name if method_name is not None else self.method,
            (multiplexed_model_id if multiplexed_model_id is not None
             else self.multiplexed_model_id),
            stream if stream is not None else self._stream,
            max_retries if max_retries is not None else self.max_retries,
            tenant if tenant is not None else self.tenant,
            deadline_s if deadline_s is not None else self.deadline_s,
            _shared=self._shared,
        )

    def _controller(self):
        from ray_tpu.serve.controller import CONTROLLER_NAME

        return rt.get_actor(CONTROLLER_NAME)

    def _subscribe_invalidation(self):
        """Push invalidation from the controller (LongPollHost analog):
        a routes push zeroes last_refresh so the NEXT request refetches,
        instead of waiting out the poll TTL. Polling stays as fallback."""
        s = self._shared
        with s["lock"]:
            if s["subscribed"]:
                return
            s["subscribed"] = True
        try:
            from ray_tpu._private import worker as worker_mod

            def on_push(_payload, _s=s):
                with _s["lock"]:
                    _s["last_refresh"] = 0.0

            worker_mod.get_client().subscribe_push(
                f"serve_routes:{self.app_name}", on_push
            )
        except Exception:  # noqa: BLE001 — polling still works
            logger.debug("route-invalidation push subscribe failed for "
                         "app %r; falling back to TTL polling",
                         self.app_name, exc_info=True)

    def _refresh(self, force: bool = False):
        self._subscribe_invalidation()
        s = self._shared
        now = time.monotonic()
        with s["lock"]:
            lr0 = s["last_refresh"]
            if not force and s["replicas"] and now - lr0 < 1.0:
                return
        # Request-dispatch path: rides the data-plane rpc timeout, NOT the
        # deploy-readiness knob (tuning deploys must not break dispatch).
        try:
            info = rt.get(
                self._controller().get_replicas.remote(self.app_name),
                timeout=get_config().serve_rpc_timeout_s,
            )
        except (ActorError, WorkerCrashedError, GetTimeoutError,
                ValueError) as e:
            # Controller dead/restarting (it comes back with
            # max_restarts=-1 and restores from checkpoint): keep
            # serving from the CACHED route table — the data plane must
            # not depend on the control plane being up. Bump
            # last_refresh so we don't hammer a dead controller every
            # request; the next TTL expiry (or a routes push from the
            # restarted controller) retries.
            with s["lock"]:
                if s["replicas"]:
                    s["last_refresh"] = time.monotonic()
                    return
            raise RuntimeError(
                f"serve controller unreachable and no cached routes for "
                f"app {self.app_name!r}"
            ) from e
        with s["lock"]:
            if info["version"] >= s["version"]:
                s["version"] = info["version"]
                s["replicas"] = info["replicas"]
                s["max_ongoing"] = info.get("max_ongoing", 0)
                s["prefix"] = {
                    aid: set(keys)
                    for aid, keys in (info.get("prefix") or {}).items()
                }
                s["page_size"] = info.get("page_size") or 0
            if s["last_refresh"] == lr0:
                s["last_refresh"] = time.monotonic()
            # else: a push invalidation zeroed last_refresh while our RPC
            # was in flight — leave it zeroed so the next request refetches
            # the post-change table instead of trusting this possibly-stale
            # response for a full TTL.
            live = {r._actor_id.binary() for r in s["replicas"]}
            s["inflight"] = {
                k: v for k, v in s["inflight"].items() if k in live
            }
            s["cb"] = {k: v for k, v in s["cb"].items() if k in live}

    # -- circuit breaker ------------------------------------------------
    def _cb_fail(self, key: bytes, death: bool = False):
        """Record a dispatch failure against a replica. Deaths weigh a
        full threshold (an ActorDiedError needs no corroboration);
        sheds/unavailability accumulate. The breaker also opens on the
        recent-outcome window: >= 50% failures over the last
        2*threshold outcomes of THIS handle's traffic (the handle-local
        "burn rate"), which catches a flapping replica whose failures
        never run consecutively. While open, _pick_replica skips the
        replica until serve_cb_reset_s passes (half-open: the next
        pick is the trial; one more failure re-opens instantly because
        the consecutive count stays saturated)."""
        cfg = get_config()
        s = self._shared
        threshold = max(1, cfg.serve_cb_failure_threshold)
        now = time.monotonic()
        with s["lock"]:
            ent = s["cb"].setdefault(
                key, {"fails": 0, "open_until": 0.0, "window": []}
            )
            ent["fails"] += threshold if death else 1
            ent["window"] = (ent["window"] + [False])[-2 * threshold:]
            window = ent["window"]
            burned = (len(window) >= 2 * threshold
                      and window.count(False) * 2 >= len(window))
            opened = ent["fails"] >= threshold or burned
            if opened:
                ent["open_until"] = now + cfg.serve_cb_reset_s
            if death:
                # Stale-route fix: a death observed by a response means
                # the cached table lists a corpse — refetch on the next
                # dispatch instead of waiting out the TTL.
                s["last_refresh"] = 0.0
        from ray_tpu.serve import observatory

        observatory.set_circuit_state(
            self.app_name, key.hex()[:12], 2 if opened else 0
        )

    def _cb_ok(self, key: bytes):
        s = self._shared
        had = False
        with s["lock"]:
            ent = s["cb"].get(key)
            if ent is not None:
                had = ent["fails"] > 0 or ent["open_until"] > 0.0
                ent["fails"] = 0
                ent["open_until"] = 0.0
                ent["window"] = (ent["window"] + [True])[-16:]
        if had:
            from ray_tpu.serve import observatory

            observatory.set_circuit_state(
                self.app_name, key.hex()[:12], 0
            )

    def _open_circuits(self) -> set:
        s = self._shared
        now = time.monotonic()
        with s["lock"]:
            return {k for k, e in s["cb"].items()
                    if e["open_until"] > now}

    # -- routing ---------------------------------------------------------
    def _route_key(self, args) -> Optional[str]:
        """Prefix-affinity routing key for a prompt-shaped first arg:
        the hash of its first KV page (paged_kv.prefix_route_key). None
        whenever affinity doesn't apply — no advertised prefixes, a
        multiplexed handle (model residency outranks cache residency),
        or a first arg that isn't a token sequence spanning a page."""
        s = self._shared
        with s["lock"]:
            page_size = s["page_size"]
            has_prefixes = bool(s["prefix"])
        if (not has_prefixes or not page_size or self.multiplexed_model_id
                or not args):
            return None
        prompt = args[0]
        if not isinstance(prompt, (list, tuple)) and not (
                hasattr(prompt, "ndim") and getattr(prompt, "ndim", 0) == 1):
            return None
        try:
            if len(prompt) < page_size:
                return None
            from ray_tpu.serve import paged_kv

            return paged_kv.prefix_route_key(prompt, page_size)
        except (TypeError, ValueError):  # non-token contents
            return None

    def _pick_replica(self, exclude=frozenset(),
                      route_key: Optional[str] = None):
        """Power-of-two by handle-local in-flight count (router.py:295) —
        no probe RPCs on the request path. Multiplexed requests hash the
        model id to a stable replica so its weights stay resident.
        `route_key`: a prompt's first-page prefix hash — when some
        candidate replica advertises it (its prefix cache holds the
        prompt's opening page), the pick prefers covering replicas (the
        least-loaded of them), so repeat prompts land where their KV
        pages already live and prefill skips them. Falls through to the
        normal pick when nobody covers it.
        `exclude`: actor ids observed dead by a retrying response — skip
        them while the controller's table still lists them. Replicas
        with an OPEN circuit breaker are skipped the same way unless
        every candidate is open (breakers protect against some sick
        replicas, not against having none)."""
        self._refresh()
        s = self._shared
        with s["lock"]:
            replicas = list(s["replicas"])
        open_keys = self._open_circuits()
        live = [r for r in replicas if r._actor_id.binary() not in exclude]
        if not live:
            self._refresh(force=True)
            with s["lock"]:
                replicas = list(s["replicas"])
            live = [r for r in replicas
                    if r._actor_id.binary() not in exclude] or replicas
            if not live:
                raise RuntimeError(
                    f"no running replicas for app {self.app_name!r}"
                )
        closed = [r for r in live
                  if r._actor_id.binary() not in open_keys]
        replicas = closed or live
        if self.multiplexed_model_id:
            idx = zlib.crc32(self.multiplexed_model_id.encode()) % len(replicas)
            return replicas[idx]
        if route_key is not None:
            with s["lock"]:
                pm = dict(s["prefix"])
            covering = [r for r in replicas
                        if route_key in pm.get(r._actor_id.hex(), ())]
            if covering:
                with s["lock"]:
                    return min(
                        covering,
                        key=lambda r: s["inflight"].get(
                            r._actor_id.binary(), 0),
                    )
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)
        with s["lock"]:
            ia = s["inflight"].get(a._actor_id.binary(), 0)
            ib = s["inflight"].get(b._actor_id.binary(), 0)
        return a if ia <= ib else b

    def _track(self, replica):
        s = self._shared
        key = replica._actor_id.binary()
        with s["lock"]:
            s["inflight"][key] = s["inflight"].get(key, 0) + 1

        def done():
            with s["lock"]:
                n = s["inflight"].get(key, 0) - 1
                if n <= 0:
                    s["inflight"].pop(key, None)
                else:
                    s["inflight"][key] = n

        return done

    # -- survival-plane request metadata ---------------------------------
    def _make_meta(self, rid: str = "") -> Dict[str, Any]:
        """The wire meta every hop reads: an ABSOLUTE deadline (0 = no
        deadline), the tenant label, and an idempotency key that stays
        STABLE across redispatches of this logical request."""
        deadline_s = self.deadline_s or get_config().serve_default_deadline_s
        return {
            "deadline_ts": time.time() + deadline_s if deadline_s > 0
            else 0.0,
            "tenant": self.tenant,
            "idem_key": os.urandom(8).hex(),
            "rid": rid,
        }

    def _shed_check(self, meta: Dict[str, Any]):
        """Handle-side fast shed: if EVERY live replica is already
        loaded past its bound by this handle's own in-flight counts,
        reject in microseconds instead of queueing an RPC that the
        replica would shed anyway. Zero RPCs — this is what keeps shed
        decisions sub-millisecond under a burst."""
        from ray_tpu.serve import observatory

        if meta["deadline_ts"] and time.time() > meta["deadline_ts"]:
            observatory.record_deadline_expired(self.app_name, "handle")
            raise RequestCancelledError(
                "deadline expired before dispatch",
                reason="deadline", app=self.app_name, rid=meta["rid"],
            )
        s = self._shared
        with s["lock"]:
            bound = s.get("max_ongoing", 0)
            if not bound or not s["replicas"]:
                return
            limit = bound + get_config().serve_max_queued_per_replica
            least = min(
                s["inflight"].get(r._actor_id.binary(), 0)
                for r in s["replicas"]
            )
        if least >= limit:
            observatory.record_shed(self.app_name, self.tenant, "queue_full")
            raise ServeOverloadedError(
                f"all replicas of {self.app_name!r} are at their admission "
                f"bound ({least} handle-local in-flight >= {limit})",
                app=self.app_name, tenant=self.tenant, reason="queue_full",
                retry_after_s=min(5.0, max(0.1, 0.02 * least)),
            )

    def remote(self, *args, **kwargs):
        """Dispatch a request; returns a DeploymentResponse (streaming
        handles return an iterator over chunks instead)."""
        from ray_tpu.util import tracing

        if self._stream:
            return self._stream_call(args, kwargs)  # rtlint: disable=RT009 — the streaming path builds its own meta inside _stream_call
        # Serve-path trace propagation: the caller's active span (or a
        # fresh root when tracing is enabled) rides the request so the
        # replica's execution joins the request's span tree.
        from ray_tpu.serve import observatory

        obs_ctx = observatory.make_wire_ctx(self.tenant)
        meta = self._make_meta(rid=obs_ctx["rid"] if obs_ctx else "")
        trace_ctx = tracing.inject()
        # Chaos: deterministic dispatch stall (deadline tests burn the
        # budget at this hop on purpose).
        injected = chaos.take_dispatch_delay()
        if injected:
            time.sleep(injected)
        self._refresh()
        self._shed_check(meta)
        route_key = self._route_key(args)
        replica = self._pick_replica(route_key=route_key)
        done = self._track(replica)
        if obs_ctx is not None:
            # handle_queue ends here: routing done, dispatching now.
            obs_ctx["disp_t"] = time.time()
        ref = replica.handle_request.remote(
            self.method, args, kwargs, self.multiplexed_model_id, trace_ctx,
            obs_ctx, meta,
        )

        failed = {replica._actor_id.binary()}

        def redispatch():
            # The chosen replica refused or died: drop the cached route
            # table, pick a replica we haven't seen fail (the
            # controller's table may still list the dead one while
            # self-healing replaces it). The SAME meta rides along —
            # notably the idem_key, so a request the dead replica
            # half-finished cannot execute twice where it matters.
            self._refresh(force=True)
            r = self._pick_replica(exclude=frozenset(failed),
                                   route_key=route_key)
            failed.add(r._actor_id.binary())
            d = self._track(r)
            if obs_ctx is not None:
                # Re-dispatch restarts the wire leg; the backoff before
                # it stays attributed to handle_queue-side waiting.
                obs_ctx["disp_t"] = time.time()
            new_ref = r.handle_request.remote(
                self.method, args, kwargs, self.multiplexed_model_id,
                trace_ctx, obs_ctx, meta,
            )
            if new_ref._future is not None:
                new_ref._future.add_done_callback(lambda _f: d())
            return new_ref, r._actor_id.binary()

        return DeploymentResponse(
            ref, on_done=done, redispatch=redispatch,
            max_retries=self.max_retries,
            deadline_ts=meta["deadline_ts"],
            replica_key=replica._actor_id.binary(),
            cb_ok=self._cb_ok, cb_fail=self._cb_fail,
            rid=meta["rid"],
        )

    def _stream_call(self, args, kwargs):
        """Generator deployment: yields chunks as the replica produces
        them (reference: handle_request_streaming, replica.py:478).

        Resume-or-restart: when the serving replica dies mid-stream the
        generator re-starts the request on another replica (same meta,
        same idem_key) and fast-forwards to the chunk offset already
        delivered, up to serve_stream_resume_attempts times. The client
        sees a contiguous chunk sequence; contents of the re-generated
        prefix are only guaranteed to match for DETERMINISTIC requests
        (greedy decode) — sampled requests may continue differently."""
        from ray_tpu.serve import observatory
        from ray_tpu.util import tracing

        obs_ctx = observatory.make_wire_ctx(self.tenant)
        meta = self._make_meta(rid=obs_ctx["rid"] if obs_ctx else "")
        trace_ctx = tracing.inject()
        injected = chaos.take_dispatch_delay()
        if injected:
            time.sleep(injected)
        self._refresh()
        self._shed_check(meta)
        route_key = self._route_key(args)

        def start_on(replica):
            if obs_ctx is not None:
                obs_ctx["disp_t"] = time.time()
            return rt.get(
                replica.start_stream.remote(
                    self.method, args, kwargs, self.multiplexed_model_id,
                    trace_ctx, obs_ctx, meta,
                ),
                timeout=get_config().serve_rpc_timeout_s,
            )

        # Dead replicas this logical request has observed; picks exclude
        # them. The resume-attempt budget is shared between dispatch-time
        # deaths (the picked replica died before start_stream landed) and
        # mid-stream deaths.
        failed: set = set()
        attempts = [0]

        def start_fresh():
            """Pick a replica and start the request on it, retrying past
            dead (or draining) picks until the resume budget runs out."""
            while True:
                r = self._pick_replica(exclude=frozenset(failed),
                                       route_key=route_key)
                try:
                    return r, start_on(r)
                except (ActorError, WorkerCrashedError, TaskError) as e:
                    if _is_death(e):
                        self._cb_fail(r._actor_id.binary(), death=True)
                    elif not _is_draining(e):
                        raise
                    failed.add(r._actor_id.binary())
                    if attempts[0] >= (
                            get_config().serve_stream_resume_attempts):
                        raise
                    attempts[0] += 1
                    self._refresh(force=True)

        replica, sid = start_fresh()

        def gen():
            nonlocal replica, sid
            start = 0
            while True:
                try:
                    out = rt.get(
                        replica.next_chunks.remote(sid, start),  # rtlint: disable=RT009 — chunk pulls ride the stream registered with meta at start_stream; each pull is rpc-timeout bounded
                        timeout=get_config().serve_rpc_timeout_s,
                    )
                except (ActorError, WorkerCrashedError, TaskError) as e:
                    if not _is_death(e):
                        raise
                    self._cb_fail(replica._actor_id.binary(), death=True)
                    failed.add(replica._actor_id.binary())
                    if attempts[0] >= (
                            get_config().serve_stream_resume_attempts):
                        raise
                    attempts[0] += 1
                    self._refresh(force=True)
                    # Restart the request; next_chunks(sid, start) below
                    # skips the chunks the client already consumed.
                    replica, sid = start_fresh()
                    journal.emit(
                        "serve.stream_resume", app=self.app_name,
                        rid=meta["rid"], offset=start,
                        attempt=attempts[0],
                        replica=replica._actor_id.hex(),
                    )
                    continue
                for c in out["chunks"]:
                    yield c
                start += len(out["chunks"])
                if out["error"]:
                    raise RuntimeError(
                        f"stream failed in replica: {out['error']}"
                    )
                if out["done"]:
                    self._cb_ok(replica._actor_id.binary())
                    return

        return StreamingResponse(gen(), rid=meta["rid"])

    def __reduce__(self):
        # Router state (locks, in-flight counts) is process-local: a handle
        # shipped to another process (deployment composition) starts fresh.
        return (
            DeploymentHandle,
            (self.app_name, self.method, self.multiplexed_model_id,
             self._stream, self.max_retries, self.tenant, self.deadline_s),
        )

    def __call__(self, *args, **kwargs):
        raise TypeError("use handle.remote(...) for deployment calls")
