"""HTTP proxy: the ingress data plane.

Analog of the reference's ProxyActor/HTTPProxy (serve/_private/proxy.py:1115
/ :759, uvicorn+starlette) built on aiohttp: JSON requests POSTed to
/{app_name} are routed through a DeploymentHandle (power-of-two balancing)
and the JSON response returned.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import ray_tpu as rt


@rt.remote
class ProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        import asyncio

        from aiohttp import web

        from ray_tpu.serve.handle import DeploymentHandle

        self.host = host
        self.port = port
        self._handles: Dict[str, DeploymentHandle] = {}
        self._ready = threading.Event()

        async def handle_request(request: web.Request):
            app_name = request.match_info["app"]
            handle = self._handles.get(app_name)
            if handle is None:
                handle = DeploymentHandle(app_name)
                self._handles[app_name] = handle
            try:
                payload = await request.json()
            except Exception:
                payload = None
            loop = asyncio.get_event_loop()

            def call():
                if isinstance(payload, dict):
                    return rt.get(handle.remote(**payload), timeout=60)
                if payload is None:
                    return rt.get(handle.remote(), timeout=60)
                return rt.get(handle.remote(payload), timeout=60)

            try:
                result = await loop.run_in_executor(None, call)
                return web.json_response({"result": result})
            except Exception as e:  # noqa: BLE001
                return web.json_response(
                    {"error": f"{type(e).__name__}: {e}"}, status=500
                )

        async def healthz(request):
            return web.json_response({"status": "ok"})

        def run_server():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            app = web.Application()
            app.router.add_get("/-/healthz", healthz)
            app.router.add_post("/{app}", handle_request)
            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, self.host, self.port)
            loop.run_until_complete(site.start())
            self._ready.set()
            loop.run_forever()

        self._thread = threading.Thread(target=run_server, daemon=True)
        self._thread.start()
        self._ready.wait(timeout=10)

    def address(self):
        return f"http://{self.host}:{self.port}"

    def ready(self) -> bool:
        return self._ready.is_set()
