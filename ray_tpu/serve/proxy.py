"""HTTP proxy: the ingress data plane.

Analog of the reference's ProxyActor/HTTPProxy (serve/_private/proxy.py:1115
/ :759, uvicorn+starlette) built on aiohttp. JSON requests POSTed to
/{app_name} route through a DeploymentHandle; the response resolves
WITHOUT holding a thread per in-flight request (the round-1 weakness): the
actor-call completion future is awaited on the event loop. Streaming
deployments (`?stream=1` or `Accept: text/event-stream`) are served as
Server-Sent Events; the `serve_multiplexed_model_id` header tags requests
for model multiplexing (reference: serve/_private/proxy.py header of the
same name).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import ray_tpu as rt
from ray_tpu._private.config import get_config


@rt.remote
class ProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        import asyncio
        import json

        from aiohttp import web

        from ray_tpu._private.worker import _IN_STORE
        from ray_tpu.serve.handle import DeploymentHandle

        self.host = host
        self.port = port
        self._handles: Dict[str, DeploymentHandle] = {}
        self._binary_port: Optional[int] = None
        self._ready = threading.Event()

        def get_handle(app_name: str) -> DeploymentHandle:
            handle = self._handles.get(app_name)
            if handle is None:
                handle = DeploymentHandle(app_name)
                self._handles[app_name] = handle
            return handle

        async def resolve(loop, response):
            """Await a DeploymentResponse without burning a thread: the
            completion future resolves on the client loop; only store-kind
            results (rare for JSON responses) fall back to an executor."""
            ref = response.ref
            if ref._future is not None:
                value = await asyncio.wrap_future(ref._future)
                if value is not _IN_STORE:
                    return value
            return await loop.run_in_executor(
                None,
                lambda: rt.get(ref, timeout=get_config().serve_rpc_timeout_s),
            )

        async def handle_request(request: web.Request):
            app_name = request.match_info["app"]
            model_id = request.headers.get("serve_multiplexed_model_id", "")
            tenant = request.headers.get("serve_tenant", "")
            want_stream = (
                request.query.get("stream") == "1"
                or "text/event-stream" in request.headers.get("Accept", "")
            )
            try:
                payload = await request.json()
            except Exception:
                payload = None
            loop = asyncio.get_event_loop()
            handle = get_handle(app_name)
            if model_id:
                handle = handle.options(multiplexed_model_id=model_id)
            if tenant:
                # Observatory attribution: per-tenant tokens/SLO burn.
                handle = handle.options(tenant=tenant)

            def dispatch(h):
                if isinstance(payload, dict):
                    return h.remote(**payload)
                if payload is None:
                    return h.remote()
                return h.remote(payload)

            try:
                if want_stream:
                    sse = web.StreamResponse(
                        headers={
                            "Content-Type": "text/event-stream",
                            "Cache-Control": "no-cache",
                        }
                    )
                    await sse.prepare(request)
                    # After prepare() no second response can be returned:
                    # mid-stream failures become a terminal SSE error event.
                    try:
                        chunk_iter = await loop.run_in_executor(
                            None, dispatch, handle.options(stream=True)
                        )

                        def pull(it):
                            try:
                                return next(it), False
                            except StopIteration:
                                return None, True

                        it = iter(chunk_iter)
                        while True:
                            chunk, done = await loop.run_in_executor(
                                None, pull, it
                            )
                            if done:
                                break
                            await sse.write(
                                f"data: {json.dumps(chunk)}\n\n".encode()
                            )
                    except Exception as e:  # noqa: BLE001
                        await sse.write(
                            b"event: error\ndata: "
                            + json.dumps(
                                f"{type(e).__name__}: {e}"
                            ).encode()
                            + b"\n\n"
                        )
                    await sse.write_eof()
                    return sse
                # Dispatch is quick (replica pick + actor-call submit);
                # the potentially-long wait is the await below, which
                # holds no thread.
                response = await loop.run_in_executor(None, dispatch, handle)
                result = await resolve(loop, response)
                return web.json_response({"result": result})
            except Exception as e:  # noqa: BLE001
                return web.json_response(
                    {"error": f"{type(e).__name__}: {e}"}, status=500
                )

        async def healthz(request):
            return web.json_response({"status": "ok"})

        async def h_serve_call(d, conn):
            """Binary-framed ingress (the reference gRPC proxy's role,
            serve/_private/grpc_util.py): length-prefixed msgpack frames —
            the same wire format the C++ client speaks — carrying
            {app, method?, args?, kwargs?, multiplexed_model_id?,
            tenant?}. The result must be msgpack-encodable."""
            app_name = d["app"]
            handle = get_handle(app_name)
            if d.get("method") and d["method"] != "__call__":
                handle = handle.options(method_name=d["method"])
            if d.get("multiplexed_model_id"):
                handle = handle.options(
                    multiplexed_model_id=d["multiplexed_model_id"]
                )
            if d.get("tenant"):
                handle = handle.options(tenant=d["tenant"])
            args = d.get("args") or []
            kwargs = d.get("kwargs") or {}
            loop = asyncio.get_event_loop()
            response = await loop.run_in_executor(
                None, lambda: handle.remote(*args, **kwargs)
            )
            result = await resolve(loop, response)
            return {"result": result}

        def run_server():
            from ray_tpu._private.protocol import RpcServer

            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            app = web.Application()
            app.router.add_get("/-/healthz", healthz)
            app.router.add_post("/{app}", handle_request)
            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, self.host, self.port)
            loop.run_until_complete(site.start())
            # port=0 -> the OS picked one; report the real port so many
            # proxies can coexist on one test host.
            self.port = site._server.sockets[0].getsockname()[1]
            brpc = RpcServer(self.host, 0)
            brpc.register("serve_call", h_serve_call)
            loop.run_until_complete(brpc.start())
            self._binary_port = brpc.port
            self._ready.set()
            loop.run_forever()

        self._thread = threading.Thread(target=run_server, daemon=True)
        self._thread.start()
        self._ready.wait(timeout=get_config().serve_ready_timeout_s)

    def address(self):
        return f"http://{self.host}:{self.port}"

    def binary_address(self):
        """(host, port) of the framed-msgpack ingress."""
        return (self.host, self._binary_port)

    def ready(self) -> bool:
        return self._ready.is_set()
