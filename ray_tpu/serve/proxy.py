"""HTTP proxy: the ingress data plane.

Analog of the reference's ProxyActor/HTTPProxy (serve/_private/proxy.py:1115
/ :759, uvicorn+starlette) built on aiohttp. JSON requests POSTed to
/{app_name} route through a DeploymentHandle; the response resolves
WITHOUT holding a thread per in-flight request (the round-1 weakness): the
actor-call completion future is awaited on the event loop. Streaming
deployments (`?stream=1` or `Accept: text/event-stream`) are served as
Server-Sent Events; the `serve_multiplexed_model_id` header tags requests
for model multiplexing (reference: serve/_private/proxy.py header of the
same name).

Survival plane (PR 8): typed serve failures map to distinct HTTP codes —
429 + Retry-After for admission shed (ServeOverloadedError: retryable,
the request was never executed), 503 + Retry-After for replica death
mid-request (retryable: redispatch exhausted its attempts), 504 for
deadline expiry (NOT retryable: the budget is gone) — instead of a
generic 500, so clients and load balancers can tell "back off" from
"try another instance" from "give up". Every response increments
`serve_http_responses_total{app,code}` and lands one access-log line
tagged with the outcome kind.

The `serve_deadline_ms` request header sets the request's end-to-end
budget: the proxy converts it to an absolute deadline that propagates
handle -> replica -> engine.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Dict, Optional

import ray_tpu as rt
from ray_tpu._private.config import get_config
from ray_tpu.exceptions import (
    ActorError,
    GetTimeoutError,
    PromptTooLongError,
    RequestCancelledError,
    ServeOverloadedError,
    TaskError,
    WorkerCrashedError,
)

logger = logging.getLogger("ray_tpu.serve.proxy")

_metrics_lock = threading.Lock()
_metrics: Optional[Dict] = None


def _proxy_metrics() -> Dict:
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util import metrics as _mx

            _metrics = {
                "responses": _mx.get_or_create(
                    _mx.Counter, "serve_http_responses_total",
                    "HTTP responses by status code (200 ok, 413 prompt "
                    "too long, 429 shed, 503 replica death, 504 deadline, "
                    "500 other), per app",
                    tag_keys=("app", "code"),
                ),
            }
        return _metrics


def _classify_error(e: BaseException):
    """(status, retry_after_s | None, kind) for a failed serve request.

    Typed serve errors usually arrive WRAPPED in TaskError (they were
    raised inside the replica); classification looks through to the
    cause, falling back to cause_cls_name when the cause did not
    unpickle."""
    cause = getattr(e, "cause", None) if isinstance(e, TaskError) else e
    cause_name = (getattr(e, "cause_cls_name", "")
                  if isinstance(e, TaskError) else type(e).__name__)
    if isinstance(cause, ServeOverloadedError) or (
            cause_name == "ServeOverloadedError"):
        retry = getattr(cause, "retry_after_s", 1.0) or 1.0
        return 429, retry, "shed"
    if isinstance(cause, RequestCancelledError) or (
            cause_name == "RequestCancelledError"):
        return 504, None, "deadline"
    if isinstance(cause, PromptTooLongError) or (
            cause_name == "PromptTooLongError"):
        # 413: structural rejection — retrying the same prompt against
        # the same app cannot succeed, so no Retry-After.
        return 413, None, "prompt_too_long"
    if isinstance(e, (ActorError, WorkerCrashedError)) or (
            cause_name in ("ActorDiedError", "ActorUnavailableError",
                           "WorkerCrashedError")):
        return 503, 1.0, "replica_death"
    if isinstance(e, GetTimeoutError):
        return 504, None, "timeout"
    return 500, None, "error"


def _count_response(app: str, code: int) -> None:
    try:
        _proxy_metrics()["responses"].inc(
            1, tags={"app": app, "code": str(code)}
        )
    except Exception:  # rtlint: disable=RT007 — metrics must never fail a response
        pass


@rt.remote
class ProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        import asyncio
        import json

        from aiohttp import web

        from ray_tpu._private.worker import _IN_STORE
        from ray_tpu.serve.handle import DeploymentHandle
        from ray_tpu.util import journal

        journal.set_process_label("proxy")
        self.host = host
        self.port = port
        self._handles: Dict[str, DeploymentHandle] = {}
        self._binary_port: Optional[int] = None
        self._ready = threading.Event()

        def get_handle(app_name: str) -> DeploymentHandle:
            handle = self._handles.get(app_name)
            if handle is None:
                handle = DeploymentHandle(app_name)
                self._handles[app_name] = handle
            return handle

        async def resolve(loop, response, deadline_ts: float = 0.0):
            """Await a DeploymentResponse without burning a thread: the
            completion future resolves on the client loop; only store-kind
            results (rare for JSON responses) fall back to an executor.
            A deadline bounds the await — past it the client gets 504
            instead of a result it no longer wants."""
            ref = response.ref
            if ref._future is not None:
                fut = asyncio.wrap_future(ref._future)
                if deadline_ts:
                    remaining = deadline_ts - time.time()
                    if remaining <= 0:
                        fut.cancel()
                        raise RequestCancelledError(
                            "deadline expired before result",
                            reason="deadline",
                        )
                    try:
                        value = await asyncio.wait_for(fut, timeout=remaining)
                    except asyncio.TimeoutError:
                        raise RequestCancelledError(
                            "deadline expired while awaiting result",
                            reason="deadline",
                        ) from None
                else:
                    value = await fut
                if value is not _IN_STORE:
                    return value
            return await loop.run_in_executor(
                None,
                lambda: rt.get(ref, timeout=get_config().serve_rpc_timeout_s),
            )

        async def handle_request(request: web.Request):
            app_name = request.match_info["app"]
            model_id = request.headers.get("serve_multiplexed_model_id", "")
            tenant = request.headers.get("serve_tenant", "")
            deadline_ms = request.headers.get("serve_deadline_ms", "")
            want_stream = (
                request.query.get("stream") == "1"
                or "text/event-stream" in request.headers.get("Accept", "")
            )
            try:
                payload = await request.json()
            except Exception:  # noqa: BLE001 — body may be empty/non-JSON
                logger.debug("request body for %s is not JSON; "
                             "forwarding an empty payload", app_name,
                             exc_info=True)
                payload = None
            loop = asyncio.get_event_loop()
            handle = get_handle(app_name)
            if model_id:
                handle = handle.options(multiplexed_model_id=model_id)
            if tenant:
                # Observatory attribution: per-tenant tokens/SLO burn.
                handle = handle.options(tenant=tenant)
            deadline_ts = 0.0
            if deadline_ms:
                try:
                    budget_s = max(0.001, float(deadline_ms) / 1000.0)
                    handle = handle.options(deadline_s=budget_s)
                    deadline_ts = time.time() + budget_s
                except ValueError:
                    pass  # malformed header: no deadline

            def dispatch(h):  # rtlint: disable=RT009 — the deadline rides the handle itself via .options(deadline_s=budget_s) above
                if isinstance(payload, dict):
                    return h.remote(**payload)
                if payload is None:
                    return h.remote()
                return h.remote(payload)

            try:
                if want_stream:
                    sse = web.StreamResponse(
                        headers={
                            "Content-Type": "text/event-stream",
                            "Cache-Control": "no-cache",
                        }
                    )
                    await sse.prepare(request)
                    # After prepare() no second response can be returned:
                    # mid-stream failures become a terminal SSE error event.
                    try:
                        chunk_iter = await loop.run_in_executor(
                            None, dispatch, handle.options(stream=True)
                        )

                        def pull(it):
                            try:
                                return next(it), False
                            except StopIteration:
                                return None, True

                        it = iter(chunk_iter)
                        while True:
                            chunk, done = await loop.run_in_executor(
                                None, pull, it
                            )
                            if done:
                                break
                            await sse.write(
                                f"data: {json.dumps(chunk)}\n\n".encode()
                            )
                    except Exception as e:  # noqa: BLE001
                        status, _retry, kind = _classify_error(e)
                        _count_response(app_name, status)
                        logger.info(
                            "POST /%s -> stream error %d (%s): %s",
                            app_name, status, kind, e,
                        )
                        await sse.write(
                            b"event: error\ndata: "
                            + json.dumps(
                                {
                                    "error": f"{type(e).__name__}: {e}",
                                    "status": status,
                                    "kind": kind,
                                }
                            ).encode()
                            + b"\n\n"
                        )
                    else:
                        _count_response(app_name, 200)
                    await sse.write_eof()
                    return sse
                # Dispatch is quick (replica pick + actor-call submit);
                # the potentially-long wait is the await below, which
                # holds no thread.
                response = await loop.run_in_executor(None, dispatch, handle)
                result = await resolve(loop, response, deadline_ts)
                _count_response(app_name, 200)
                return web.json_response({"result": result})
            except Exception as e:  # noqa: BLE001
                status, retry_after, kind = _classify_error(e)
                headers = {}
                if retry_after is not None:
                    headers["Retry-After"] = str(
                        max(1, math.ceil(retry_after))
                    )
                _count_response(app_name, status)
                # Access log distinguishes shed (429: request never ran,
                # back off) from replica_death (503: retry elsewhere)
                # from deadline (504: budget gone, do not retry).
                logger.info(
                    "POST /%s -> %d (%s): %s", app_name, status, kind, e
                )
                return web.json_response(
                    {"error": f"{type(e).__name__}: {e}", "kind": kind},
                    status=status,
                    headers=headers,
                )

        async def healthz(request):
            return web.json_response({"status": "ok"})

        async def h_serve_call(d, conn):
            """Binary-framed ingress (the reference gRPC proxy's role,
            serve/_private/grpc_util.py): length-prefixed msgpack frames —
            the same wire format the C++ client speaks — carrying
            {app, method?, args?, kwargs?, multiplexed_model_id?,
            tenant?}. The result must be msgpack-encodable."""
            app_name = d["app"]
            handle = get_handle(app_name)
            if d.get("method") and d["method"] != "__call__":
                handle = handle.options(method_name=d["method"])
            if d.get("multiplexed_model_id"):
                handle = handle.options(
                    multiplexed_model_id=d["multiplexed_model_id"]
                )
            if d.get("tenant"):
                handle = handle.options(tenant=d["tenant"])
            deadline_ts = 0.0
            if d.get("deadline_ms"):
                budget_s = max(0.001, float(d["deadline_ms"]) / 1000.0)
                handle = handle.options(deadline_s=budget_s)
                deadline_ts = time.time() + budget_s
            args = d.get("args") or []
            kwargs = d.get("kwargs") or {}
            loop = asyncio.get_event_loop()
            try:
                response = await loop.run_in_executor(
                    None, lambda: handle.remote(*args, **kwargs)  # rtlint: disable=RT009 — deadline rides the handle via .options(deadline_s=...) above
                )
                result = await resolve(loop, response, deadline_ts)
            except Exception as e:  # noqa: BLE001
                status, retry_after, kind = _classify_error(e)
                _count_response(app_name, status)
                logger.info(
                    "serve_call %s -> %d (%s): %s", app_name, status, kind, e
                )
                return {
                    "error": f"{type(e).__name__}: {e}",
                    "status": status,
                    "kind": kind,
                    "retry_after_s": retry_after,
                }
            _count_response(app_name, 200)
            return {"result": result}

        def run_server():
            from ray_tpu._private.protocol import RpcServer

            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            app = web.Application()
            app.router.add_get("/-/healthz", healthz)
            app.router.add_post("/{app}", handle_request)
            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, self.host, self.port)
            loop.run_until_complete(site.start())
            # port=0 -> the OS picked one; report the real port so many
            # proxies can coexist on one test host.
            self.port = site._server.sockets[0].getsockname()[1]
            brpc = RpcServer(self.host, 0)
            brpc.register("serve_call", h_serve_call)
            loop.run_until_complete(brpc.start())
            self._binary_port = brpc.port
            self._ready.set()
            loop.run_forever()

        self._thread = threading.Thread(target=run_server, daemon=True)
        self._thread.start()
        self._ready.wait(timeout=get_config().serve_ready_timeout_s)

    def address(self):
        return f"http://{self.host}:{self.port}"

    def binary_address(self):
        """(host, port) of the framed-msgpack ingress."""
        return (self.host, self._binary_port)

    def ready(self) -> bool:
        return self._ready.is_set()
