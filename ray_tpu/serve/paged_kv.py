"""Paged KV cache: the engine's memory plane as a first-class subsystem.

The slotted cache (llm.init_slotted_cache) pins one whole `[max_len]`
row per request: a 30-token chat holds the same HBM as a 4k-token
document, concurrency is fixed at `num_slots` no matter the workload,
and two requests sharing a prompt prefix each recompute and store it.
This module replaces the row with PAGES — the vLLM PagedAttention idea
(arXiv:2309.06180), built for the engine's TPU discipline of static
shapes and zero steady-state host traffic:

  * One page pool `[layers, pages, page_size, kv_heads, head_dim]` and
    a per-slot block table `[slots, pages_per_slot]` resident on
    device. Decode gathers K/V *through* the block table (one gather
    per layer inside the jitted step); prefill scatters rows into the
    pages the table names. Program shapes depend only on the pool and
    table geometry, so compilation stays bounded exactly as before.
  * A host-side free-list allocator with REFCOUNTED pages. Admission
    reserves every page a request can ever touch up front
    (ceil((prompt + max_new + 1) / page_size)); decode then never
    allocates, so the block table uploads only at admission/eviction —
    the same single-upload discipline as the sampling params, and the
    steady-state decode loop keeps doing zero host->device transfers.
  * A PREFIX CACHE: a token-hash trie over full-page runs (chain hash
    per page, so a lookup is O(pages) dict probes). A request whose
    prompt prefix is resident maps the shared pages into its block
    table (refcount bump, no copy) and skips those prefill chunks
    entirely. Pages are copy-on-write: the one case where a new
    request must write into a shared page (its first recomputed token
    lands mid-page) forks that page first. Cache entries hold their own
    page references, so a donor request finishing — or being evicted —
    never invalidates the sharers; under pool pressure the cache LRU-
    releases entries back to the free list.

Page 0 is reserved as the NULL/scratch page: block-table entries
default to it, inactive-slot decode writes park in it, and prefill
padding rows drop into it — it is never gathered unmasked, so its
contents are never observable.

Bit-exactness with the slotted path: when `max_len % page_size == 0`
the gathered attention width equals `max_len`, gathered row i of a slot
is absolute position i (pages are table-ordered), and masked lanes
underflow to exact 0.0 in the f32 softmax — the decode outputs are
bit-identical, which tests/test_paged_kv.py pins against
`RT_SERVE_KV=slotted`.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.transformer import (
    TransformerConfig,
    _embed_tokens,
    project_logits,
)
from ray_tpu.ops import rmsnorm, rope_frequencies

# The reserved NULL/scratch page (see module docstring).
NULL_PAGE = 0


class OutOfPages(RuntimeError):
    """The pool cannot cover an allocation. Admission-time only: the
    engine requeues the request at the front of its tenant queue and
    retries as decoding requests finish and release pages."""

    def __init__(self, needed: int, free: int, total: int):
        super().__init__(
            f"page pool exhausted: need {needed} pages, {free} free of "
            f"{total} usable"
        )
        self.needed = needed
        self.free = free
        self.total = total


class PagePool:
    """Host-side free-list allocator over the device page pool.

    Pure bookkeeping — it never touches device memory. Refcounts make
    prefix sharing safe: a page is returned to the free list only when
    its last holder (request block table or prefix-cache entry)
    releases it. Single-threaded by design: only the engine loop thread
    allocates/releases (admission and eviction both happen there)."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("PagePool needs >= 2 pages (page 0 is reserved)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # LIFO free list: recently-freed pages are re-used first (their
        # rows are about to be overwritten anyway).
        self._free: List[int] = list(range(1, self.num_pages))
        self._refs = np.zeros(self.num_pages, dtype=np.int32)

    @property
    def usable(self) -> int:
        return self.num_pages - 1  # page 0 reserved

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.usable - len(self._free)

    def alloc(self, n: int) -> List[int]:
        """Take `n` pages off the free list at refcount 1. All-or-
        nothing: raises OutOfPages without allocating anything when the
        list is short (partial grants would leak on the error path)."""
        if n < 0:
            raise ValueError("alloc of negative page count")
        if n > len(self._free):
            raise OutOfPages(n, len(self._free), self.usable)
        pages = [self._free.pop() for _ in range(n)]
        self._refs[pages] = 1
        return pages

    def ref(self, pages: Sequence[int]) -> None:
        """Add one reference to each page (prefix sharing / cache insert)."""
        for p in pages:
            if self._refs[p] <= 0:
                raise ValueError(f"ref of unallocated page {p}")
            self._refs[p] += 1

    def release(self, pages: Sequence[int]) -> None:
        """Drop one reference from each page; pages reaching zero return
        to the free list."""
        for p in pages:
            r = int(self._refs[p]) - 1
            if r < 0:
                raise ValueError(f"release of unallocated page {p}")
            self._refs[p] = r
            if r == 0:
                self._free.append(p)

    def refcount(self, page: int) -> int:
        return int(self._refs[page])

    def reset(self) -> None:
        """Forget everything (engine failure recovery: the device cache
        was rebuilt, so every outstanding reference is void)."""
        self._free = list(range(1, self.num_pages))
        self._refs[:] = 0


class PrefixCache:
    """Token-hash trie over full-page runs, flattened to one dict.

    Each cached page is keyed by the CHAIN hash of the prompt prefix it
    completes (h_i = blake2b(h_{i-1} || tokens of page i)), so a chain
    key identifies the entire token prefix, not just one page's tokens
    — matching is `for each key: dict probe`, longest resident prefix
    wins, no tree pointers needed. The cache holds its OWN reference on
    every resident page: donors finishing (or dying) cannot invalidate
    sharers, and `evict_pages` under pool pressure releases LRU entries
    deepest-first (an OrderedDict move-to-end on match keeps recency;
    entries of one insertion land in chain order, so popping from the
    front releases stale roots last — a child page is never left
    resident without its parent chain being droppable first is NOT
    required for correctness: a match simply stops at the first missing
    link)."""

    def __init__(self, pool: PagePool):
        self._pool = pool
        # chain-hash key -> (page, depth). Ordered: LRU at the front.
        self._entries: "OrderedDict[str, Tuple[int, int]]" = OrderedDict()

    @property
    def pages_held(self) -> int:
        return len(self._entries)

    def match(self, keys: Sequence[str]) -> List[int]:
        """Longest resident prefix of `keys`, as pages. The caller
        receives ONE reference per returned page (release when the
        request's block table drops them)."""
        pages: List[int] = []
        for k in keys:
            hit = self._entries.get(k)
            if hit is None:
                break
            self._entries.move_to_end(k)
            pages.append(hit[0])
        if pages:
            self._pool.ref(pages)
        return pages

    def insert(self, keys: Sequence[str], pages: Sequence[int]) -> int:
        """Publish a prompt's full pages under their chain keys (called
        at prefill completion, so concurrent requests share as early as
        possible). The cache takes its own reference on each newly
        inserted page; keys already resident just refresh recency.
        Returns the number of pages newly inserted."""
        added = 0
        for depth, (k, p) in enumerate(zip(keys, pages)):
            if k in self._entries:
                self._entries.move_to_end(k)
                continue
            self._pool.ref([p])
            self._entries[k] = (int(p), depth)
            added += 1
        return added

    def evict_pages(self, n: int) -> int:
        """Release up to `n` LRU entries back toward the pool (allocation
        pressure). Returns how many entries were dropped — the caller
        retries its alloc; freed-page count can be lower when a sharer
        still holds a reference."""
        dropped = 0
        while dropped < n and self._entries:
            _, (page, _) = self._entries.popitem(last=False)
            self._pool.release([page])
            dropped += 1
        return dropped

    def flush(self) -> int:
        """Drop every entry (chaos hook / tests). Returns entries dropped."""
        return self.evict_pages(len(self._entries))

    def reset(self) -> None:
        """Forget entries WITHOUT releasing (engine failure recovery:
        the pool was reset, the references no longer exist)."""
        self._entries.clear()

    def roots(self, limit: int = 64) -> List[str]:
        """Most-recently-used depth-0 chain keys — the replica's
        advertised prefix set for affinity routing. Depth 0 only: a
        router match on the FIRST page is what predicts the rest of the
        chain being resident, and it keeps the advertisement bounded."""
        out = [k for k, (_, d) in self._entries.items() if d == 0]
        return out[-limit:]


def page_hashes(tokens, page_size: int) -> List[str]:
    """Chain hashes of every FULL page of `tokens` (partial tail pages
    are never cached — their rows would change as the request decodes).
    Key i commits to tokens[0 : (i+1)*page_size]."""
    arr = np.asarray(tokens, dtype=np.int32).reshape(-1)
    out: List[str] = []
    parent = b""
    for i in range(len(arr) // page_size):
        h = hashlib.blake2b(
            parent + arr[i * page_size:(i + 1) * page_size].tobytes(),
            digest_size=16,
        )
        parent = h.digest()
        out.append(h.hexdigest())
    return out


def prefix_route_key(tokens, page_size: int) -> Optional[str]:
    """The depth-0 chain key of a prompt (None when the prompt does not
    fill one page) — what the handle matches against replicas'
    advertised `roots` for prefix-affinity routing."""
    arr = np.asarray(tokens, dtype=np.int32).reshape(-1)
    if page_size < 1 or len(arr) < page_size:
        return None
    return hashlib.blake2b(
        arr[:page_size].tobytes(), digest_size=16
    ).hexdigest()


def init_paged_cache(cfg: TransformerConfig, slots: int, num_pages: int,
                     page_size: int, pages_per_slot: int,
                     mesh=None) -> Dict:
    """Device state of the paged cache: the page pool, per-slot lengths,
    and the block table (all entries NULL_PAGE). Sharding matches the
    slotted cache: KV heads over "tp", everything else replicated."""
    shape = (cfg.n_layers, num_pages, page_size, cfg.n_kv_heads,
             cfg.head_dim)
    cache = {
        "k": jnp.zeros(shape, dtype=cfg.dtype),
        "v": jnp.zeros(shape, dtype=cfg.dtype),
        "lengths": jnp.zeros((slots,), dtype=jnp.int32),
        "block_tables": jnp.zeros((slots, pages_per_slot),
                                  dtype=jnp.int32),
    }
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        kv_sharding = NamedSharding(mesh, P(None, None, None, "tp", None))
        rep = NamedSharding(mesh, P())
        cache = {
            "k": jax.device_put(cache["k"], kv_sharding),
            "v": jax.device_put(cache["v"], kv_sharding),
            "lengths": jax.device_put(cache["lengths"], rep),
            "block_tables": jax.device_put(cache["block_tables"], rep),
        }
    return cache


def decode_paged(params, tokens, k_pages, v_pages, lengths, active,
                 block_tables, temps, top_ks, top_ps, key,
                 cfg: TransformerConfig, max_len: int):
    """One decode step for every slot, K/V gathered through the block
    table — the paged twin of llm._decode_slots (same contract: same
    inputs plus the table, same outputs).

    Each active slot writes its new K/V row into page
    `block_tables[slot, lengths[slot] // page_size]` at row
    `lengths[slot] % page_size`; inactive slots park the write in the
    NULL page. Attention gathers the slot's whole table (width =
    pages_per_slot * page_size) and masks by length, exactly like the
    slotted step masks its `max_len` row."""
    from ray_tpu.serve.llm import (  # local import: llm imports us too
        _grouped_attention, _layer_body, _pick_tokens,
    )

    s_ = tokens.shape[0]
    ps = k_pages.shape[2]
    mp = block_tables.shape[1]
    width = mp * ps
    kvh, hd = k_pages.shape[3], k_pages.shape[4]
    x = _embed_tokens(params, tokens[:, None], cfg)  # [S, 1, d]
    cos, sin = rope_frequencies(cfg.head_dim, max_len, cfg.rope_theta)
    positions = lengths[:, None]
    pos_w = jnp.where(active, jnp.minimum(lengths, max_len - 1), 0)
    page_of = jnp.minimum(pos_w // ps, mp - 1)
    rows_w = pos_w % ps
    slot_idx = jnp.arange(s_)
    pages_w = jnp.where(active, block_tables[slot_idx, page_of], NULL_PAGE)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (s_, 1, width), 2)
    valid = k_pos <= positions[:, :, None]

    def write_kv(kc, vc, k, v):
        # kc [pages, ps, kvh, hd]: scatter one row per slot, then gather
        # each slot's pages back as a contiguous [width] view. Inactive
        # slots all target (NULL_PAGE, 0); whichever lands is never
        # unmasked.
        kc = kc.at[pages_w, rows_w].set(k[:, 0].astype(kc.dtype))
        vc = vc.at[pages_w, rows_w].set(v[:, 0].astype(vc.dtype))
        k_att = kc[block_tables].reshape(s_, width, kvh, hd)
        v_att = vc[block_tables].reshape(s_, width, kvh, hd)
        return kc, vc, k_att, v_att

    def layer(carry, inputs):
        x = carry
        lp, k_cache_l, v_cache_l = inputs
        x, k_cache_l, v_cache_l = _layer_body(
            x, lp, k_cache_l, v_cache_l, cfg, cos, sin, positions,
            write_kv, valid,
        )
        return x, (k_cache_l, v_cache_l)

    x, (k_new, v_new) = jax.lax.scan(
        layer, x, (params["layers"], k_pages, v_pages)
    )
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = project_logits(x[:, -1], params, cfg)
    new_lengths = jnp.where(active, lengths + 1, lengths)
    if temps is None:
        next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        next_tokens = _pick_tokens(logits, temps, top_ks, top_ps, key)
    return next_tokens, k_new, v_new, new_lengths


def prefill_chunk_paged(params, tokens, n_valid, slot, offset, k_pages,
                        v_pages, lengths, block_tables,
                        cfg: TransformerConfig, max_len: int):
    """Chunked prefill into pages — the paged twin of
    llm._prefill_chunk. Chunk rows scatter into the pages the slot's
    block-table row names (padding rows and anything past `max_len`
    drop into the NULL page, the paged equivalent of mode="drop");
    queries attend causally against the slot's gathered page run.

    Prefix-cache resumption needs nothing special here: the engine
    starts `offset` at the shared-prefix boundary and the gathered
    pages already hold the donor's K/V rows below it."""
    from ray_tpu.serve.llm import _layer_body  # local import (cycle)

    _, c = tokens.shape
    ps = k_pages.shape[2]
    mp = block_tables.shape[1]
    width = mp * ps
    kvh, hd = k_pages.shape[3], k_pages.shape[4]
    x = _embed_tokens(params, tokens, cfg)
    cos, sin = rope_frequencies(cfg.head_dim, max_len, cfg.rope_theta)
    positions = offset + jnp.arange(c, dtype=jnp.int32)[None, :]
    q_pos = positions[:, :, None]                               # [1, C, 1]
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (1, c, width), 2)
    valid = (k_pos <= q_pos) & (k_pos < offset + n_valid)
    bt_row = jax.lax.dynamic_slice_in_dim(block_tables, slot, 1, axis=0)[0]
    pos = offset + jnp.arange(c, dtype=jnp.int32)
    in_range = (pos < offset + n_valid) & (pos < max_len)
    page_of = jnp.minimum(pos // ps, mp - 1)
    pages_w = jnp.where(in_range, bt_row[page_of], NULL_PAGE)
    rows_w = pos % ps

    def write_kv(kc, vc, k, v):
        kc = kc.at[pages_w, rows_w].set(k[0].astype(kc.dtype))
        vc = vc.at[pages_w, rows_w].set(v[0].astype(vc.dtype))
        k_att = kc[bt_row].reshape(1, width, kvh, hd)
        v_att = vc[bt_row].reshape(1, width, kvh, hd)
        return kc, vc, k_att, v_att

    def layer(carry, inputs):
        x = carry
        lp, k_cache_l, v_cache_l = inputs
        x, k_cache_l, v_cache_l = _layer_body(
            x, lp, k_cache_l, v_cache_l, cfg, cos, sin, positions,
            write_kv, valid,
        )
        return x, (k_cache_l, v_cache_l)

    x, (k_new, v_new) = jax.lax.scan(
        layer, x, (params["layers"], k_pages, v_pages)
    )
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    last = jax.lax.dynamic_slice(x, (0, n_valid - 1, 0), (1, 1, x.shape[-1]))
    logits = project_logits(last[:, 0], params, cfg)
    new_lengths = lengths.at[slot].set(offset + n_valid)
    return logits, k_new, v_new, new_lengths


def cow_copy_page(k_pages, v_pages, src, dst):
    """Copy one page's rows across all layers (the copy-on-write fork).
    Jitted by the engine with donated buffers so it runs in place."""
    k_pages = k_pages.at[:, dst].set(k_pages[:, src])
    v_pages = v_pages.at[:, dst].set(v_pages[:, src])
    return k_pages, v_pages
