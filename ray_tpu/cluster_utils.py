"""In-process multi-node cluster harness for tests.

Analog of python/ray/cluster_utils.py:108 in the reference: `Cluster` boots
multiple raylets (each with its own object store, resources, and worker
pool) against one GCS, which is how nearly all "distributed" tests run on a
single machine. Raylet control loops share one event-loop thread here;
workers are real subprocesses.
"""

from __future__ import annotations

from typing import Dict, Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.ids import JobID
from ray_tpu._private.node import EventLoopThread, resolve_resources
from ray_tpu._private.raylet import Raylet


class Cluster:
    def __init__(self, gcs_persist_path: Optional[str] = None):
        self.io = EventLoopThread("rt-cluster")
        self.gcs_persist_path = gcs_persist_path
        self.gcs = GcsServer(persist_path=gcs_persist_path)
        self.gcs_port = self.io.run(self.gcs.start())
        self.raylets = []
        self.head = None
        self._client = None

    def kill_gcs(self, hard: bool = False):
        """Stop the GCS (fault injection). `hard=True` skips the final
        snapshot flush — recovery then depends entirely on WAL replay."""
        self.io.run(
            self.gcs.kill() if hard else self.gcs.stop(), timeout=5
        )

    def restart_gcs(self):
        """Start a fresh GCS on the same port; with a persist path it
        restores its durable tables and live raylets re-register within a
        heartbeat (GCS fault tolerance, redis_store_client.h:33 analog)."""
        self.gcs = GcsServer(
            port=self.gcs_port, persist_path=self.gcs_persist_path
        )
        assert self.io.run(self.gcs.start()) == self.gcs_port

    def add_node(
        self,
        num_cpus: float = 1,
        num_tpus: Optional[float] = None,
        resources: Optional[Dict[str, float]] = None,
        object_store_memory: Optional[int] = None,
        labels: Optional[Dict[str, str]] = None,
        env_overrides: Optional[Dict[str, str]] = None,
    ) -> Raylet:
        node_resources = dict(resources or {})
        node_resources["CPU"] = float(num_cpus)
        if num_tpus is not None:
            node_resources["TPU"] = float(num_tpus)
        raylet = Raylet(
            "127.0.0.1",
            self.gcs_port,
            node_resources,
            labels=labels,
            object_store_memory=object_store_memory,
            is_head=self.head is None,
        )
        if env_overrides:
            raylet.spawn_env_overrides = env_overrides
        self.io.run(raylet.start())
        self.raylets.append(raylet)
        if self.head is None:
            self.head = raylet
        return raylet

    def connect(self):
        """Attach the current process as a driver on the head node."""
        from ray_tpu._private.worker import CoreClient

        assert self.head is not None, "add_node() first"
        client = CoreClient(
            self.io.loop,
            ("127.0.0.1", self.gcs_port),
            ("127.0.0.1", self.head.port),
            self.head.store_name,
            self.head.node_id.binary(),
            JobID.from_random(),
            mode="driver",
        )
        client.connect()
        self._client = client
        worker_mod.set_client(client, "driver")
        return client

    def remove_node(self, raylet: Raylet):
        self.io.run(raylet.stop(), timeout=10)
        self.raylets.remove(raylet)
        self.io.run(self.gcs._mark_node_dead(raylet.node_id.binary(), "removed"))

    def kill_raylet(self, raylet: Raylet):
        """Node failure without graceful teardown: the raylet's services
        stop abruptly, its workers are SIGKILLed, and the GCS discovers the
        death through the dropped connection (chaos testing, reference:
        test_utils.py RayletKiller :1446)."""
        self.io.run(raylet.kill(), timeout=10)
        self.raylets.remove(raylet)
        self.io.run(self.gcs._mark_node_dead(raylet.node_id.binary(), "killed"))

    def shutdown(self):
        if self._client is not None:
            try:
                self._client.disconnect()
            except Exception:
                pass
            worker_mod.set_client(None, None)
        for raylet in list(self.raylets):
            try:
                self.io.run(raylet.stop(), timeout=10)
            except Exception:
                pass
        self.raylets.clear()
        try:
            self.io.run(self.gcs.stop(), timeout=5)
        except Exception:
            pass
        self.io.stop()
