"""Inference benchmark: KV-cache decode throughput on the flagship model.

Prints one JSON line per batch size: prefill tokens/s and steady-state
decode tokens/s/chip for the 0.8B Llama config (the serving-side
counterpart of bench.py's training MFU; decode is memory-bandwidth-bound,
so tokens/s scales with batch until HBM saturates). Writes
BENCH_INFER.json. CPU fallback uses the tiny config.

Run: python bench_infer.py
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import replace


def _ensure_backend():
    """A dead TPU tunnel hangs jax.devices() forever; probe it in a
    killable subprocess (bench.py's pattern) and fall back to CPU."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return
    from bench import _probe_tunnel

    if not _probe_tunnel():
        print("[bench_infer] TPU tunnel dead; falling back to CPU",
              file=sys.stderr, flush=True)
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""


_ensure_backend()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main():
    from ray_tpu.models import configs, init_params
    from ray_tpu.models.generate import decode_step, init_kv_cache, prefill

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        cfg = replace(configs.get_config("llama2-1b"), n_layers=12,
                      max_seq=1024, remat=False)
        batches = (1, 8, 32)
        prompt_len, decode_steps = 512, 64
    else:
        cfg = replace(configs.tiny, remat=False)
        batches = (4,)
        prompt_len, decode_steps = 32, 8

    params = init_params(jax.random.PRNGKey(0), cfg)
    results = []
    for batch in batches:
        max_len = prompt_len + decode_steps
        prompt = jax.random.randint(
            jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab_size
        )
        cache = init_kv_cache(cfg, batch, max_len)
        jprefill = jax.jit(lambda p, t, c: prefill(p, t, c, cfg))
        jdecode = jax.jit(lambda p, t, c: decode_step(p, t, c, cfg))

        # Warm both compilations.
        logits, cache1 = jprefill(params, prompt, cache)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        _, cache2 = jdecode(params, tok, cache1)
        jax.device_get(logits)

        t0 = time.perf_counter()
        logits, cache1 = jprefill(params, prompt, init_kv_cache(cfg, batch, max_len))
        jax.device_get(logits)
        prefill_s = time.perf_counter() - t0

        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        t0 = time.perf_counter()
        c = cache1
        for _ in range(decode_steps):
            logits, c = jdecode(params, tok, c)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        jax.device_get(tok)
        decode_s = time.perf_counter() - t0

        entry = {
            "metric": "llama2(0.8B) decode tokens/s/chip" if on_tpu
                      else "tiny decode tokens/s (cpu fallback)",
            "batch": batch,
            "prefill_tokens_per_s": round(batch * prompt_len / prefill_s, 1),
            "decode_tokens_per_s": round(batch * decode_steps / decode_s, 1),
            "ms_per_decode_step": round(decode_s / decode_steps * 1e3, 2),
        }
        print(json.dumps(entry), flush=True)
        results.append(entry)

    # Continuous batching at mixed arrivals vs static batch=1 (the
    # serving north-star, BASELINE.json configs[4]): requests join a
    # running decode loop at step boundaries instead of waiting for the
    # current batch to finish.
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    n_req = 8 if on_tpu else 6
    n_tok = 32 if on_tpu else 8
    cb_prompt_len = min(prompt_len, 64)
    rng = jax.random.PRNGKey(7)
    prompts = [
        list(map(int, jax.device_get(jax.random.randint(
            jax.random.fold_in(rng, i), (cb_prompt_len,), 0, cfg.vocab_size
        ))))
        for i in range(n_req)
    ]
    from ray_tpu.models.generate import generate

    # Warm the static path's compilation before timing it (the engine's
    # warmup request below plays the same role for the continuous path).
    jax.device_get(generate(
        params, jnp.asarray([prompts[0]], dtype=jnp.int32), cfg,
        max_new_tokens=n_tok,
    ))
    t0 = time.perf_counter()
    for p in prompts:
        jax.device_get(generate(
            params, jnp.asarray([p], dtype=jnp.int32), cfg,
            max_new_tokens=n_tok,
        ))
    static_s = time.perf_counter() - t0

    eng = ContinuousBatchingEngine(
        params, cfg, num_slots=4, max_len=cb_prompt_len + n_tok + 1,
        prefill_chunk=cb_prompt_len,
    )
    try:
        eng.submit(prompts[0], max_new_tokens=n_tok).result(timeout=600)
        t0 = time.perf_counter()
        handles = [eng.submit(p, max_new_tokens=n_tok) for p in prompts]
        for h in handles:
            h.result(timeout=600)
        cont_s = time.perf_counter() - t0
    finally:
        eng.shutdown()
    entry = {
        "metric": "continuous batching tokens/s" + (
            "/chip" if on_tpu else " (cpu fallback)"
        ),
        "requests": n_req,
        "tokens_per_request": n_tok,
        "static_batch1_tokens_per_s": round(n_req * n_tok / static_s, 1),
        "continuous_tokens_per_s": round(n_req * n_tok / cont_s, 1),
        "speedup_vs_static": round(static_s / cont_s, 2),
    }
    print(json.dumps(entry), flush=True)
    results.append(entry)

    if on_tpu:
        with open("BENCH_INFER.json", "w") as f:
            json.dump(results, f, indent=1)
    else:
        # CPU fallback is a smoke run: never overwrite the committed
        # TPU artifact with fallback numbers.
        print("[bench_infer] cpu fallback: BENCH_INFER.json left as-is",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
